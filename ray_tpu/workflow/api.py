"""Workflow execution + durable storage.

Reference: ``python/ray/workflow/workflow_executor.py:32`` (executor
driving a workflow state machine), ``workflow_storage.py`` (step-result
checkpoints), ``api.py`` (run/resume/get_status surface).  Storage is a
directory tree::

    <storage>/<workflow_id>/dag.pkl          the bound DAG (cloudpickle)
    <storage>/<workflow_id>/input.pkl        execute() input
    <storage>/<workflow_id>/steps/<uuid>.pkl one checkpoint per DAG node
    <storage>/<workflow_id>/status           RUNNING|SUCCESSFUL|FAILED

Each step runs as a normal task through the DAG node; its materialized
result checkpoints BEFORE the next step starts, so resume() skips every
completed step (the reference's exactly-once-per-step contract).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional

from ray_tpu._private import serialization
from ray_tpu.dag.node import ClassNode, DAGNode, InputNode

_state: Dict[str, Any] = {"dir": None}
_lock = threading.Lock()


def init(storage: Optional[str] = None):
    """Set the durable storage root (reference: workflow.init)."""
    _state["dir"] = storage or os.path.join(
        os.path.expanduser("~"), ".ray_tpu_workflows")
    os.makedirs(_state["dir"], exist_ok=True)


def _root() -> str:
    if _state["dir"] is None:
        init()
    return _state["dir"]


def _wf_dir(workflow_id: str) -> str:
    return os.path.join(_root(), workflow_id)


def _write(path: str, obj):
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(serialization.dumps_inline(obj))
    os.replace(tmp, path)  # atomic: a crash never leaves a torn checkpoint


def _read(path: str):
    with open(path, "rb") as f:
        return serialization.loads_inline(f.read())


def _set_status(workflow_id: str, status: str):
    with open(os.path.join(_wf_dir(workflow_id), "status"), "w") as f:
        f.write(status)


def get_status(workflow_id: str) -> str:
    """RUNNING | SUCCESSFUL | FAILED | RESUMABLE | NOT_FOUND."""
    d = _wf_dir(workflow_id)
    if not os.path.isdir(d):
        return "NOT_FOUND"
    try:
        with open(os.path.join(d, "status")) as f:
            s = f.read().strip()
    except OSError:
        return "NOT_FOUND"
    if s == "RUNNING":
        # A RUNNING marker with no live executor means a crashed run —
        # surfaced as RESUMABLE (reference: workflow_access.py resumable
        # detection; our executor is in-process so any RUNNING we did not
        # start ourselves is a leftover).
        with _lock:
            if workflow_id not in _state.get("live", set()):
                return "RESUMABLE"
    return s


def list_all() -> List[tuple]:
    root = _root()
    out = []
    for wid in sorted(os.listdir(root)):
        if os.path.isdir(os.path.join(root, wid)):
            out.append((wid, get_status(wid)))
    return out


def delete(workflow_id: str):
    import shutil

    shutil.rmtree(_wf_dir(workflow_id), ignore_errors=True)


def _execute_durably(dag: DAGNode, workflow_id: str, input_value):
    """Walk the DAG children-first, checkpointing each node's materialized
    result; already-checkpointed nodes are loaded, not re-run."""
    import ray_tpu as ray

    steps_dir = os.path.join(_wf_dir(workflow_id), "steps")
    os.makedirs(steps_dir, exist_ok=True)
    memo: Dict[str, Any] = {}
    order = dag.topo_order()
    for node in order:
        ckpt = os.path.join(steps_dir, node._stable_uuid + ".pkl")
        if isinstance(node, InputNode):
            memo[node._stable_uuid] = input_value
            continue
        if isinstance(node, ClassNode):
            # Actors are processes, not values: they cannot checkpoint.
            # Re-instantiated on resume (reference: virtual actors are a
            # separate subsystem; plain workflow DAG treats them the same
            # way).
            memo[node._stable_uuid] = node._execute_impl(memo, (), {})
            continue
        if os.path.exists(ckpt):
            memo[node._stable_uuid] = _read(ckpt)
            continue
        ref = node._execute_impl(memo, (input_value,), {})
        value = ray.get(ref)
        _write(ckpt, value)
        memo[node._stable_uuid] = value
    return memo[order[-1]._stable_uuid]


def run(dag: DAGNode, *, workflow_id: str, input_value=None) -> Any:
    """Execute durably; blocking (reference: workflow.run, api.py)."""
    d = _wf_dir(workflow_id)
    os.makedirs(d, exist_ok=True)
    dag_path = os.path.join(d, "dag.pkl")
    if not os.path.exists(dag_path):
        _write(dag_path, dag)
        _write(os.path.join(d, "input.pkl"), input_value)
    else:
        # Re-running an existing id resumes from its STORED dag (stable
        # step uuids must match the checkpoints on disk).
        dag = _read(dag_path)
        input_value = _read(os.path.join(d, "input.pkl"))
    with _lock:
        _state.setdefault("live", set()).add(workflow_id)
    _set_status(workflow_id, "RUNNING")
    try:
        out = _execute_durably(dag, workflow_id, input_value)
    except BaseException:
        _set_status(workflow_id, "FAILED")
        raise
    finally:
        with _lock:
            _state.setdefault("live", set()).discard(workflow_id)
    # Output FIRST, then the status flip: a crash between the two must
    # never yield a SUCCESSFUL workflow without a stored output.
    _write(os.path.join(d, "output.pkl"), out)
    _set_status(workflow_id, "SUCCESSFUL")
    return out


def run_async(dag: DAGNode, *, workflow_id: str, input_value=None):
    """Like run() but on a daemon thread; returns a Future."""
    from concurrent.futures import Future

    fut: Future = Future()

    def body():
        try:
            fut.set_result(run(dag, workflow_id=workflow_id,
                               input_value=input_value))
        except BaseException as e:  # noqa: BLE001
            fut.set_exception(e)

    threading.Thread(target=body, daemon=True,
                     name=f"workflow-{workflow_id}").start()
    return fut


def resume(workflow_id: str) -> Any:
    """Continue a crashed/failed run from its checkpoints (reference:
    workflow.resume — completed steps load from storage)."""
    d = _wf_dir(workflow_id)
    if not os.path.isdir(d):
        raise ValueError(f"no workflow {workflow_id!r}")
    dag = _read(os.path.join(d, "dag.pkl"))
    input_value = _read(os.path.join(d, "input.pkl"))
    with _lock:
        _state.setdefault("live", set()).add(workflow_id)
    _set_status(workflow_id, "RUNNING")
    try:
        out = _execute_durably(dag, workflow_id, input_value)
    except BaseException:
        _set_status(workflow_id, "FAILED")
        raise
    finally:
        with _lock:
            _state.setdefault("live", set()).discard(workflow_id)
    _write(os.path.join(d, "output.pkl"), out)
    _set_status(workflow_id, "SUCCESSFUL")
    return out


def get_output(workflow_id: str) -> Any:
    path = os.path.join(_wf_dir(workflow_id), "output.pkl")
    if not os.path.exists(path):
        raise ValueError(f"workflow {workflow_id!r} has no stored output "
                         f"(status={get_status(workflow_id)})")
    return _read(path)
