"""ray_tpu.workflow — durable DAG execution (Workflow equivalent).

Reference: ``python/ray/workflow/`` (``workflow_executor.py:32`` state
machine over checkpointed steps, ``workflow_storage.py`` durable results,
``workflow_state_from_dag.py`` building runs from DAG nodes).  Same model,
condensed: ``workflow.run(dag, workflow_id=...)`` executes a
``ray_tpu.dag`` graph step by step, persisting every node's result (and
the DAG itself) to local storage; a crash mid-run leaves a RESUMABLE
workflow whose completed steps are NOT re-executed on
``workflow.resume(workflow_id)`` — exactly-once per step via checkpoints.
"""

from ray_tpu.workflow.api import (
    delete,
    get_output,
    get_status,
    init,
    list_all,
    resume,
    run,
    run_async,
)

__all__ = ["init", "run", "run_async", "resume", "get_output",
           "get_status", "list_all", "delete"]
