"""Actors — stateful workers.

Reference: ``python/ray/actor.py`` (ActorClass :377, ``_remote`` :659,
ActorHandle :1022) + centralized actor management in the GCS
(``src/ray/gcs/gcs_server/gcs_actor_manager.h:281``) + ordered task
submission (``src/ray/core_worker/transport/direct_actor_task_submitter.h:67``).

Semantics kept from the reference: one process per actor, per-handle FIFO
method ordering, ``max_restarts`` restart-on-death, named actors with
namespaces, ``max_concurrency`` threaded actors, handles picklable into
tasks.  TPU-specific: an actor created with ``num_tpus=k`` owns k chips for
its lifetime — its process env pins the chips before any jax import, which
is the actor-model analog of one JAX process per TPU host.
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, Optional

from ray_tpu._private import serialization
from ray_tpu._private.api_internal import require_runtime
from ray_tpu._private.ids import ActorID, new_task_id
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu.remote_function import (
    _normalize_resources,
    _strategy_tuple,
    serialize_args,
)

_ACTOR_OPTIONS = {
    "num_cpus", "num_tpus", "num_gpus", "resources", "name", "namespace",
    "max_restarts", "max_concurrency", "lifetime", "runtime_env",
    "scheduling_strategy", "memory", "max_task_retries", "get_if_exists",
    "_metadata",
}


def method(**opts):
    """Per-method options decorator (reference: python/ray/actor.py
    ``@ray.method(num_returns=...)``)."""

    def wrap(fn):
        fn.__ray_method_options__ = opts
        return fn

    return wrap


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str, num_returns: int):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns

    def options(self, **overrides):
        m = ActorMethod(self._handle, self._name,
                        overrides.get("num_returns", self._num_returns))
        return m

    def _build_spec(self, rt, args, kwargs):
        """(spec, num_returns) for one call — the _bulk_submit hook."""
        spec = self._handle._build_method_spec(
            rt, self._name, args, kwargs, self._num_returns)
        return spec, self._num_returns

    def remote(self, *args, **kwargs):
        return self._handle._submit_method(
            self._name, args, kwargs, self._num_returns)


class ActorHandle:
    """Refcounted handle (reference: actor out-of-scope GC,
    gcs_actor_manager.h "RemoveActorNameFromRegistry on all handles out of
    scope").  Every live handle holds one count at the head; pickling a
    handle adds one IN-FLIGHT count that the deserialized copy takes
    ownership of (transfer-on-send).  When the count reaches zero the head
    terminates the actor after its queued work drains — unnamed,
    non-detached actors only (named actors here persist until killed or
    job end, a deliberate simplification)."""

    def __init__(self, actor_id: bytes, method_meta: Dict[str, int],
                 name: Optional[str] = None, *, _register: bool = True):
        self._actor_id = actor_id
        self._method_meta = method_meta
        self._name = name
        if _register:
            try:
                require_runtime().actor_handle_addref(actor_id)
            except Exception:
                pass  # runtime not up (e.g. handle built during shutdown)

    @property
    def _id_hex(self):
        return self._actor_id.hex()

    def __getattr__(self, item):
        meta = object.__getattribute__(self, "_method_meta")
        if item in meta:
            return ActorMethod(self, item, meta[item])
        raise AttributeError(
            f"Actor has no method {item!r}; remote methods: {sorted(meta)}")

    def _build_method_spec(self, rt, method_name, args, kwargs,
                           num_returns):
        """Spec for one method call (shared by .remote and the bulk
        submission helper, remote_function._bulk_submit)."""
        spec = {
            "task_id": new_task_id().binary(),
            "actor_id": self._actor_id,
            "method": method_name,
            "num_returns": num_returns,
            "name": f"actor.{method_name}",
            "func_id": None,
        }
        serialize_args(rt, args, kwargs, spec)
        return spec

    def _submit_method(self, method_name, args, kwargs, num_returns):
        rt = require_runtime()
        spec = self._build_method_spec(rt, method_name, args, kwargs,
                                       num_returns)
        refs = rt.submit_task(spec)
        if num_returns == 0:
            return None
        if num_returns == 1:
            return refs[0]
        return refs

    def __reduce__(self):
        # Transfer-on-send with a one-shot token: the serialized bytes
        # hold one count bound to ``token``; the FIRST deserialization
        # returns it (each copy registers its own count in __init__), so
        # a stored pickle materialized N times stays balanced.  A pickle
        # that is never deserialized holds its count until job end — the
        # documented slack vs the reference's full borrow protocol.
        import os as _os

        token = _os.urandom(8)
        try:
            require_runtime().actor_handle_serialized(self._actor_id,
                                                      token)
        except Exception:
            pass
        return (_rebuild_handle, (self._actor_id, self._method_meta,
                                  self._name, token))

    def __del__(self):
        try:
            require_runtime().actor_handle_decref(self._actor_id)
        except Exception:
            pass  # interpreter shutdown

    def __repr__(self):
        return f"ActorHandle({self._actor_id.hex()[:12]})"


def _rebuild_handle(actor_id, method_meta, name, token=None):
    h = ActorHandle(actor_id, method_meta, name)
    if token is not None:
        try:
            require_runtime().actor_handle_deserialized(actor_id, token)
        except Exception:
            pass
    return h


def _collect_methods(cls) -> Dict[str, int]:
    meta = {}
    for name in dir(cls):
        if name.startswith("__") and name != "__call__":
            continue
        fn = getattr(cls, name, None)
        if callable(fn):
            opts = getattr(fn, "__ray_method_options__", {})
            meta[name] = opts.get("num_returns", 1)
    return meta


class ActorClass:
    def __init__(self, cls, options: Optional[Dict[str, Any]] = None):
        for k in options or {}:
            if k not in _ACTOR_OPTIONS:
                raise ValueError(f"Invalid actor option {k!r}")
        self._cls = cls
        self._options = dict(options or {})
        self._payload: Optional[bytes] = None
        self._func_id: Optional[str] = None
        # dir()-walk of the class is invariant: computed once, shared by
        # clones (options() re-clones carry it over like _payload).
        self._method_meta: Optional[Dict[str, int]] = None
        self.__name__ = getattr(cls, "__name__", "Actor")

    def __call__(self, *a, **kw):
        raise TypeError(
            f"Actor class {self.__name__} cannot be instantiated directly; "
            f"use {self.__name__}.remote().")

    def options(self, **overrides) -> "ActorClass":
        merged = dict(self._options)
        merged.update(overrides)
        clone = ActorClass(self._cls, merged)
        clone._payload = self._payload
        clone._func_id = self._func_id
        clone._method_meta = self._method_meta
        return clone

    def bind(self, *args, **kwargs):
        """Lazy actor-construction DAG node (reference: python/ray/dag
        ClassNode); method .bind on the result adds ClassMethodNodes."""
        from ray_tpu.dag.node import ClassNode

        return ClassNode(self, args, kwargs)

    def remote(self, *args, **kwargs) -> ActorHandle:
        rt = require_runtime()
        opts = self._options
        if opts.get("get_if_exists") and opts.get("name"):
            try:
                return get_actor(opts["name"],
                                 opts.get("namespace", "default"))
            except ValueError:
                pass
        if self._payload is None:
            try:
                self._payload = serialization.dumps_inline(self._cls)
            except Exception as err:  # noqa: BLE001 — diagnosed, re-raised
                from ray_tpu.devtools.serializability import (
                    diagnose_pickle_error,
                )

                diagnose_pickle_error(self._cls, self.__name__, err)
            self._func_id = "actor-" + hashlib.sha1(
                self._payload).hexdigest()[:24]
        if self._method_meta is None:
            self._method_meta = _collect_methods(self._cls)
        method_meta = self._method_meta
        resources = _normalize_resources(opts)
        spec = {
            "task_id": new_task_id().binary(),
            "func_id": self._func_id,
            "num_returns": 1,
            "name": f"{self.__name__}.__init__",
            "resources": resources,
            "scheduling_strategy": _strategy_tuple(
                opts.get("scheduling_strategy")),
        }
        serialize_args(rt, args, kwargs, spec)
        creation_opts = {
            "max_restarts": opts.get("max_restarts", 0),
            # In-flight/queued method calls on a restarting actor are
            # replayed up to this many times each (0 = fail them with
            # ActorDiedError, the legacy behavior; -1 = unlimited).
            "max_task_retries": opts.get("max_task_retries", 0),
            "max_concurrency": opts.get("max_concurrency", 1),
            "name": opts.get("name"),
            "namespace": opts.get("namespace", "default"),
            "resources": resources,
            "scheduling_strategy": spec["scheduling_strategy"],
            "method_names": method_meta,
            "lifetime": opts.get("lifetime"),
        }
        spec["func_payload"] = self._payload
        if rt.is_worker():
            actor_id = rt._request(
                lambda rid: ("create_actor_req", rid, spec, creation_opts))
            if isinstance(actor_id, Exception):
                raise actor_id
        else:
            actor_id = rt.create_actor(spec, creation_opts)
        return ActorHandle(actor_id, method_meta, opts.get("name"))


def get_actor(name: str, namespace: str = "default") -> ActorHandle:
    rt = require_runtime()
    if rt.is_worker():
        reply = rt._request(lambda rid: ("get_actor_req", rid, name,
                                         namespace))
        ok, actor_id, method_meta = reply
        if not ok:
            raise ValueError(f"No actor named {name!r}")
        return ActorHandle(actor_id, method_meta, name)
    actor_id, actor = rt.get_named_actor(name, namespace)
    return ActorHandle(actor_id, actor.options.get("method_names", {}), name)
