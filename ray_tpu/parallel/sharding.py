"""Logical-axis sharding rules.

Model code names tensor dimensions *logically* ("batch", "embed", "heads",
"expert", ...); one rules table maps logical names to mesh axes.  Swapping
the table re-shards the whole model — DP-only, FSDP, 2D (fsdp x tp), MoE —
without touching model code.  This is the TPU-native replacement for the
reference's per-framework DDP/FSDP wrapping (``prepare_model``,
``python/ray/train/torch/train_loop_utils.py:75``): there the strategy is
baked into wrapper modules; here it is data.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.parallel.mesh import (
    AXIS_DP, AXIS_EP, AXIS_FSDP, AXIS_PP, AXIS_SP, AXIS_TP,
)

# A logical axis maps to one mesh axis, a tuple of mesh axes (dimension
# sharded over their product), or None (replicated).
MeshAxes = Union[None, str, Tuple[str, ...]]
LogicalAxisRules = Dict[str, MeshAxes]

# Megatron-style 2D sharding + MoE + sequence parallelism.  Batch is split
# over (dp, fsdp): fsdp behaves as extra data parallelism for activations
# while sharding parameters ZeRO-3 style on their "embed"-like dimension.
DEFAULT_RULES: LogicalAxisRules = {
    "batch": (AXIS_DP, AXIS_FSDP),
    "seq": AXIS_SP,               # sequence/context parallelism (ring attn)
    "embed": None,                # activation embed dim stays replicated
    "heads": AXIS_TP,             # attention heads over tensor axis
    "kv_heads": AXIS_TP,
    "head_dim": None,
    "mlp": AXIS_TP,               # ffn hidden: column-parallel then row-parallel
    "vocab": AXIS_TP,             # embedding/vocab-parallel output head
    "kernel_in": AXIS_FSDP,       # ZeRO-3: param input dim over fsdp
    "expert": AXIS_EP,            # MoE experts over expert axis
    "stage": AXIS_PP,             # pipeline stages (stacked-stage layout)
    "layer": None,                # scanned-layer leading dim (non-pipelined)
}


def logical_to_mesh_axes(
    logical_axes: Sequence[Optional[str]],
    rules: Optional[LogicalAxisRules] = None,
) -> P:
    """('batch','seq','embed') -> PartitionSpec(('dp','fsdp'),'sp',None).

    Mesh axes already consumed by an earlier dimension are dropped (a mesh
    axis can shard at most one dimension of a given tensor) — same contract
    as flax's logical partitioning, re-implemented to stay decoupled from
    flax internals.
    """
    rules = DEFAULT_RULES if rules is None else rules
    used = set()
    out = []
    for name in logical_axes:
        axes = rules.get(name) if name is not None else None
        if axes is None:
            out.append(None)
            continue
        if isinstance(axes, str):
            axes = (axes,)
        axes = tuple(a for a in axes if a not in used)
        used.update(axes)
        if not axes:
            out.append(None)
        elif len(axes) == 1:
            out.append(axes[0])
        else:
            out.append(axes)
    return P(*out)


def named_sharding(mesh: Mesh, *logical_axes: Optional[str],
                   rules: Optional[LogicalAxisRules] = None) -> NamedSharding:
    return NamedSharding(mesh, logical_to_mesh_axes(logical_axes, rules))


def with_logical_constraint(x: jax.Array, logical_axes: Sequence[Optional[str]],
                            mesh: Optional[Mesh] = None,
                            rules: Optional[LogicalAxisRules] = None) -> jax.Array:
    """``lax.with_sharding_constraint`` by logical names.  Inside jit under a
    mesh context the PartitionSpec alone suffices (jax>=0.4.30 semantics)."""
    spec = logical_to_mesh_axes(logical_axes, rules)
    if getattr(jax, "shard_map", None) is None and spec:
        # Legacy-jax path: manual_shard_map regions are FULL manual
        # there, and a constraint naming a manually-bound mesh axis is
        # rejected at lowering (too late for a try/except here).
        # Constraints are propagation hints, not semantics — drop any
        # that touch a bound axis.
        get_bound = getattr(jax.core,
                            "unsafe_get_axis_names_DO_NOT_USE", None)
        bound = set(get_bound()) if get_bound is not None else set()
        if bound:
            named = set()
            for a in spec:
                if a is not None:
                    named.update(a if isinstance(a, tuple) else (a,))
            if named & bound:
                return x
    if mesh is not None:
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
    return jax.lax.with_sharding_constraint(x, spec)


def shard_pytree(tree: Any, spec_tree: Any, mesh: Mesh,
                 rules: Optional[LogicalAxisRules] = None) -> Any:
    """Device-put a pytree of host arrays according to a matching pytree of
    logical-axis tuples (e.g. from a model's ``param_logical_axes()``)."""
    def _put(x, axes):
        return jax.device_put(x, named_sharding(mesh, *axes, rules=rules))
    return jax.tree.map(_put, tree, spec_tree,
                        is_leaf=lambda x: x is None)


def manual_shard_map(f, axis_names, in_specs, out_specs,
                     mesh: Optional[Mesh] = None):
    """shard_map manual over only ``axis_names`` (other mesh axes stay under
    GSPMD auto-propagation), resolved against the *context* mesh so ops that
    wrap themselves in shard_map (ring attention over 'sp', pipeline over
    'pp') nest inside each other and inside jit.  ``mesh`` is only used to
    establish a context when none exists (eager/standalone calls).

    Two jax API generations are supported, feature-detected once:
    ``jax.shard_map`` (axis_names/check_vma, context-mesh resolution) on
    current releases, and the 0.4.x ``jax.experimental.shard_map`` — an
    explicit-mesh API where partial-manual is spelled as the complement
    ``auto=`` set and the context mesh comes from the classic Mesh
    context (``thread_resources``)."""
    import contextlib

    if getattr(jax, "shard_map", None) is None:
        return _manual_shard_map_04(f, axis_names, in_specs, out_specs,
                                    mesh)

    mapped = jax.shard_map(f, in_specs=in_specs, out_specs=out_specs,
                           axis_names=set(axis_names), check_vma=False)

    def call(*args):
        # Mesh-context check happens at call time, inside the with: a
        # jax.set_mesh constructed eagerly at wrap time would mutate the
        # global mesh immediately and be single-use.
        ctx = jax.sharding.get_abstract_mesh()
        need_ctx = (ctx is None or ctx.empty) and mesh is not None
        cm = jax.set_mesh(mesh) if need_ctx else contextlib.nullcontext()
        with cm:
            from jax._src import core as _core
            if _core.trace_state_clean():
                # Partial-manual shard_map only lowers correctly under jit
                # (eager evaluation tries to complete out_specs with every
                # mesh axis); jit here is semantically free.
                return jax.jit(mapped)(*args)
            return mapped(*args)

    return call


def _manual_shard_map_04(f, axis_names, in_specs, out_specs,
                         mesh: Optional[Mesh]):
    """manual_shard_map for jax 0.4.x (see above)."""
    from jax.experimental.shard_map import shard_map as _shard_map

    # One (mapped, jitted) pair per resolved mesh: rebuilding them per
    # call would defeat jax's trace/compile cache (keyed on callable
    # identity) and recompile the region on every eager invocation.
    cache: Dict[Any, tuple] = {}

    def call(*args):
        # Context mesh wins (new-API semantics); ``mesh`` covers
        # standalone/eager calls.  Resolved per call: the wrapping mesh
        # context is only live at trace time.
        from jax._src import core as _core
        from jax._src import mesh as _mesh_lib

        ctx = _mesh_lib.thread_resources.env.physical_mesh
        use = ctx if ctx is not None and not ctx.empty else mesh
        if use is None or use.empty:
            raise ValueError(
                "manual_shard_map needs an active mesh context (use_mesh) "
                "or an explicit mesh argument")
        ent = cache.get(use)
        if ent is None:
            # FULL manual (not ``auto=`` partial): 0.4.x's partitioner
            # hits a manual-subgroup CHECK (spmd_partitioner.cc:512)
            # resharding in and out of partial-manual regions.  Axes the
            # specs don't mention are replicated across the region
            # instead of auto-propagated — numerically identical, at
            # worst extra gathers on those axes for this legacy path.
            mapped = _shard_map(f, use, in_specs=in_specs,
                                out_specs=out_specs, check_rep=False)
            ent = cache[use] = (mapped, jax.jit(mapped))
        mapped, jitted = ent
        with use:
            if _core.trace_state_clean():
                return jitted(*args)
            return mapped(*args)

    return call


def sharding_tree(spec_tree: Any, mesh: Mesh,
                  rules: Optional[LogicalAxisRules] = None) -> Any:
    """Pytree of logical-axis tuples -> pytree of NamedShardings (for jit
    in_shardings/out_shardings)."""
    return jax.tree.map(
        lambda axes: named_sharding(mesh, *axes, rules=rules), spec_tree,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            a is None or isinstance(a, str) for a in x))
