"""ray_tpu.parallel — parallelism strategies as first-class mesh axes.

The reference delegates multi-device parallelism to out-of-band libraries
(torch.distributed inside Train workers, ``python/ray/train/torch/config.py:113``;
NCCL/Gloo groups in ``python/ray/util/collective/``; JAX model parallelism only
via the Alpa release tests, ``release/alpa_tests/``).  On TPU, parallelism is a
property of the *compiled program*: a ``jax.sharding.Mesh`` over ICI/DCN plus
partition specs, with XLA inserting the collectives.  This package makes that
the framework's first-class layer:

- :mod:`mesh`       — mesh axes (dp, fsdp, ep, pp, sp, tp) and construction.
- :mod:`sharding`   — logical-axis rules -> ``NamedSharding``/``PartitionSpec``.
- :mod:`pipeline`   — GPipe-style pipeline parallelism via shard_map+ppermute.
(``ray.util.collective``-equivalent host-level API lives in
``ray_tpu.util.collective``; in-mesh collectives are ``jax.lax.p*``.)
"""

from ray_tpu.parallel.mesh import (
    AXIS_DP,
    AXIS_EP,
    AXIS_FSDP,
    AXIS_PP,
    AXIS_SP,
    AXIS_TP,
    MESH_AXES,
    MeshConfig,
    make_mesh,
    use_mesh,
)
from ray_tpu.parallel.sharding import (
    LogicalAxisRules,
    DEFAULT_RULES,
    logical_to_mesh_axes,
    named_sharding,
    shard_pytree,
    with_logical_constraint,
)

__all__ = [
    "AXIS_DP", "AXIS_FSDP", "AXIS_EP", "AXIS_PP", "AXIS_SP", "AXIS_TP",
    "MESH_AXES", "MeshConfig", "make_mesh", "use_mesh",
    "LogicalAxisRules", "DEFAULT_RULES", "logical_to_mesh_axes",
    "named_sharding", "shard_pytree", "with_logical_constraint",
]
