"""Pipeline parallelism: GPipe schedule over the 'pp' mesh axis.

The reference has no pipeline parallelism (SURVEY.md §5 — Alpa provided
inter-op parallelism *on top of* Ray in release tests only).  Here it is a
framework primitive: transformer layers are split into ``pp`` contiguous
stages; each device in the 'pp' axis holds one stage's weights; microbatches
flow through the ring with ``lax.ppermute`` carrying activations stage to
stage over ICI.

Implementation: ``jax.shard_map`` manual *only over 'pp'* (``axis_names``),
so dp/fsdp/tp/sp/ep stay under GSPMD propagation inside the stage body —
pipeline composes with the other strategies instead of forcing a full
manual rewrite.  The schedule is plain GPipe (fill/drain bubble of
``pp - 1`` steps; acceptable at microbatches >> pp, 1F1B is a later
optimization).  The loop is ``lax.scan`` + ``ppermute`` + ``lax.cond`` —
all reverse-differentiable, so the pipelined backward (reverse ppermutes)
falls out of AD.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.parallel.mesh import AXIS_PP


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   stage_params: Any, x: jax.Array, *,
                   mesh: Mesh, num_microbatches: int,
                   axis_name: str = AXIS_PP,
                   manual_axes: Optional[set] = None,
                   x_spec: P = P()) -> jax.Array:
    """Run ``x`` through ``pp`` stages of ``stage_fn``.

    stage_params: pytree whose leaves have leading dim ``pp`` (stage-stacked)
    — sharded over 'pp' by the caller or re-sharded here via in_specs.
    x: (batch, ...) activations; batch must divide by ``num_microbatches``.
    stage_fn(params_for_stage, x_mb) -> y_mb with identical shape.

    ``manual_axes``/``x_spec``: extra axes to bind manually in the same
    region (e.g. 'sp' with a seq-sharded ``x_spec`` for ring attention
    inside pipeline stages — manual regions cannot nest).
    """
    manual_axes = manual_axes or {axis_name}
    n = mesh.shape[axis_name]
    if n == 1 and manual_axes == {axis_name}:
        return stage_fn(jax.tree.map(lambda p: p[0], stage_params), x)
    b = x.shape[0]
    if b % num_microbatches:
        raise ValueError(f"batch {b} % microbatches {num_microbatches} != 0")
    mb = b // num_microbatches
    x_mb = x.reshape((num_microbatches, mb) + x.shape[1:])

    def body(params, xs):
        # shard_map hands each pp rank its stage slice with a leading
        # singleton stage dim — strip it.
        params = jax.tree.map(lambda p: p[0], params)
        idx = jax.lax.axis_index(axis_name)
        steps = num_microbatches + n - 1
        perm = [(i, (i + 1) % n) for i in range(n)]

        def step(buf, t):
            take = jnp.clip(t, 0, num_microbatches - 1)
            fresh = jax.lax.dynamic_index_in_dim(xs, take, 0, keepdims=False)
            inp = jnp.where(idx == 0, fresh, buf)
            out = stage_fn(params, inp)
            return jax.lax.ppermute(out, axis_name, perm), out

        _, outs = jax.lax.scan(step, jnp.zeros_like(xs[0]),
                               jnp.arange(steps))
        # Last stage produced the real outputs at steps n-1 .. n-1+M-1;
        # broadcast them to every pp rank (masked psum) so downstream
        # (final norm / loss) is replicated over 'pp'.
        outs = jax.lax.dynamic_slice_in_dim(outs, n - 1, num_microbatches, 0)
        mask = (idx == n - 1).astype(outs.dtype)
        return jax.lax.psum(outs * mask, axis_name)

    from ray_tpu.parallel.sharding import manual_shard_map
    specs_p = jax.tree.map(lambda _: P(axis_name), stage_params)
    mb_spec = P(None, *x_spec)   # microbatch dim prepended
    y_mb = manual_shard_map(
        body, manual_axes, in_specs=(specs_p, mb_spec), out_specs=mb_spec,
        mesh=mesh,
    )(stage_params, x_mb)
    return y_mb.reshape(x.shape)


def split_stages(layer_params: Any, num_stages: int) -> Any:
    """Reshape stacked-layer params (L, ...) -> (pp, L/pp, ...)."""
    def rs(p):
        l = p.shape[0]
        if l % num_stages:
            raise ValueError(f"{l} layers not divisible by {num_stages} stages")
        return p.reshape((num_stages, l // num_stages) + p.shape[1:])
    return jax.tree.map(rs, layer_params)
