"""Device mesh construction for TPU pods.

Replaces the reference's process-group bootstrap (NCCL rendezvous in
``python/ray/train/torch/config.py:113``, group management in
``python/ray/util/collective/collective.py:120``) with the XLA-native
equivalent: one global ``jax.sharding.Mesh`` whose axes encode every
parallelism strategy.  Axis order is chosen so the *innermost* (fastest
varying, ICI-adjacent) axes carry the heaviest traffic:

    (dp, fsdp, ep, pp, sp, tp)

- ``tp``   tensor parallelism — per-layer allreduce/allgather every matmul;
           must ride ICI, so it is innermost (adjacent devices).
- ``sp``   sequence/context parallelism — ring attention ppermute traffic.
- ``pp``   pipeline stages — point-to-point activation transfers.
- ``ep``   expert parallelism — all-to-all token routing.
- ``fsdp`` ZeRO-3 parameter sharding — per-step allgather/reduce-scatter.
- ``dp``   pure data parallelism — one gradient psum per step; tolerates DCN,
           so it is outermost (maps to the multi-slice axis on multi-pod).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np

AXIS_DP = "dp"
AXIS_FSDP = "fsdp"
AXIS_EP = "ep"
AXIS_PP = "pp"
AXIS_SP = "sp"
AXIS_TP = "tp"

MESH_AXES: Tuple[str, ...] = (AXIS_DP, AXIS_FSDP, AXIS_EP, AXIS_PP, AXIS_SP, AXIS_TP)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Axis sizes for the global mesh.  ``-1`` on at most one axis means
    "absorb all remaining devices" (like torch DeviceMesh / maxtext).

    The reference's ScalingConfig (``python/ray/air/config.py:80``) carries
    only ``num_workers``/``use_gpu``; a TPU ScalingConfig instead carries a
    MeshConfig — the shape of the parallelism, not just its degree.
    """

    dp: int = -1
    fsdp: int = 1
    ep: int = 1
    pp: int = 1
    sp: int = 1
    tp: int = 1

    def sizes(self, n_devices: int) -> Tuple[int, ...]:
        sizes = [self.dp, self.fsdp, self.ep, self.pp, self.sp, self.tp]
        wild = [i for i, s in enumerate(sizes) if s == -1]
        if len(wild) > 1:
            raise ValueError("at most one mesh axis may be -1")
        fixed = math.prod(s for s in sizes if s != -1)
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {fixed}")
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {tuple(sizes)} wants {fixed} devices, have {n_devices}")
        return tuple(sizes)

    @staticmethod
    def auto(n_devices: int,
             prefer: Sequence[str] = (AXIS_TP, AXIS_PP, AXIS_SP, AXIS_EP,
                                      AXIS_FSDP, AXIS_DP)) -> "MeshConfig":
        """Factor ``n_devices`` into powers of two across axes in ``prefer``
        order (innermost-heaviest first) — used by tests and the multi-chip
        dry-run to exercise every axis that fits."""
        sizes = {a: 1 for a in MESH_AXES}
        rest = n_devices
        for axis in prefer:
            if rest % 2 == 0 and rest > 1:
                sizes[axis] = 2
                rest //= 2
        # Any leftover factor (odd or large) goes to dp.
        sizes[AXIS_DP] *= rest
        return MeshConfig(**sizes)


def make_mesh(config: Optional[MeshConfig] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> jax.sharding.Mesh:
    """Build the global mesh.

    On real TPU hardware ``jax.make_mesh`` lays axes out over the physical
    ICI torus (it calls the device-assignment heuristics that keep inner
    axes on adjacent chips); on the CPU backend used in tests it reshapes
    ``jax.devices()`` row-major, which preserves axis semantics.

    Axes are ``Auto`` (GSPMD propagation): model code steers the partitioner
    with ``with_sharding_constraint`` rather than jax 0.9's explicit
    sharding-in-types mode, which would demand out_shardings on every
    ambiguous op (gathers, einsums) throughout model code.  On jax
    releases predating ``jax.sharding.AxisType`` (<= 0.4.x) every axis is
    implicitly Auto, so the kwarg is simply omitted — feature-detected,
    since passing it would raise (AttributeError here, TypeError inside
    ``jax.make_mesh``).
    """
    devices = list(devices if devices is not None else jax.devices())
    config = config or MeshConfig()
    sizes = config.sizes(len(devices))
    axis_type = getattr(jax.sharding, "AxisType", None)
    kwargs = ({"axis_types": (axis_type.Auto,) * len(MESH_AXES)}
              if axis_type is not None else {})
    try:
        try:
            return jax.make_mesh(sizes, MESH_AXES, devices=devices,
                                 **kwargs)
        except TypeError:
            if not kwargs:
                raise
            # jax.make_mesh exists but predates the axis_types kwarg.
            kwargs = {}
            return jax.make_mesh(sizes, MESH_AXES, devices=devices)
    except (ValueError, NotImplementedError):
        # jax.make_mesh's contiguous-remapping can reject exotic topologies;
        # fall back to a plain row-major reshape.
        arr = np.asarray(devices).reshape(sizes)
        try:
            return jax.sharding.Mesh(arr, MESH_AXES, **kwargs)
        except TypeError:
            return jax.sharding.Mesh(arr, MESH_AXES)


def mesh_axis_size(mesh: jax.sharding.Mesh, axis: str) -> int:
    return mesh.shape[axis]


def use_mesh(mesh: jax.sharding.Mesh):
    """Activate ``mesh`` as the ambient mesh, as a context manager.

    On current jax this is ``jax.set_mesh``; releases predating it
    (<= 0.4.x) get the classic ``Mesh`` context manager, which sets the
    thread-resource physical mesh that pjit/shard_map resolve against —
    the same role."""
    setter = getattr(jax, "set_mesh", None)
    if setter is not None:
        return setter(mesh)
    return mesh
