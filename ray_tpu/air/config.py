"""AIR config dataclasses (reference: python/ray/air/config.py).

``ScalingConfig`` (:80 in the reference) is the TPU divergence point: the
reference scales by ``num_workers x use_gpu``; on TPU the unit of scale is a
slice with a mesh shape, so ScalingConfig carries a
:class:`ray_tpu.parallel.MeshConfig` plus chips-per-worker.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

from ray_tpu.parallel.mesh import MeshConfig


@dataclasses.dataclass
class ScalingConfig:
    """How a trainer scales out.

    num_workers: processes (one per TPU host in multi-host).
    tpu_chips_per_worker: chips each worker owns (0 = CPU worker).
    mesh: global mesh axis sizes laid over num_workers * chips_per_worker
          devices (reference analog: none — torch DDP is dp-only).
    resources_per_worker: extra scheduler resources, as in the reference.
    """

    num_workers: int = 1
    tpu_chips_per_worker: int = 0
    mesh: Optional[MeshConfig] = None
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"

    @property
    def total_chips(self) -> int:
        return self.num_workers * self.tpu_chips_per_worker

    def worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker or {"CPU": 1.0})
        if self.tpu_chips_per_worker:
            res["TPU"] = float(self.tpu_chips_per_worker)
        return res


@dataclasses.dataclass
class FailureConfig:
    """Reference: python/ray/air/config.py:508."""

    max_failures: int = 0  # 0 = no retries; -1 = infinite


@dataclasses.dataclass
class CheckpointConfig:
    """Reference: python/ray/air/config.py:567."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0


@dataclasses.dataclass
class RunConfig:
    """Reference: python/ray/air/config.py:695."""

    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: Optional[FailureConfig] = None
    checkpoint_config: Optional[CheckpointConfig] = None
    stop: Optional[Dict[str, Any]] = None
    verbose: int = 1
