"""Worker-side training session API (reference: python/ray/air/session.py:43
and python/ray/train/_internal/session.py:63).

Inside ``train_loop_per_worker`` user code calls::

    from ray_tpu.air import session
    session.report({"loss": ...}, checkpoint=Checkpoint.from_dict(...))
    session.get_world_rank(); session.get_checkpoint()

Reports accumulate in the active session and are returned to the driver by
the worker actor when the loop finishes (the driver-side streaming queue of
the reference is a round-2 item; Tune-style mid-training coordination uses
the iterative Trainable API instead).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from ray_tpu.air.checkpoint import Checkpoint

_local = threading.local()


class _TrainSession:
    def __init__(self, world_rank: int = 0, world_size: int = 1,
                 local_rank: int = 0,
                 checkpoint: Optional[Checkpoint] = None,
                 trial_info: Optional[Dict[str, Any]] = None,
                 stream_topic: Optional[str] = None):
        self.world_rank = world_rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.loaded_checkpoint = checkpoint
        self.trial_info = trial_info or {}
        self.stream_topic = stream_topic
        self.reports: List[Dict[str, Any]] = []
        self.checkpoints: List[Checkpoint] = []

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None):
        entry = dict(metrics)
        entry["_training_iteration"] = len(self.reports)
        self.reports.append(entry)
        if checkpoint is not None:
            self.checkpoints.append(checkpoint)
        if self.stream_topic is not None:
            # Live-stream to the driver so mid-training checkpoints survive
            # worker death (reference: the session result queue,
            # train/_internal/session.py:322).
            try:
                from ray_tpu._private.worker_main import get_worker_runtime
                rt = get_worker_runtime()
                if rt is not None:
                    import pickle
                    # Only rank 0 ships checkpoint bytes — the driver
                    # keeps rank 0's anyway, other ranks' would be
                    # serialized and dropped.
                    ship = (checkpoint is not None
                            and self.world_rank == 0)
                    payload = pickle.dumps({
                        "rank": self.world_rank,
                        "metrics": entry,
                        "checkpoint": (checkpoint.to_bytes()
                                       if ship else None),
                    })
                    rt.publish_event(self.stream_topic, payload)
            except Exception:
                pass  # streaming is best-effort; end-of-run return is exact


def _set_session(s: Optional[_TrainSession]):
    _local.session = s


def _get_session() -> Optional[_TrainSession]:
    return getattr(_local, "session", None)


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    s = _get_session()
    if s is None:
        raise RuntimeError("session.report() outside a train session")
    s.report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    s = _get_session()
    return s.loaded_checkpoint if s else None


def get_world_rank() -> int:
    s = _get_session()
    return s.world_rank if s else 0


def get_world_size() -> int:
    s = _get_session()
    return s.world_size if s else 1


def get_local_rank() -> int:
    s = _get_session()
    return s.local_rank if s else 0


def get_trial_name() -> Optional[str]:
    s = _get_session()
    return s.trial_info.get("name") if s else None
