"""Result object returned by Trainer.fit / Tuner (reference:
python/ray/air/result.py)."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from ray_tpu.air.checkpoint import Checkpoint


@dataclasses.dataclass
class Result:
    metrics: Dict[str, Any]
    checkpoint: Optional[Checkpoint] = None
    error: Optional[BaseException] = None
    metrics_history: Optional[List[Dict[str, Any]]] = None

    @property
    def best_checkpoint(self) -> Optional[Checkpoint]:
        return self.checkpoint
