"""ray_tpu.air — shared trainer/tuner plumbing (Ray AIR equivalent).

Reference: ``python/ray/air/`` (SURVEY.md §2.3) — config dataclasses
(``config.py:80,508,567,695``), the morphing Checkpoint (``checkpoint.py:63``),
and ``session.report`` (``session.py:43``).  TPU-first difference: a
ScalingConfig describes a device *mesh shape* (MeshConfig), not just a worker
count + use_gpu flag.
"""

from ray_tpu.air.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.result import Result
from ray_tpu.air import session

__all__ = ["ScalingConfig", "RunConfig", "FailureConfig", "CheckpointConfig",
           "Checkpoint", "Result", "session"]
