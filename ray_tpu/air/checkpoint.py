"""Checkpoint: a value object morphing dict <-> directory <-> bytes.

Reference: ``python/ray/air/checkpoint.py:63`` — the same free-morphing
contract (a Checkpoint created from any form can be consumed in any form),
TPU-adapted: array leaves are numpy/jax arrays saved with ``np.savez`` and a
JSON-encoded pytree skeleton, so sharded jax params round-trip after a
``jax.device_get``.  (Orbax integration for async multi-host checkpointing
lives in train/checkpointing.py.)
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import tempfile
from typing import Any, Dict, Optional

import numpy as np

_ARRAYS = "__arrays__.npz"
_PAYLOAD = "__payload__.pkl"
_META = "__meta__.json"


def _split_arrays(obj: Any, prefix: str, arrays: Dict[str, np.ndarray]):
    """Replace array leaves with placeholders, collecting them flat."""
    if isinstance(obj, dict):
        return {k: _split_arrays(v, f"{prefix}/{k}", arrays)
                for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        vals = [_split_arrays(v, f"{prefix}/{i}", arrays)
                for i, v in enumerate(obj)]
        return type(obj)(vals) if not isinstance(obj, tuple) else tuple(vals)
    try:
        import jax
        if isinstance(obj, jax.Array):
            arrays[prefix] = np.asarray(jax.device_get(obj))
            return {"__array_ref__": prefix}
    except ImportError:
        pass
    if isinstance(obj, np.ndarray):
        arrays[prefix] = obj
        return {"__array_ref__": prefix}
    return obj


def _join_arrays(obj: Any, arrays) -> Any:
    if isinstance(obj, dict):
        if set(obj) == {"__array_ref__"}:
            return arrays[obj["__array_ref__"]]
        return {k: _join_arrays(v, arrays) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        vals = [_join_arrays(v, arrays) for v in obj]
        return tuple(vals) if isinstance(obj, tuple) else vals
    return obj


class Checkpoint:
    """Morphing checkpoint (dict | directory | bytes)."""

    def __init__(self, data: Optional[Dict[str, Any]] = None,
                 path: Optional[str] = None):
        if (data is None) == (path is None):
            raise ValueError("exactly one of data/path required")
        self._data = data
        self._path = path

    # -- constructors ------------------------------------------------------
    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        return cls(data=dict(data))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path=path)

    @classmethod
    def from_bytes(cls, blob: bytes) -> "Checkpoint":
        return cls(data=pickle.loads(blob))

    # -- consumers ---------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        if self._data is not None:
            return self._data
        arrays = {}
        npz_path = os.path.join(self._path, _ARRAYS)
        if os.path.exists(npz_path):
            with np.load(npz_path, allow_pickle=False) as z:
                arrays = {k: z[k] for k in z.files}
        with open(os.path.join(self._path, _PAYLOAD), "rb") as f:
            skeleton = pickle.load(f)
        return _join_arrays(skeleton, arrays)

    def to_directory(self, path: Optional[str] = None) -> str:
        if self._path is not None and path is None:
            return self._path
        path = path or tempfile.mkdtemp(prefix="rtpu-ckpt-")
        os.makedirs(path, exist_ok=True)
        if self._path is not None:
            if os.path.abspath(self._path) != os.path.abspath(path):
                shutil.copytree(self._path, path, dirs_exist_ok=True)
            return path
        arrays: Dict[str, np.ndarray] = {}
        skeleton = _split_arrays(self._data, "", arrays)
        if arrays:
            np.savez(os.path.join(path, _ARRAYS), **arrays)
        with open(os.path.join(path, _PAYLOAD), "wb") as f:
            pickle.dump(skeleton, f)
        with open(os.path.join(path, _META), "w") as f:
            json.dump({"format": "ray_tpu.air.Checkpoint", "version": 1}, f)
        return path

    def to_bytes(self) -> bytes:
        return pickle.dumps(self.to_dict())

    def __repr__(self):
        kind = "dict" if self._data is not None else f"dir:{self._path}"
        return f"Checkpoint({kind})"
