"""ray_tpu — a TPU-native distributed computing framework.

Capability parity with the Ray reference (tasks, actors, objects, placement
groups, Train/Tune/Data/Serve/RLlib-equivalents) re-designed for TPU
hardware: the device plane is JAX/XLA — workers own TPU chips, collectives
ride ICI via ``jax.lax.p*`` under ``pjit``/``shard_map`` meshes, hot kernels
are Pallas — while the runtime plane (scheduling, ownership, object store)
stays host-side, as in the reference.

Public surface mirrors ``python/ray/__init__.py``:

    import ray_tpu as ray
    ray.init()
    @ray.remote
    def f(x): return x + 1
    ray.get(f.remote(1))
"""

import os as _os

# Opt-in lock-order checker (RAY_TPU_LOCKCHECK=1 logs cycles, =raise
# raises).  Must install BEFORE the runtime modules below are imported so
# their module- and instance-level locks are minted as recording proxies.
if _os.environ.get("RAY_TPU_LOCKCHECK", "0") not in ("", "0"):
    from ray_tpu.devtools import lockcheck as _lockcheck

    _lockcheck.install_from_env()

from ray_tpu._private.api import (
    available_resources,
    cancel,
    cluster_resources,
    get,
    init,
    is_initialized,
    kill,
    nodes,
    put,
    shutdown,
    wait,
)
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu.actor import ActorClass, ActorHandle, get_actor, method
from ray_tpu.remote_function import RemoteFunction, remote_decorator
from ray_tpu.runtime_context import get_runtime_context
from ray_tpu.util.tracing import timeline  # noqa: F401 (public API)
from ray_tpu import exceptions

__version__ = "0.1.0"


def remote(*args, **kwargs):
    """``@remote`` / ``@remote(num_cpus=..., num_tpus=...)`` decorator
    (reference: python/ray/_private/worker.py remote)."""
    if len(args) == 1 and not kwargs and callable(args[0]):
        return remote_decorator(None)(args[0])
    if args:
        raise TypeError("@remote options must be keyword arguments")
    return remote_decorator(kwargs)


__all__ = [
    "init", "shutdown", "is_initialized", "remote", "get", "put", "wait",
    "kill", "cancel", "get_actor", "method", "nodes", "cluster_resources",
    "available_resources", "ObjectRef", "ActorClass", "ActorHandle",
    "RemoteFunction", "get_runtime_context", "timeline", "exceptions",
    "__version__",
]
