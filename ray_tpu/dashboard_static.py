"""Dashboard frontend: a single self-contained HTML page over the REST
API (reference: ``dashboard/client/`` — a React SPA; here a build-free
vanilla-JS page polling the same endpoints, so the dashboard has a human
UI without a node/webpack toolchain in the image)."""

INDEX_HTML = """<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8">
<title>ray_tpu dashboard</title>
<style>
  body { font-family: -apple-system, 'Segoe UI', Roboto, sans-serif;
         margin: 0; background: #f6f7f9; color: #1a2029; }
  header { background: #1a2029; color: #fff; padding: 10px 20px;
           display: flex; align-items: baseline; gap: 16px; }
  header h1 { font-size: 16px; margin: 0; }
  header .sess { color: #9aa4b2; font-size: 12px; }
  main { padding: 16px 20px; display: grid; gap: 16px;
         grid-template-columns: repeat(auto-fit, minmax(420px, 1fr)); }
  section { background: #fff; border-radius: 8px; padding: 12px 16px;
            box-shadow: 0 1px 3px rgba(16,24,40,.1); }
  h2 { font-size: 13px; text-transform: uppercase; letter-spacing: .06em;
       color: #5b6575; margin: 0 0 8px; }
  table { border-collapse: collapse; width: 100%; font-size: 12.5px; }
  th, td { text-align: left; padding: 4px 8px;
           border-bottom: 1px solid #eef0f3; white-space: nowrap; }
  th { color: #5b6575; font-weight: 600; }
  .num { text-align: right; font-variant-numeric: tabular-nums; }
  .ok { color: #127a46; } .bad { color: #b3261e; }
  .pill { display: inline-block; padding: 1px 8px; border-radius: 10px;
          background: #eef0f3; font-size: 11.5px; }
  .bar { height: 8px; background: #eef0f3; border-radius: 4px;
         overflow: hidden; min-width: 120px; }
  .bar > div { height: 100%; background: #3565d9; }
  footer { color: #9aa4b2; font-size: 11px; padding: 8px 20px; }
</style>
</head>
<body>
<header>
  <h1>ray_tpu</h1>
  <span class="sess" id="session"></span>
  <span class="sess" id="updated"></span>
</header>
<main>
  <section><h2>Cluster</h2><div id="cluster"></div></section>
  <section><h2>Nodes</h2><div id="nodes"></div></section>
  <section><h2>Task summary</h2><div id="summary"></div></section>
  <section><h2>Actors</h2><div id="actors"></div></section>
  <section><h2>Jobs</h2><div id="jobs"></div></section>
  <section><h2>Head handler latency</h2><div id="handlers"></div></section>
</main>
<footer>
  raw JSON: <a href="/api/cluster">/api/cluster</a>,
  <a href="/api/nodes">/api/nodes</a>, <a href="/api/tasks">/api/tasks</a>,
  <a href="/api/actors">/api/actors</a>, <a href="/api/jobs">/api/jobs</a>,
  <a href="/api/metrics">/api/metrics</a>,
  <a href="/api/handler_stats">/api/handler_stats</a>,
  <a href="/api/timeline">/api/timeline</a> (open in Perfetto)
</footer>
<script>
const $ = id => document.getElementById(id);
const esc = s => String(s).replace(/[&<>"]/g,
  c => ({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;'}[c]));
function table(rows, cols) {
  if (!rows.length) return '<span class="pill">none</span>';
  let h = '<table><tr>' + cols.map(c =>
      `<th${c.num ? ' class="num"' : ''}>${esc(c.name)}</th>`).join('')
      + '</tr>';
  for (const r of rows)
    h += '<tr>' + cols.map(c => `<td class="${c.num ? 'num' : ''}">`
        + c.fmt(r) + '</td>').join('') + '</tr>';
  return h + '</table>';
}
function bar(frac) {
  const pct = Math.round(Math.min(1, Math.max(0, frac)) * 100);
  return `<div class="bar"><div style="width:${pct}%"></div></div>`;
}
async function j(path) { return (await fetch(path)).json(); }
async function refresh() {
  try {
    const [cluster, nodes, summary, actors, jobs, handlers] =
      await Promise.all([j('/api/cluster'), j('/api/nodes'),
                         j('/api/summary'), j('/api/actors'),
                         j('/api/jobs'), j('/api/handler_stats')]);
    $('session').textContent = 'session ' + cluster.session_id;
    $('updated').textContent = 'updated ' +
        new Date().toLocaleTimeString();
    const res = cluster.resources || {}, avail = cluster.available || {};
    $('cluster').innerHTML = table(Object.keys(res).map(k => ({
        k, total: res[k], avail: avail[k] ?? 0})), [
      {name: 'resource', fmt: r => esc(r.k)},
      {name: 'available', num: true,
       fmt: r => esc(r.avail) + ' / ' + esc(r.total)},
      {name: 'used', fmt: r =>
          bar(r.total ? (r.total - r.avail) / r.total : 0)},
    ]);
    $('nodes').innerHTML = table(nodes, [
      {name: 'node', fmt: r => esc(r.node_id.slice(0, 12))},
      {name: 'state', fmt: r => r.alive
          ? '<span class="ok">ALIVE</span>'
          : '<span class="bad">DEAD</span>'},
      {name: 'CPU', num: true, fmt: r =>
          esc((r.available.CPU ?? 0) + ' / ' + (r.resources.CPU ?? 0))},
      {name: 'TPU', num: true, fmt: r =>
          esc((r.available.TPU ?? '-') + ' / ' + (r.resources.TPU ?? '-'))},
    ]);
    $('summary').innerHTML = table(
      Object.entries(summary).sort().map(([k, v]) => ({k, v})), [
        {name: 'task : state', fmt: r => esc(r.k)},
        {name: 'count', num: true, fmt: r => esc(r.v)},
      ]);
    $('actors').innerHTML = table(actors.slice(0, 50), [
      {name: 'actor', fmt: r => esc(r.actor_id.slice(0, 12))},
      {name: 'name', fmt: r => esc(r.name || '-')},
      {name: 'state', fmt: r => r.state === 'ALIVE'
          ? '<span class="ok">ALIVE</span>'
          : `<span class="pill">${esc(r.state)}</span>`},
      {name: 'pending', num: true, fmt: r => esc(r.pending_tasks)},
    ]);
    $('jobs').innerHTML = table(jobs, [
      {name: 'job', fmt: r => esc(r.job_id)},
      {name: 'status', fmt: r => esc(r.status)},
      {name: 'entrypoint', fmt: r => esc(
          (r.entrypoint || '').slice(0, 48))},
    ]);
    $('handlers').innerHTML = table(handlers.slice(0, 12), [
      {name: 'handler', fmt: r => esc(r.handler)},
      {name: 'count', num: true, fmt: r => esc(r.count)},
      {name: 'mean µs', num: true, fmt: r => esc(r.mean_us)},
      {name: 'max ms', num: true, fmt: r => esc(r.max_ms)},
    ]);
  } catch (e) {
    $('updated').textContent = 'update failed: ' + e;
  }
}
refresh();
setInterval(refresh, 2000);
</script>
</body>
</html>
"""
