"""Dashboard: HTTP observability over the runtime's state tables.

Reference: ``dashboard/`` (aiohttp REST head with per-module routes —
nodes/actors/jobs/state/metrics — backing the React UI).  Condensed to
the REST surface (the part tools consume): JSON endpoints over the state
API, user metrics, job manager, and a minimal HTML index for humans.

    GET /api/nodes | /api/actors | /api/tasks | /api/objects
        /api/workers | /api/placement_groups
    GET /api/summary          task-name x state counts
    GET /api/metrics          user Counter/Gauge/Histogram snapshot
    GET /api/jobs             submitted jobs
    GET /api/cluster          resources + availability
"""

from __future__ import annotations

import asyncio
import threading
from typing import Any, Dict, Optional

from ray_tpu._private import api_internal

_state: Dict[str, Any] = {"server": None}


def start_dashboard(host: str = "127.0.0.1", port: int = 8265) -> str:
    """Serve the dashboard from a driver thread; returns the URL
    (reference default port 8265)."""
    from aiohttp import web

    rt = api_internal.require_runtime()

    async def api_state(request: web.Request):
        kind = request.match_info["kind"]
        try:
            return web.json_response(rt.state_query(kind))
        except ValueError as e:
            return web.json_response({"error": str(e)}, status=404)

    async def api_summary(request):
        from ray_tpu.util.state import summarize_tasks

        return web.json_response(summarize_tasks())

    async def api_metrics(request):
        from ray_tpu.util import metrics

        return web.json_response(metrics.snapshot())

    async def api_jobs(request):
        from ray_tpu.job_submission import _get_manager

        return web.json_response(_get_manager(rt).list())

    async def api_cluster(request):
        return web.json_response({
            "resources": rt.cluster_resources(),
            "available": rt.available_resources(),
            "session_id": rt.session_id,
        })

    async def api_timeline(request):
        from ray_tpu.util.tracing import chrome_trace

        return web.json_response(chrome_trace(rt.state_query("spans")))

    async def index(request):
        # Build-free SPA over the REST endpoints (reference:
        # dashboard/client React app; see dashboard_static.py).
        from ray_tpu.dashboard_static import INDEX_HTML

        return web.Response(text=INDEX_HTML, content_type="text/html")

    app = web.Application()
    app.router.add_get("/", index)
    app.router.add_get("/api/summary", api_summary)
    app.router.add_get("/api/metrics", api_metrics)
    app.router.add_get("/api/jobs", api_jobs)
    app.router.add_get("/api/cluster", api_cluster)
    app.router.add_get("/api/timeline", api_timeline)
    app.router.add_get("/api/{kind}", api_state)

    runner = web.AppRunner(app)
    ready = threading.Event()
    holder: Dict[str, Any] = {}

    def serve_thread():
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, host, port)
        loop.run_until_complete(site.start())
        holder["loop"] = loop
        ready.set()
        loop.run_forever()

    t = threading.Thread(target=serve_thread, daemon=True,
                         name="ray_tpu-dashboard")
    t.start()
    if not ready.wait(10):
        raise RuntimeError("dashboard failed to start")
    _state["server"] = (t, runner, holder)
    return f"http://{host}:{port}"


def stop_dashboard():
    server = _state.get("server")
    if server:
        try:
            server[2]["loop"].call_soon_threadsafe(server[2]["loop"].stop)
        except Exception:
            pass
        _state["server"] = None
