"""Lazy task/actor DAGs (reference: ``python/ray/dag/dag_node.py:23``).

``fn.bind(*args)`` builds a graph instead of executing; ``.execute(input)``
walks it, submitting each node exactly once per execution with upstream
ObjectRefs as arguments — so the whole DAG is in flight at once and the
runtime's dependency tracking provides the ordering (the reference's
FunctionNode/ClassNode/InputNode surface, minus compiled-graph channels
which this snapshot's reference also lacks).
"""

from ray_tpu.dag.node import (
    ClassMethodNode,
    ClassNode,
    DAGNode,
    FunctionNode,
    InputNode,
)

__all__ = ["DAGNode", "FunctionNode", "InputNode", "ClassNode",
           "ClassMethodNode"]
