"""DAG node types.

Reference: ``python/ray/dag/dag_node.py:23`` (DAGNode base + bound
args/options), ``function_node.py``, ``class_node.py``, ``input_node.py``.
Execution semantics match: a node executes once per ``execute()`` call;
upstream results flow as ObjectRefs so the scheduler sees the real
dependency graph and runs independent branches in parallel.
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, List, Optional, Tuple


class DAGNode:
    def __init__(self, args: Tuple[Any, ...] = (),
                 kwargs: Optional[Dict[str, Any]] = None):
        self._bound_args = tuple(args)
        self._bound_kwargs = dict(kwargs or {})
        # Stable across copies/pickles — workflow storage keys step results
        # by it (reference: _stable_uuid, dag_node.py).
        self._stable_uuid = uuid.uuid4().hex

    # -- traversal ---------------------------------------------------------
    def _children(self) -> List["DAGNode"]:
        out = []
        for a in list(self._bound_args) + list(self._bound_kwargs.values()):
            if isinstance(a, DAGNode):
                out.append(a)
        return out

    def topo_order(self) -> List["DAGNode"]:
        """Children-first order (every node once)."""
        seen: Dict[str, DAGNode] = {}
        order: List[DAGNode] = []

        def visit(n: DAGNode):
            if n._stable_uuid in seen:
                return
            seen[n._stable_uuid] = n
            for c in n._children():
                visit(c)
            order.append(n)

        visit(self)
        return order

    # -- execution ---------------------------------------------------------
    def execute(self, *input_args, _memo: Optional[dict] = None, **input_kw):
        """Run the whole DAG; returns this node's result handle
        (ObjectRef for function/method nodes, actor handle for ClassNode).
        """
        memo = _memo if _memo is not None else {}
        for node in self.topo_order():
            if node._stable_uuid not in memo:
                memo[node._stable_uuid] = node._execute_impl(
                    memo, input_args, input_kw)
        return memo[self._stable_uuid]

    def _resolve(self, memo, input_args, input_kw):
        def one(a):
            return memo[a._stable_uuid] if isinstance(a, DAGNode) else a

        args = [one(a) for a in self._bound_args]
        kwargs = {k: one(v) for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def _execute_impl(self, memo, input_args, input_kw):
        raise NotImplementedError


class InputNode(DAGNode):
    """Runtime-input placeholder (reference: input_node.py); supports use
    as a context manager for parity with the reference idiom::

        with InputNode() as inp:
            dag = f.bind(inp)
        dag.execute(5)
    """

    def __init__(self):
        super().__init__()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _execute_impl(self, memo, input_args, input_kw):
        if not input_args:
            raise ValueError("DAG has an InputNode: execute(...) needs an "
                             "input argument")
        return input_args[0]


class FunctionNode(DAGNode):
    def __init__(self, remote_fn, args, kwargs):
        super().__init__(args, kwargs)
        self._fn = remote_fn

    def _execute_impl(self, memo, input_args, input_kw):
        args, kwargs = self._resolve(memo, input_args, input_kw)
        return self._fn.remote(*args, **kwargs)


class ClassNode(DAGNode):
    """Actor construction node: executing it instantiates the actor; its
    handle memoizes for downstream ClassMethodNodes."""

    def __init__(self, actor_cls, args, kwargs):
        super().__init__(args, kwargs)
        self._cls = actor_cls

    def _execute_impl(self, memo, input_args, input_kw):
        args, kwargs = self._resolve(memo, input_args, input_kw)
        return self._cls.remote(*args, **kwargs)

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        return _MethodBinder(self, item)


class _MethodBinder:
    def __init__(self, class_node: ClassNode, method: str):
        self._class_node = class_node
        self._method = method

    def bind(self, *args, **kwargs) -> "ClassMethodNode":
        return ClassMethodNode(self._class_node, self._method, args, kwargs)


class ClassMethodNode(DAGNode):
    def __init__(self, class_node: ClassNode, method: str, args, kwargs):
        super().__init__(args, kwargs)
        self._class_node = class_node
        self._method = method

    def _children(self):
        return super()._children() + [self._class_node]

    def _execute_impl(self, memo, input_args, input_kw):
        handle = memo[self._class_node._stable_uuid]
        args, kwargs = self._resolve(memo, input_args, input_kw)
        return getattr(handle, self._method).remote(*args, **kwargs)
