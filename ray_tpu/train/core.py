"""Sharded train-step construction: params + optimizer on a mesh.

The reference's analog is the torch training loop the user writes inside
``train_loop_per_worker`` plus DDP wrapping (``prepare_model``,
``python/ray/train/torch/train_loop_utils.py:75``).  Here the framework owns
the step: loss -> grad -> optax update, jitted once over the global mesh;
XLA inserts the gradient psum (dp), reduce-scatter/all-gather (fsdp), and
layer collectives (tp/sp/ep) from the sharding annotations.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from ray_tpu.models.llama import (
    LlamaConfig, forward_pipelined, init_params, loss_fn, param_logical_axes,
)
from ray_tpu.parallel.mesh import AXIS_DP, AXIS_FSDP, AXIS_PP
from ray_tpu.parallel.sharding import (
    LogicalAxisRules, named_sharding, shard_pytree,
)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    step: jax.Array
    params: Any
    opt_state: Any


def init_train_state(key: jax.Array, cfg: LlamaConfig,
                     optimizer: optax.GradientTransformation,
                     mesh=None,
                     rules: Optional[LogicalAxisRules] = None) -> TrainState:
    """Init params (host) and optimizer state, sharded onto ``mesh``.

    Optimizer state leaves mirror param leaves (adam mu/nu), so they inherit
    the matching param sharding; scalar leaves replicate.
    """
    params = init_params(key, cfg)
    if mesh is not None:
        params = shard_pytree(params, param_logical_axes(cfg), mesh, rules)
    opt_state = jax.jit(optimizer.init)(params) if mesh is not None \
        else optimizer.init(params)
    return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                      opt_state=opt_state)


def make_train_step(cfg: LlamaConfig,
                    optimizer: optax.GradientTransformation, *,
                    mesh=None, rules: Optional[LogicalAxisRules] = None,
                    pipelined: bool = False,
                    num_microbatches: int = 1,
                    donate: bool = True
                    ) -> Callable[[TrainState, Dict[str, jax.Array]],
                                  Tuple[TrainState, Dict[str, jax.Array]]]:
    """Build the jitted train step.  Batch: {"tokens": (b, s+1) int32}."""

    def compute_loss(params, batch):
        forward_fn = None
        if pipelined:
            forward_fn = lambda p, t: forward_pipelined(
                p, t, cfg, mesh=mesh, num_microbatches=num_microbatches,
                rules=rules)
        return loss_fn(params, batch, cfg, mesh=mesh, rules=rules,
                       forward_fn=forward_fn)

    def step(state: TrainState, batch) -> Tuple[TrainState, Dict]:
        (_, metrics), grads = jax.value_and_grad(
            compute_loss, has_aux=True)(state.params, batch)
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
        params = optax.apply_updates(state.params, updates)
        metrics = dict(metrics,
                       grad_norm=optax.global_norm(grads).astype(jnp.float32))
        return TrainState(step=state.step + 1, params=params,
                          opt_state=opt_state), metrics

    donate_argnums = (0,) if donate else ()
    return jax.jit(step, donate_argnums=donate_argnums)


def default_optimizer(lr: float = 3e-4, weight_decay: float = 0.1,
                      warmup: int = 100, decay_steps: int = 10000,
                      grad_clip: float = 1.0) -> optax.GradientTransformation:
    """AdamW + cosine schedule + clipping — the standard LLM recipe."""
    sched = optax.warmup_cosine_decay_schedule(
        0.0, lr, warmup, max(decay_steps, warmup + 1))
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(sched, b1=0.9, b2=0.95, weight_decay=weight_decay),
    )
