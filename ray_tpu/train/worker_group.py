"""WorkerGroup: a gang of training-worker actors.

Reference: ``python/ray/train/_internal/worker_group.py:92`` — N actors
created from one ``RayTrainWorker`` class, ``execute``/``execute_async``
running a function on every worker.  TPU difference: each worker owns
``tpu_chips_per_worker`` chips (the scheduler pins ``TPU_VISIBLE_CHIPS``
before the worker's first jax import), so a worker is "one JAX process on
one TPU host" and in-worker collectives ride ICI.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import ray_tpu as ray
from ray_tpu.remote_function import _bulk_submit
from ray_tpu.util.placement_group import PlacementGroup


@ray.remote
class TrainWorker:
    """Reference: RayTrainWorker (worker_group.py:40)."""

    def __init__(self, metadata: Dict[str, Any]):
        self._metadata = metadata
        self._env: Dict[str, str] = {}

    def set_env(self, env: Dict[str, str]):
        import os
        self._env.update(env)
        os.environ.update(env)
        return True

    def get_metadata(self):
        import os
        import socket
        return {
            "hostname": socket.gethostname(),
            "pid": os.getpid(),
            "tpu_chips": os.environ.get("TPU_VISIBLE_CHIPS", ""),
        }

    def execute(self, fn: Callable, *args, **kwargs):
        return fn(*args, **kwargs)

    def run_train_fn(self, train_fn: Callable, config: Dict[str, Any],
                     session_kwargs: Dict[str, Any]):
        """Run the user loop under an active air session; return the
        session's reports + checkpoints (driver-side aggregation)."""
        from ray_tpu.air.session import _TrainSession, _set_session
        sess = _TrainSession(**session_kwargs)
        _set_session(sess)
        try:
            train_fn(config)
        finally:
            _set_session(None)
        ckpt_blobs = [c.to_bytes() for c in sess.checkpoints]
        return {"reports": sess.reports, "checkpoints": ckpt_blobs}


class WorkerGroup:
    def __init__(self, num_workers: int,
                 resources_per_worker: Dict[str, float],
                 placement_group: Optional[PlacementGroup] = None):
        self.num_workers = num_workers
        self._workers = []
        for i in range(num_workers):
            opts = {"resources": dict(resources_per_worker)}
            cpu = opts["resources"].pop("CPU", 1.0)
            tpu = opts["resources"].pop("TPU", 0.0)
            kw = {"num_cpus": cpu, "num_tpus": int(tpu),
                  "resources": opts["resources"] or None}
            if placement_group is not None:
                from ray_tpu.util.scheduling_strategies import (
                    PlacementGroupSchedulingStrategy,
                )
                kw["scheduling_strategy"] = PlacementGroupSchedulingStrategy(
                    placement_group=placement_group,
                    placement_group_bundle_index=i)
            self._workers.append(
                TrainWorker.options(**kw).remote({"rank": i}))

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        return ray.get(self.execute_async(fn, *args, **kwargs))

    def execute_async(self, fn: Callable, *args, **kwargs):
        # Bulk path: one runtime submission for the whole worker group.
        return _bulk_submit([(w.execute, (fn,) + args, kwargs)
                             for w in self._workers])

    def execute_single(self, index: int, fn: Callable, *args, **kwargs):
        return ray.get(self._workers[index].execute.remote(fn, *args,
                                                           **kwargs))

    @property
    def workers(self):
        return list(self._workers)

    def shutdown(self):
        for w in self._workers:
            try:
                ray.kill(w)
            except Exception:
                pass
        self._workers = []
