"""BackendExecutor: placement group + worker gang + backend rendezvous.

Reference: ``python/ray/train/_internal/backend_executor.py:43`` —
``start`` (:94) creates the placement group (:147) and WorkerGroup, sets
rank/world env vars (:255), and runs the framework backend's ``on_start``;
``start_training`` (:325) launches the user loop on every worker.
TPU difference vs ``_share_cuda_visible_devices`` (:205): chip visibility is
pinned by the scheduler at worker spawn (TPU_VISIBLE_CHIPS), not shared
post-hoc — a JAX process must see its chips before first import.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import ray_tpu as ray
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import ScalingConfig
from ray_tpu.train.backend import Backend, JaxConfig
from ray_tpu.train.worker_group import WorkerGroup
from ray_tpu.util.placement_group import placement_group, remove_placement_group


class TrainingFailedError(RuntimeError):
    pass


class BackendExecutor:
    def __init__(self, backend_config: Optional[JaxConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None):
        self._backend_config = backend_config or JaxConfig()
        self._scaling = scaling_config or ScalingConfig()
        self._backend: Backend = self._backend_config.backend_cls()
        self._worker_group: Optional[WorkerGroup] = None
        self._pg = None
        self.streamed_reports = []
        self.latest_checkpoint: Optional[Checkpoint] = None

    def start(self):
        sc = self._scaling
        bundles = [sc.worker_resources() for _ in range(sc.num_workers)]
        self._pg = placement_group(bundles, strategy=sc.placement_strategy)
        ray.get(self._pg.ready(), timeout=60)
        self._worker_group = WorkerGroup(
            sc.num_workers, sc.worker_resources(), placement_group=self._pg)
        # rank/world env (reference: backend_executor.py:255)
        futs = []
        for rank, w in enumerate(self._worker_group.workers):
            futs.append(w.set_env.remote({
                "RANK": str(rank),
                "WORLD_RANK": str(rank),
                "WORLD_SIZE": str(sc.num_workers),
                "LOCAL_RANK": "0",
            }))
        ray.get(futs)
        self._backend.on_start(self._worker_group, self._backend_config)

    @property
    def worker_group(self) -> WorkerGroup:
        if self._worker_group is None:
            raise RuntimeError("BackendExecutor not started")
        return self._worker_group

    def run_training(self, train_fn: Callable[[Dict[str, Any]], None],
                     config: Dict[str, Any],
                     checkpoint: Optional[Checkpoint] = None
                     ) -> List[Dict[str, Any]]:
        """Run the loop on every worker; block; return per-rank session
        payloads (reports + checkpoint bytes).  While blocked, drains the
        workers\' report stream so ``latest_checkpoint``/``streamed_reports``
        survive a mid-run worker death (reference: session result queue +
        get_next_results, backend_executor.py:426)."""
        import pickle
        import uuid

        wg = self.worker_group
        topic = f"train-{uuid.uuid4().hex[:12]}"
        self._topic = topic
        ckpt = checkpoint.to_bytes() if checkpoint is not None else None
        futs = []
        for rank, w in enumerate(wg.workers):
            session_kwargs = {
                "world_rank": rank,
                "world_size": wg.num_workers,
                "local_rank": 0,
                "checkpoint": Checkpoint.from_bytes(ckpt) if ckpt else None,
                "stream_topic": topic,
            }
            futs.append(w.run_train_fn.remote(train_fn, config,
                                              session_kwargs))
        from ray_tpu._private.api_internal import require_runtime
        rt = require_runtime()
        pending = list(futs)
        try:
            while pending:
                _, pending = ray.wait(pending, num_returns=len(pending),
                                      timeout=0.25)
                self._drain_stream(rt, topic, pickle)
            self._drain_stream(rt, topic, pickle)
            return ray.get(futs)
        except Exception as e:
            self._drain_stream(rt, topic, pickle)
            raise TrainingFailedError(str(e)) from e

    def _drain_stream(self, rt, topic: str, pickle):
        for raw in rt.poll_events(topic):
            try:
                ev = pickle.loads(raw)
            except Exception:
                continue
            self.streamed_reports.append(ev)
            if ev.get("checkpoint") and ev.get("rank") == 0:
                self.latest_checkpoint = Checkpoint.from_bytes(
                    ev["checkpoint"])

    def shutdown(self):
        if self._worker_group is not None:
            try:
                self._backend.on_shutdown(self._worker_group,
                                          self._backend_config)
            finally:
                self._worker_group.shutdown()
                self._worker_group = None
        if self._pg is not None:
            try:
                remove_placement_group(self._pg)
            except Exception:
                pass
            self._pg = None
