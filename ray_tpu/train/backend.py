"""Collective backends: how a worker gang becomes one SPMD program.

Reference seam: ``python/ray/train/torch/config.py:148`` — ``_TorchBackend
.on_start`` runs ``dist.init_process_group('nccl', tcp://rank0)`` on every
worker (SURVEY.md §2.3 calls this "the exact seam the TPU build replaces").

Here the backend is JAX: rank 0 publishes a coordinator address; every
worker calls ``jax.distributed.initialize(coordinator, n, rank)`` and the
global device mesh spans all workers' chips — collectives are XLA over
ICI (in-host) / DCN (cross-host), no NCCL-style library in sight.
"""

from __future__ import annotations

import socket
from typing import Any, Dict, Optional


class Backend:
    """Plugin interface (reference: train/backend.py BackendConfig/Backend)."""

    def on_start(self, worker_group, backend_config) -> None:
        pass

    def on_shutdown(self, worker_group, backend_config) -> None:
        pass


class JaxConfig:
    """Backend config for JAX SPMD training.

    distributed=False runs each worker as an independent JAX process (unit
    tests, single worker); True wires jax.distributed across the gang.
    """

    def __init__(self, distributed: Optional[bool] = None,
                 coordinator_port: int = 0):
        self.distributed = distributed
        self.coordinator_port = coordinator_port

    @property
    def backend_cls(self):
        return _JaxBackend


def _free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _init_jax_distributed(coordinator: str, num_processes: int,
                          process_id: int):
    import jax
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id)
    return {"process_index": jax.process_index(),
            "device_count": jax.device_count(),
            "local_device_count": jax.local_device_count()}


class _JaxBackend(Backend):
    """Reference analog: _TorchBackend (train/torch/config.py:103)."""

    def on_start(self, worker_group, backend_config: JaxConfig):
        n = worker_group.num_workers
        distributed = backend_config.distributed
        if distributed is None:
            distributed = n > 1
        if not distributed:
            return
        # Rank 0's host runs the coordination service, so hostname AND a
        # free port must both be probed on rank 0's machine (reference: TCP
        # rendezvous on rank-0, train/torch/config.py:113).
        fixed = backend_config.coordinator_port

        def _rendezvous_addr():
            import socket as s
            host = s.gethostname()
            if fixed:
                return f"{host}:{fixed}"
            sock = s.socket()
            sock.bind(("", 0))
            port = sock.getsockname()[1]
            sock.close()
            return f"{host}:{port}"

        coordinator = worker_group.execute_single(0, _rendezvous_addr)
        import ray_tpu as ray
        futs = [
            w.execute.remote(_init_jax_distributed, coordinator, n, rank)
            for rank, w in enumerate(worker_group.workers)
        ]
        infos = ray.get(futs, timeout=120)
        counts = {i["device_count"] for i in infos}
        if len(counts) != 1:
            raise RuntimeError(f"inconsistent global device counts: {infos}")

    def on_shutdown(self, worker_group, backend_config):
        def _shutdown():
            try:
                import jax
                jax.distributed.shutdown()
            except Exception:
                pass
            return True
        try:
            worker_group.execute(_shutdown)
        except Exception:
            pass
