"""Trainers: the user-facing fit() entry points.

Reference: ``python/ray/train/base_trainer.py:52`` (``fit`` :538) and
``data_parallel_trainer.py:56``.  The reference wraps every trainer into a
Tune Trainable (:663) so fit == a single Tune trial; here fit() drives the
BackendExecutor directly and the Tune layer (ray_tpu.tune) wraps trainers
the same way via ``as_trainable`` for HPO.

``JaxTrainer`` is the TorchTrainer-equivalent: SPMD data-parallel training
where each worker is one JAX process owning its TPU chips, the collective
backend is jax.distributed + XLA (train/backend.py), and the in-worker
step is a pjit-ed mesh program (train/core.py).

Fault tolerance matches the reference (``FailureConfig(max_failures)``,
``backend_executor.py:522,583``): on worker failure the whole gang is torn
down and restarted from the latest reported checkpoint — elastic restart,
slice-granular, which is the only sane recovery unit on TPU (a chip failure
kills the slice; SURVEY.md §7 hard-part 5).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import FailureConfig, RunConfig, ScalingConfig
from ray_tpu.air.result import Result
from ray_tpu.train.backend import JaxConfig
from ray_tpu.train.backend_executor import BackendExecutor, TrainingFailedError


class BaseTrainer:
    def __init__(self, *, scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.resume_from_checkpoint = resume_from_checkpoint

    def fit(self) -> Result:
        raise NotImplementedError

    def as_trainable(self):
        """Wrap into a Tune trainable (reference: base_trainer.py:663)."""
        trainer = self

        def train_func(config):
            t = trainer._with_config_overrides(config)
            result = t.fit()
            return result.metrics

        return train_func

    def _with_config_overrides(self, config: Dict[str, Any]):
        return self


class DataParallelTrainer(BaseTrainer):
    """Reference: python/ray/train/data_parallel_trainer.py:56."""

    def __init__(self, train_loop_per_worker: Callable[[Dict[str, Any]], None],
                 *, train_loop_config: Optional[Dict[str, Any]] = None,
                 backend_config: Optional[JaxConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        super().__init__(scaling_config=scaling_config,
                         run_config=run_config,
                         resume_from_checkpoint=resume_from_checkpoint)
        self._train_fn = train_loop_per_worker
        self._train_config = train_loop_config or {}
        self._backend_config = backend_config or JaxConfig()
        self._datasets = datasets or {}

    def fit(self) -> Result:
        failure = self.run_config.failure_config or FailureConfig()
        retries = failure.max_failures
        checkpoint = self.resume_from_checkpoint
        last_error: Optional[BaseException] = None
        while True:
            executor = BackendExecutor(self._backend_config,
                                       self.scaling_config)
            try:
                executor.start()
                config = dict(self._train_config)
                if self._datasets:
                    config["__datasets__"] = {
                        k: _shard_dataset(d, self.scaling_config.num_workers)
                        for k, d in self._datasets.items()}
                payloads = executor.run_training(self._train_fn, config,
                                                 checkpoint)
                return _payloads_to_result(payloads)
            except TrainingFailedError as e:
                last_error = e
                # Group restart from the latest checkpoint streamed before
                # the death (reference: backend_executor.py:522
                # get_with_failure_handling + the session result queue).
                if executor.latest_checkpoint is not None:
                    checkpoint = executor.latest_checkpoint
                if retries == 0:
                    return Result(metrics={}, checkpoint=checkpoint,
                                  error=e)
                if retries > 0:
                    retries -= 1
            finally:
                executor.shutdown()


def _shard_dataset(dataset, num_shards: int):
    if hasattr(dataset, "split"):
        return dataset.split(num_shards)
    return [dataset] * num_shards


def _payloads_to_result(payloads) -> Result:
    rank0 = payloads[0]
    reports = rank0["reports"]
    ckpt = None
    if rank0["checkpoints"]:
        ckpt = Checkpoint.from_bytes(rank0["checkpoints"][-1])
    metrics = reports[-1] if reports else {}
    return Result(metrics=metrics, checkpoint=ckpt,
                  metrics_history=reports)


class JaxTrainer(DataParallelTrainer):
    """The TorchTrainer-equivalent for TPU (reference seam:
    python/ray/train/torch/torch_trainer.py + torch/config.py:29).

    The collective plane is jax.distributed/XLA — there is nothing like
    ``prepare_model`` to wrap: the user loop builds a mesh over the global
    devices (``jax.devices()`` spans the gang after rendezvous) and jits a
    sharded step; see ray_tpu.train.core.make_train_step.
    """
