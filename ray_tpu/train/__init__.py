"""ray_tpu.train — distributed training orchestration (Ray Train equivalent).

Reference: ``python/ray/train/`` (SURVEY.md §2.3) — BaseTrainer/
DataParallelTrainer/BackendExecutor/WorkerGroup, with per-framework collective
backends (``train/torch/config.py:148`` starts NCCL process groups).  The TPU
build replaces that seam with JAX: the "backend" is a mesh + sharded
train step; gradient traffic is XLA collectives over ICI, never an external
library.
"""

from ray_tpu.train.core import (
    TrainState,
    default_optimizer,
    init_train_state,
    make_train_step,
)
from ray_tpu.train.backend import Backend, JaxConfig
from ray_tpu.train.backend_executor import BackendExecutor, TrainingFailedError
from ray_tpu.train.trainer import (
    BaseTrainer,
    DataParallelTrainer,
    JaxTrainer,
)
from ray_tpu.train.worker_group import WorkerGroup
from ray_tpu.train.pipeline_actors import PipelineStage, PipelineTrainer

__all__ = [
    "TrainState", "init_train_state", "make_train_step", "default_optimizer",
    "Backend", "JaxConfig", "BackendExecutor", "TrainingFailedError",
    "BaseTrainer", "DataParallelTrainer", "JaxTrainer", "WorkerGroup",
    "PipelineStage", "PipelineTrainer",
]
