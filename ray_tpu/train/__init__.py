"""ray_tpu.train — distributed training orchestration (Ray Train equivalent).

Reference: ``python/ray/train/`` (SURVEY.md §2.3) — BaseTrainer/
DataParallelTrainer/BackendExecutor/WorkerGroup, with per-framework collective
backends (``train/torch/config.py:148`` starts NCCL process groups).  The TPU
build replaces that seam with JAX: the "backend" is a mesh + sharded
train step; gradient traffic is XLA collectives over ICI, never an external
library.
"""

from ray_tpu.train.core import (
    TrainState,
    init_train_state,
    make_train_step,
)

__all__ = ["TrainState", "init_train_state", "make_train_step"]
