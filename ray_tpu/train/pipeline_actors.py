"""Distributed pipeline-parallel training: 1F1B stage actors over the
striped data plane.

Reference: PipeDream (SOSP'19) one-forward-one-backward scheduling and
GPipe (NeurIPS'19) micro-batching.  ``parallel/pipeline.py`` runs the
GPipe schedule INSIDE one XLA program (``lax.ppermute`` over the 'pp'
mesh axis of a single host) and documents its fill/drain bubble as
"acceptable at microbatches >> pp, 1F1B is a later optimization" — this
module is that step, taken across PROCESSES: each pipeline stage is a
long-lived restartable actor owning its stage's params on its own
devices, and the 1F1B schedule is driven by the actor call pipeline
itself.

- **Data plane**: micro-batch activations (forward) and activation
  gradients (backward) move stage-to-stage as segment images pushed
  over the PR 7 direct-put verbs (``reserve_put``/``put_range``/
  ``commit_put`` — ``ObjectPusher.push`` stripes one), exactly the
  shuffle engine's partition-push shape.  Only a tiny descriptor
  ``("__mbdescr__", kind, ident, total, home_store)`` rides the actor
  call result; no activation payload ever crosses a head message.  A
  push to one's OWN store short-circuits through ``shm_store.put_local``
  and a failed/stalled push HEDGES into the pusher's store (the consumer
  then pulls over the data plane) — one gray link never kills training.
- **Schedule**: the driver submits each stage's 1F1B call sequence
  (warmup ``min(pp-1-s, M)`` forwards, steady-state one-forward-one-
  backward, cooldown backwards) without ever blocking; per-actor FIFO
  execution realizes the schedule and at most ``pp`` activation stashes
  are live per stage.  Dependencies are carried by passing the upstream
  call's result ref (the descriptor) as the downstream call's argument,
  so arg prefetch + the per-lease pipeline bound overlap the transfer
  of micro-batch t+1 with the compute of t for free.
- **Fault story**: stages are ``max_restarts``/``max_task_retries``
  actors with PR 9 ``__ray_save__``/``__ray_restore__`` hooks — params,
  optimizer state, gradient accumulators, and the activation stash all
  checkpoint, and checkpoints always capture step-boundary params
  (params change only inside ``apply_grads``).  A killed mid-pipeline
  stage restores and the head replays its in-flight calls; a replay
  that cannot complete (its input segment was already consumed) raises,
  and the driver re-drives the WHOLE loss step — ``apply_grads`` is
  idempotent per step, so stages that already applied skip.  Replay is
  thus bounded by one loss step and the driver never sees an
  ObjectLostError (descriptors are regenerated, payloads re-pushed).
- **Fallback**: ``config.distributed_training=off`` (or a single stage,
  or no runtime) runs the byte-identical single-host path — the same
  per-micro-batch loss/grad accumulation in one jitted program, every
  counter below zero (pinned by tests).

Numerics contract: total loss is the mean over micro-batches of
``loss_fn(stage_fn∘...∘stage_fn(x_mb), target_mb)`` and gradients are
the matching mean of per-micro-batch gradients — identical, term for
term, to ``pipeline_apply`` on one device, so integer-valued float32
training matches it BITWISE (all sums exact below 2**24).

LOCK ORDER: ``_STATS_LOCK`` is an independent LEAF — it guards only the
process-local counter dict read by ``train_stats()`` (the xfer_stats
flusher / ``transfer_stats()`` merge); no other lock is ever acquired
while holding it and it is never held across serialization, a push, or
any wire call.  Pinned in tests/test_lockcheck.py next to the shuffle
stats leaf.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

import ray_tpu as ray
from ray_tpu.remote_function import _bulk_submit

# ------------------------------------------------------------- counters --
# Process-local cumulative counters.  In workers (stage actors, remote
# learners) they ride the periodic ("xfer_stats", delta) flush
# (worker_main.flush_xfer_stats looks this module up lazily); in the
# driver/head process transfer_stats() merges them directly.  All zero
# while distributed_training is off — pinned by tests.
_STATS_LOCK = threading.Lock()  # lock-order: leaf (see module docstring)
_STATS = {
    "microbatch_pushes": 0,
    "stage_restarts": 0,
    "learner_queue_stalls": 0,
}


def note(key: str, n: int = 1) -> None:
    with _STATS_LOCK:
        _STATS[key] += n


def train_stats() -> Dict[str, int]:
    """Cumulative snapshot (monotonic — the flusher ships deltas).
    Deliberately NOT named ``stats()``: protocheck's counter-survival
    rule scans worker modules' ``stats()`` providers, and this module's
    keys are aggregated through the lazy flush hook instead."""
    with _STATS_LOCK:
        return dict(_STATS)


# ----------------------------------------------------------- data plane --
_DESCR_TAG = "__mbdescr__"


def _is_descr(v) -> bool:
    return isinstance(v, tuple) and len(v) == 5 and v[0] == _DESCR_TAG


def active_config():
    """The effective config: the runtime's (carries ``_system_config``
    overrides) when one is up, else the env-derived global."""
    from ray_tpu._private import api_internal
    from ray_tpu._private.config import GLOBAL_CONFIG

    rt = api_internal.get_runtime()
    return getattr(rt, "config", None) or GLOBAL_CONFIG


def _push_value(value, store: str) -> tuple:
    """Serialize one micro-batch tensor pytree and land its segment
    image in ``store``: local short-circuit through ``put_local``, else
    a striped ``ObjectPusher.push``.  A failed/stalled/unsupported
    remote push HEDGES into the pusher's own store (the consumer pulls
    it over the data plane) — training never dies on one gray link.
    Returns ``(TAG, kind, ident, total, home_store)``."""
    from ray_tpu._private import api_internal, object_transfer, serialization
    from ray_tpu._private import shm_store as shm_mod
    from ray_tpu._private.ids import ObjectID

    rt = api_internal.require_runtime()
    res = serialization.dumps_adaptive(value, 0)  # max_inline=0: parts
    meta, bufs = res[1], res[2]
    oid_bin = ObjectID.for_put().binary()
    if store and store != rt.store_id:
        ent = rt.resolve_store_addr(store)
        if ent is not None and object_transfer.peer_accepts_puts(ent[1]):
            try:
                kind, ident, total = rt._pusher.push(
                    store, ent[0], oid_bin, meta, bufs, caps=ent[1])
                note("microbatch_pushes")
                return (_DESCR_TAG, kind, ident, total, store)
            except Exception:
                # Dead or stalled-past-deadline link (the pusher already
                # retried with backoff under the PR 14 deadline core):
                # fall through to the local hedge.
                rt.forget_store_addr(store)
    kind, ident, total = shm_mod.put_local(rt.shm, oid_bin, meta, bufs)
    note("microbatch_pushes")
    return (_DESCR_TAG, kind, ident, total, rt.store_id)


def _load_value(descr: tuple):
    """Descriptor -> value.  Locally-homed segments attach by name/path,
    deserialize, COPY (loaded arrays may be zero-copy views into the
    mapping), and unlink; hedged remote-homed ones pull over the data
    plane through the runtime's materialize path.  A segment already
    consumed (an at-least-once replay re-reading its input) raises —
    the driver's step re-drive is the recovery path."""
    import os

    from ray_tpu._private import api_internal, protocol

    _tag, kind, ident, total, store = descr
    rt = api_internal.require_runtime()
    if store == rt.store_id:
        if kind == "spilled":
            seg = rt.shm.attach_path(ident)
            try:
                value = _copy_arrays(seg.deserialize())
            finally:
                seg.close()
            try:
                os.unlink(ident)
            except OSError:
                pass
        else:
            seg = rt.shm.attach(ident)
            try:
                value = _copy_arrays(seg.deserialize())
            finally:
                seg.close()
            # Owner-routed free: releases the node byte accounting the
            # pusher's reserve_put charged.
            rt.shm.unlink(ident, total)
        return value
    pkind = protocol.SHM if kind == "shm" else protocol.SPILLED
    return rt.materialize((pkind, ident, total, store))


def _copy_arrays(tree):
    import jax

    return jax.tree.map(
        lambda v: np.array(v, copy=True) if isinstance(v, np.ndarray)
        else v, tree)


def _split_microbatches(x, num_microbatches: int) -> List[Any]:
    """Split every leaf along axis 0 into ``num_microbatches`` equal
    pieces (the GPipe micro-batching contract)."""
    import jax

    def check(v):
        if v.shape[0] % num_microbatches:
            raise ValueError(
                f"batch {v.shape[0]} % microbatches {num_microbatches}"
                " != 0")

    jax.tree.map(check, x)
    return [jax.tree.map(
        lambda v: v[i * (v.shape[0] // num_microbatches):
                    (i + 1) * (v.shape[0] // num_microbatches)], x)
        for i in range(num_microbatches)]


# ------------------------------------------------------------ the actor --
@ray.remote
class PipelineStage:
    """One pipeline stage: owns its stage's params (and optimizer
    slice), computes micro-batch forwards/backwards, pushes activations
    downstream and activation-grads upstream over the striped put path.

    Single-threaded by the actor model; FIFO call order from the driver
    IS the stage's 1F1B schedule.  Backward rematerializes the forward
    (``jax.vjp`` from the stashed INPUT) — the stash is then plain
    arrays, checkpointable and bounded at ``pp`` entries in steady
    state."""

    def __init__(self, stage_fn: Callable, loss_fn: Optional[Callable],
                 params, optimizer, stage_idx: int, num_stages: int,
                 num_microbatches: int):
        import jax

        self._stage_fn = stage_fn
        self._loss_fn = loss_fn
        self._idx = stage_idx
        self._pp = num_stages
        self._M = num_microbatches
        self._params = jax.tree.map(jax.numpy.asarray, params)
        self._optimizer = optimizer
        self._opt_state = optimizer.init(self._params)
        self._applied_step = -1
        self._last_metrics: Dict[str, float] = {}
        self._next_store = ""
        self._prev_store = ""
        self._stash: Dict[int, Any] = {}
        self._accum = None
        self._loss_sum = 0.0
        self._busy_s = 0.0

        self._jit_fwd = jax.jit(stage_fn)

        def _bwd(p, x, g):
            _, vjp = jax.vjp(stage_fn, p, x)
            return vjp(g)

        self._jit_bwd = jax.jit(_bwd)
        if loss_fn is not None:

            def _loss_bwd(p, x, target):
                def f(pp_, xx):
                    return loss_fn(stage_fn(pp_, xx), target)

                return jax.value_and_grad(f, argnums=(0, 1))(p, x)

            self._jit_loss_bwd = jax.jit(_loss_bwd)

    # -- wiring ----------------------------------------------------------
    def get_store(self) -> str:
        from ray_tpu._private import api_internal

        return api_internal.require_runtime().store_id

    def set_links(self, next_store: str, prev_store: str) -> bool:
        self._next_store = next_store
        self._prev_store = prev_store
        return True

    def ping(self) -> bool:
        return True

    def pid(self) -> int:
        import os

        return os.getpid()

    # -- schedule body ----------------------------------------------------
    def forward(self, mb: int, x, target=None):
        """Compute this stage's forward for micro-batch ``mb``.  ``x``
        is a raw array pytree on stage 0 (driver-supplied) or the
        upstream stage's push descriptor; the LAST stage also receives
        its micro-batch ``target`` and returns None (its backward seeds
        from the loss), every other stage pushes its activation into
        the successor's store and returns the descriptor."""
        import jax

        if _is_descr(x):
            x = _load_value(x)
        x = jax.tree.map(jax.numpy.asarray, x)
        t0 = time.perf_counter()
        if self._idx == self._pp - 1:
            # Loss stage: defer compute to backward (value_and_grad
            # rematerializes the forward) — stash input + target.
            self._stash[mb] = (x, target)
            self._busy_s += time.perf_counter() - t0
            return None
        y = self._jit_fwd(self._params, x)
        jax.block_until_ready(y)
        self._busy_s += time.perf_counter() - t0
        self._stash[mb] = (x, None)
        return _push_value(
            jax.tree.map(np.asarray, y), self._next_store)

    def backward(self, mb: int, g=None):
        """Compute this stage's backward for micro-batch ``mb``:
        rematerialize the forward from the stashed input, accumulate
        the param gradient, push the input gradient upstream (stages
        > 0) and return its descriptor."""
        import jax

        if mb not in self._stash:
            raise RuntimeError(
                f"stage {self._idx}: no stashed activation for "
                f"microbatch {mb} (replayed past a consumed input)")
        x, target = self._stash.pop(mb)
        if self._idx == self._pp - 1:
            t0 = time.perf_counter()
            loss, (gp, gx) = self._jit_loss_bwd(self._params, x, target)
            jax.block_until_ready(loss)
            self._busy_s += time.perf_counter() - t0
            self._loss_sum += float(loss)
        else:
            if _is_descr(g):
                g = _load_value(g)
            g = jax.tree.map(jax.numpy.asarray, g)
            t0 = time.perf_counter()
            gp, gx = self._jit_bwd(self._params, x, g)
            jax.block_until_ready(gp)
            self._busy_s += time.perf_counter() - t0
        self._accum = gp if self._accum is None else jax.tree.map(
            jax.numpy.add, self._accum, gp)
        if self._idx == 0:
            return None
        return _push_value(
            jax.tree.map(np.asarray, gx), self._prev_store)

    def apply_grads(self, step: int) -> Dict[str, float]:
        """Optimizer step over the accumulated gradients / M.
        IDEMPOTENT per ``step``: a re-driven loss step (the driver's
        replay safety net) skips stages that already applied and
        returns their cached metrics — params advance exactly once."""
        import jax
        import optax

        if self._applied_step >= step:
            return dict(self._last_metrics)
        if self._accum is None:
            raise RuntimeError(
                f"stage {self._idx}: apply_grads({step}) with no "
                "accumulated gradients")
        grads = jax.tree.map(lambda gacc: gacc / self._M, self._accum)
        updates, self._opt_state = self._optimizer.update(
            grads, self._opt_state, self._params)
        self._params = optax.apply_updates(self._params, updates)
        jax.block_until_ready(self._params)
        self._applied_step = step
        metrics = {"step": float(step),
                   "grad_norm": float(optax.global_norm(grads))}
        if self._idx == self._pp - 1:
            metrics["loss"] = self._loss_sum / self._M
        self._accum = None
        self._stash.clear()
        self._loss_sum = 0.0
        self._last_metrics = metrics
        return dict(metrics)

    def reset_step(self, step: int) -> bool:
        """Clear partial state for a re-drive of ``step``.  A stage
        that already applied ``step`` keeps its post-step params (its
        apply_grads will no-op); every other stage drops its stash and
        accumulators so the re-driven schedule starts clean."""
        if self._applied_step < step:
            self._stash.clear()
            self._accum = None
            self._loss_sum = 0.0
        return True

    # -- introspection ----------------------------------------------------
    def get_params(self):
        import jax

        return jax.tree.map(np.asarray, jax.device_get(self._params))

    def get_grad_accum(self):
        """Test hook: the raw (unscaled) gradient accumulator."""
        import jax

        if self._accum is None:
            return None
        return jax.tree.map(np.asarray, jax.device_get(self._accum))

    def stage_stats(self) -> Dict[str, float]:
        return {"busy_s": self._busy_s, "applied_step": self._applied_step,
                "stash": len(self._stash)}

    # -- checkpoint hooks (PR 9) ------------------------------------------
    def __ray_save__(self):
        import jax

        to_np = lambda t: jax.tree.map(np.asarray, jax.device_get(t))
        return {
            "params": to_np(self._params),
            "opt_state": to_np(self._opt_state),
            "applied_step": self._applied_step,
            "last_metrics": dict(self._last_metrics),
            "links": (self._next_store, self._prev_store),
            "stash": {mb: to_np(v) for mb, v in self._stash.items()},
            "accum": None if self._accum is None else to_np(self._accum),
            "loss_sum": self._loss_sum,
            "busy_s": self._busy_s,
        }

    def __ray_restore__(self, state):
        import jax

        self._params = jax.tree.map(jax.numpy.asarray, state["params"])
        self._opt_state = jax.tree.map(
            lambda v: jax.numpy.asarray(v) if isinstance(v, np.ndarray)
            else v, state["opt_state"])
        self._applied_step = state["applied_step"]
        self._last_metrics = state["last_metrics"]
        self._next_store, self._prev_store = state["links"]
        self._stash = dict(state["stash"])
        self._accum = state["accum"]
        self._loss_sum = state["loss_sum"]
        self._busy_s = state["busy_s"]
        note("stage_restarts")


# ------------------------------------------------------------ the driver --
class PipelineTrainer:
    """Drive ``num_stages`` PipelineStage actors through the 1F1B
    schedule, one ``step(x, target)`` per optimizer step.

    The driver never blocks inside a step's schedule: it submits every
    stage's call sequence in dependency order (a call becomes eligible
    the moment its upstream result ref exists), passing descriptor refs
    as args — per-actor FIFO then realizes 1F1B, and the only waits are
    on the per-stage ``apply_grads`` barriers at the end.

    ``schedule="fill_drain"`` instead drives synchronous wave barriers
    (all M forwards of stage s complete before stage s+1 starts — the
    GPipe fill/drain shape with transfers ON the critical path): the
    measured A/B baseline for the bench's bubble/overlap comparison.

    Falls back to the byte-identical single-host path (same micro-batch
    loss/grad accumulation in one jitted program) when
    ``config.distributed_training`` is off, a single stage is given, or
    no runtime is initialized.
    """

    def __init__(self, stage_fn: Callable, loss_fn: Callable,
                 stage_params: Sequence[Any], *, optimizer=None,
                 num_microbatches: int = 0, distributed: Optional[bool]
                 = None, max_restarts: int = 2, max_task_retries: int = -1,
                 max_redrives: int = 2, num_cpus_per_stage: int = 1):
        import optax

        self._stage_fn = stage_fn
        self._loss_fn = loss_fn
        self._pp = len(stage_params)
        if self._pp < 1:
            raise ValueError("need at least one stage")
        cfg = active_config()
        self._M = (num_microbatches or cfg.pipeline_microbatches
                   or 2 * self._pp)
        self._optimizer = optimizer or optax.sgd(1e-2)
        self._step_num = 0
        self._max_redrives = max_redrives
        if distributed is None:
            distributed = cfg.distributed_training
        self._distributed = bool(
            distributed and self._pp > 1 and self._runtime_up())
        if self._distributed:
            self._stages = [
                PipelineStage.options(
                    num_cpus=num_cpus_per_stage,
                    max_restarts=max_restarts,
                    max_task_retries=max_task_retries,
                ).remote(stage_fn, loss_fn if s == self._pp - 1 else None,
                         stage_params[s], self._optimizer, s, self._pp,
                         self._M)
                for s in range(self._pp)]
            self._wire_links()
        else:
            self._local_params = list(stage_params)
            self._local_step = self._make_local_step()

    @staticmethod
    def _runtime_up() -> bool:
        from ray_tpu._private import api_internal

        try:
            api_internal.require_runtime()
            return True
        except Exception:
            return False

    # -- wiring -----------------------------------------------------------
    def _wire_links(self):
        stores = ray.get(_bulk_submit(
            [(s.get_store, (), None) for s in self._stages]), timeout=60)
        calls = []
        for i, s in enumerate(self._stages):
            nxt = stores[i + 1] if i + 1 < self._pp else ""
            prv = stores[i - 1] if i > 0 else ""
            calls.append((s.set_links, (nxt, prv), None))
        ray.get(_bulk_submit(calls), timeout=60)

    # -- the 1F1B schedule -------------------------------------------------
    def _stage_sched(self, s: int):
        """Per-stage 1F1B call order: warmup ``min(pp-1-s, M)``
        forwards, steady-state F/B pairs, cooldown backwards — at most
        ``pp`` live stashes per stage."""
        w = min(self._pp - 1 - s, self._M)
        seq = [("F", i) for i in range(w)]
        for i in range(self._M - w):
            seq.append(("F", w + i))
            seq.append(("B", i))
        seq.extend(("B", i) for i in range(self._M - w, self._M))
        return seq

    def _submit_1f1b(self, x_mbs, t_mbs):
        pp, M = self._pp, self._M
        fwd = [[None] * M for _ in range(pp)]
        bwd = [[None] * M for _ in range(pp)]
        scheds = [self._stage_sched(s) for s in range(pp)]
        pos = [0] * pp
        while any(pos[s] < len(scheds[s]) for s in range(pp)):
            progressed = False
            for s in range(pp):
                while pos[s] < len(scheds[s]):
                    kind, i = scheds[s][pos[s]]
                    if kind == "F":
                        if s > 0 and fwd[s - 1][i] is None:
                            break
                        if s == 0:
                            arg = x_mbs[i]
                        else:
                            arg = fwd[s - 1][i]
                        tgt = t_mbs[i] if s == pp - 1 else None
                        fwd[s][i] = self._stages[s].forward.remote(
                            i, arg, tgt)
                    else:
                        if s < pp - 1 and bwd[s + 1][i] is None:
                            break
                        arg = bwd[s + 1][i] if s < pp - 1 else None
                        bwd[s][i] = self._stages[s].backward.remote(i, arg)
                    pos[s] += 1
                    progressed = True
            assert progressed, "1F1B schedule deadlocked"
        return bwd

    def _submit_fill_drain(self, x_mbs, t_mbs):
        """Synchronous GPipe fill/drain: per-stage wave barriers, so
        every activation transfer sits on the critical path (the bench
        baseline 1F1B is measured against)."""
        pp, M = self._pp, self._M
        prev = None
        for s in range(pp):
            refs = []
            for i in range(M):
                arg = x_mbs[i] if s == 0 else prev[i]
                tgt = t_mbs[i] if s == pp - 1 else None
                refs.append(self._stages[s].forward.remote(i, arg, tgt))
            ray.get(list(refs), timeout=300)  # wave barrier
            prev = refs
        bwd = [[None] * M for _ in range(pp)]
        prev = [None] * M
        for s in range(pp - 1, -1, -1):
            refs = [self._stages[s].backward.remote(i, prev[i])
                    for i in range(M)]
            ray.get(list(refs), timeout=300)  # wave barrier
            bwd[s] = refs
            prev = refs
        return bwd

    # -- stepping ----------------------------------------------------------
    def step(self, x, target, schedule: str = "1f1b") -> Dict[str, float]:
        """One optimizer step over batch ``(x, target)`` split into M
        micro-batches.  On any stage failure the whole step re-drives
        (bounded by ``max_redrives``); ``apply_grads`` idempotency keeps
        params exactly once-advanced."""
        if not self._distributed:
            return self._step_local(x, target)
        x_mbs = [_as_np(v) for v in _split_microbatches(x, self._M)]
        t_mbs = [_as_np(v) for v in _split_microbatches(target, self._M)]
        step = self._step_num
        last_err = None
        for _attempt in range(self._max_redrives + 1):
            try:
                if schedule == "fill_drain":
                    self._submit_fill_drain(x_mbs, t_mbs)
                else:
                    self._submit_1f1b(x_mbs, t_mbs)
                applies = _bulk_submit(
                    [(s.apply_grads, (step,), None) for s in self._stages])
                metrics = ray.get(list(applies), timeout=300)
                self._step_num += 1
                return metrics[-1]
            except Exception as e:  # noqa: BLE001 — any stage fault
                last_err = e
                self._recover(step)
        raise last_err

    def _recover(self, step: int):
        """Post-fault settle: wait out restarts (ping), refresh the
        store wiring (a restarted stage may live on a new node), and
        clear partial step state on stages that have not applied."""
        for s in self._stages:
            try:
                ray.get(s.ping.remote(), timeout=120)
            except Exception:
                pass
        try:
            self._wire_links()
            ray.get(_bulk_submit(
                [(s.reset_step, (step,), None) for s in self._stages]),
                timeout=60)
        except Exception:
            pass

    # -- single-host fallback ----------------------------------------------
    def _make_local_step(self):
        import jax
        import optax

        stage_fn, loss_fn, M = self._stage_fn, self._loss_fn, self._M

        def total_loss(params_list, x, target):
            x_mbs = _split_microbatches(x, M)
            t_mbs = _split_microbatches(target, M)
            total = 0.0
            for x_mb, t_mb in zip(x_mbs, t_mbs):
                y = x_mb
                for p in params_list:
                    y = stage_fn(p, y)
                total = total + loss_fn(y, t_mb)
            return total / M

        def step(params_list, opt_state, x, target):
            loss, grads = jax.value_and_grad(total_loss)(
                params_list, x, target)
            updates, opt_state = self._optimizer.update(
                grads, opt_state, params_list)
            params_list = optax.apply_updates(params_list, updates)
            return params_list, opt_state, loss, optax.global_norm(grads)

        self._local_opt_state = self._optimizer.init(
            list(self._local_params))
        return jax.jit(step)

    def _step_local(self, x, target) -> Dict[str, float]:
        self._local_params, self._local_opt_state, loss, gn = \
            self._local_step(list(self._local_params),
                             self._local_opt_state, x, target)
        metrics = {"step": float(self._step_num), "loss": float(loss),
                   "grad_norm": float(gn)}
        self._step_num += 1
        return metrics

    # -- introspection / lifecycle ----------------------------------------
    @property
    def distributed(self) -> bool:
        return self._distributed

    @property
    def num_microbatches(self) -> int:
        return self._M

    def get_stage_params(self) -> List[Any]:
        import jax

        if not self._distributed:
            return [jax.tree.map(np.asarray, jax.device_get(p))
                    for p in self._local_params]
        return ray.get(_bulk_submit(
            [(s.get_params, (), None) for s in self._stages]), timeout=120)

    def stage_stats(self) -> List[Dict[str, float]]:
        if not self._distributed:
            return []
        return ray.get(_bulk_submit(
            [(s.stage_stats, (), None) for s in self._stages]), timeout=60)

    def stage_pids(self) -> List[int]:
        if not self._distributed:
            return []
        return ray.get(_bulk_submit(
            [(s.pid, (), None) for s in self._stages]), timeout=60)

    def shutdown(self):
        if not self._distributed:
            return
        for s in self._stages:
            try:
                ray.kill(s)
            except Exception:
                pass


def _as_np(tree):
    import jax

    return jax.tree.map(np.asarray, tree)
