"""Flagship model family: Llama-style decoder LM (dense or MoE), TPU-first.

Pure-functional design: params are a pytree of arrays, every tensor
dimension has a *logical axis name*, and one rules table
(``parallel.sharding.DEFAULT_RULES``) maps names to mesh axes — so the same
model runs DP, FSDP, 2D (fsdp x tp), MoE-EP, or sequence-parallel by
swapping rules, never editing model code.

TPU-first choices:
- layers are *stacked* on a leading "layer" dim and driven by ``lax.scan``
  (+``jax.checkpoint``): one trace/compile of a single layer regardless of
  depth, rematerialized backward to trade FLOPs for HBM.
- bf16 activations/params with f32 RMSNorm stats and f32 logits/loss — the
  MXU-native recipe.
- attention is pluggable: pallas flash (ops/attention.py), ring over 'sp'
  (ops/ring_attention.py), Ulysses all-to-all, or the XLA reference — all
  numerically interchangeable (tested).
- MoE layers use the dense-dispatch router (ops/moe.py); expert tensors are
  sharded over 'ep' so XLA lowers dispatch/combine to ICI all-to-alls.

Reference counterpart: none in Ray core (no tensor ops); RLlib's model zoo
(``rllib/models/catalog.py``) plays the "models shipped with the framework"
role, and its JAX support is a 299-LoC stub (``rllib/models/jax/``) — cited
for parity, not design.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.ops.attention import flash_attention, mha_reference
from ray_tpu.ops.ring_attention import ring_attention
from ray_tpu.ops.ulysses import ulysses_attention
from ray_tpu.ops.layers import (
    rms_norm, rope, apply_rope, swiglu, repeat_kv_heads,
)
from ray_tpu.ops.moe import moe_ffn
from ray_tpu.parallel.mesh import AXIS_DP, AXIS_FSDP, AXIS_SP, AXIS_TP
from ray_tpu.parallel.sharding import (
    DEFAULT_RULES, LogicalAxisRules, with_logical_constraint,
)


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    embed_dim: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    head_dim: int = 128
    mlp_dim: int = 11008
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    attn_impl: str = "flash"          # flash | ring | ulysses | reference
    num_experts: int = 0              # 0 = dense FFN
    num_selected: int = 2
    capacity_factor: float = 1.25
    aux_loss_coef: float = 0.01
    remat: bool = True

    @property
    def qkv_dim(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.num_kv_heads * self.head_dim

    @staticmethod
    def llama2_7b(**kw) -> "LlamaConfig":
        return LlamaConfig(**kw)

    @staticmethod
    def llama2_13b(**kw) -> "LlamaConfig":
        return LlamaConfig(embed_dim=5120, num_layers=40, num_heads=40,
                           num_kv_heads=40, mlp_dim=13824, **kw)

    @staticmethod
    def tiny(**kw) -> "LlamaConfig":
        """CI-sized config: runs on one CPU device in seconds."""
        defaults = dict(vocab_size=256, embed_dim=64, num_layers=2,
                        num_heads=4, num_kv_heads=4, head_dim=16, mlp_dim=128,
                        max_seq_len=64, dtype=jnp.float32, remat=False,
                        attn_impl="reference")
        defaults.update(kw)
        return LlamaConfig(**defaults)


def _dense_layer_shapes(cfg: LlamaConfig) -> Dict[str, Tuple[Tuple[int, ...],
                                                             Tuple]]:
    """name -> (shape-per-layer, logical axes incl. the stacked 'layer' dim)."""
    d, h, kvd, m = cfg.embed_dim, cfg.qkv_dim, cfg.kv_dim, cfg.mlp_dim
    shapes = {
        "attn_norm": ((d,), ("layer", "embed")),
        "wq": ((d, h), ("layer", "kernel_in", "heads")),
        "wk": ((d, kvd), ("layer", "kernel_in", "kv_heads")),
        "wv": ((d, kvd), ("layer", "kernel_in", "kv_heads")),
        "wo": ((h, d), ("layer", "heads", "kernel_in")),
        "mlp_norm": ((d,), ("layer", "embed")),
    }
    if cfg.num_experts:
        e = cfg.num_experts
        shapes.update({
            "router": ((d, e), ("layer", "kernel_in", None)),
            "w_gate": ((e, d, m), ("layer", "expert", "kernel_in", "mlp")),
            "w_up": ((e, d, m), ("layer", "expert", "kernel_in", "mlp")),
            "w_down": ((e, m, d), ("layer", "expert", "mlp", "kernel_in")),
        })
    else:
        shapes.update({
            "w_gate": ((d, m), ("layer", "kernel_in", "mlp")),
            "w_up": ((d, m), ("layer", "kernel_in", "mlp")),
            "w_down": ((m, d), ("layer", "mlp", "kernel_in")),
        })
    return shapes


def param_logical_axes(cfg: LlamaConfig) -> Dict[str, Any]:
    layers = {k: ax for k, (_, ax) in _dense_layer_shapes(cfg).items()}
    return {
        "embed": ("vocab", "kernel_in"),
        "layers": layers,
        "final_norm": ("embed",),
        "lm_head": ("kernel_in", "vocab"),
    }


def init_params(key: jax.Array, cfg: LlamaConfig) -> Dict[str, Any]:
    """Scaled-normal init (fan-in), params in ``cfg.param_dtype``."""
    shapes = _dense_layer_shapes(cfg)
    n_tensors = len(shapes) + 3
    keys = iter(jax.random.split(key, n_tensors))

    def norm_init(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(cfg.param_dtype)

    layers = {}
    for name, (shape, _) in shapes.items():
        full = (cfg.num_layers,) + shape
        if name.endswith("norm"):
            layers[name] = jnp.ones(full, cfg.param_dtype)
        else:
            fan_in = shape[-2] if len(shape) >= 2 else shape[-1]
            layers[name] = norm_init(next(keys), full, fan_in)
    return {
        "embed": norm_init(next(keys), (cfg.vocab_size, cfg.embed_dim), 1.0),
        "layers": layers,
        "final_norm": jnp.ones((cfg.embed_dim,), cfg.param_dtype),
        "lm_head": norm_init(next(keys), (cfg.embed_dim, cfg.vocab_size),
                             cfg.embed_dim),
    }


def _attention(q, k, v, cfg: LlamaConfig, mesh: Optional[Mesh]):
    """Dispatch to the configured attention impl.

    Pallas kernels have no SPMD partitioning rule, so under a mesh the flash
    path runs inside shard_map (batch over (dp,fsdp), heads over tp); ring /
    ulysses manage the 'sp' axis themselves.
    """
    impl = cfg.attn_impl
    if mesh is None:
        # Ring/ulysses degenerate to plain attention on one device.
        if impl == "flash":
            return flash_attention(q, k, v, causal=True)
        return mha_reference(q, k, v, causal=True)
    if impl == "ring":
        return ring_attention(q, k, v, causal=True, mesh=mesh)
    if impl == "ulysses":
        return ulysses_attention(q, k, v, causal=True, mesh=mesh)
    if impl == "reference":
        return mha_reference(q, k, v, causal=True)
    # flash under a mesh: pallas has no SPMD partitioning rule, so run the
    # kernel per-shard: batch over (dp,fsdp), heads over tp, seq replicated.
    from ray_tpu.parallel.sharding import manual_shard_map
    k, v = repeat_kv_heads(q, k, v)
    spec = P((AXIS_DP, AXIS_FSDP), None, AXIS_TP, None)
    fn = manual_shard_map(
        lambda q_, k_, v_: flash_attention(q_, k_, v_, causal=True),
        {AXIS_DP, AXIS_FSDP, AXIS_TP}, in_specs=(spec, spec, spec),
        out_specs=spec, mesh=mesh)
    return fn(q, k, v)


def _attention_sp_manual(q, k, v, cfg: LlamaConfig):
    """Attention inside an already-manual 'sp' region (pipeline path):
    call the sharded bodies inline — no nested shard_map."""
    from ray_tpu.ops.ring_attention import _ring_attention_sharded
    from ray_tpu.ops.ulysses import _ulysses_sharded
    k, v = repeat_kv_heads(q, k, v)
    sm_scale = cfg.head_dim ** -0.5
    if cfg.attn_impl == "ulysses":
        return _ulysses_sharded(q, k, v, sm_scale, True, AXIS_SP,
                                use_flash=False)
    return _ring_attention_sharded(q, k, v, sm_scale, True, AXIS_SP)


def forward(params: Dict[str, Any], tokens: jax.Array, cfg: LlamaConfig, *,
            mesh: Optional[Mesh] = None,
            rules: Optional[LogicalAxisRules] = None
            ) -> Tuple[jax.Array, jax.Array]:
    """tokens: (batch, seq) int32 -> (logits f32 (b, s, vocab), aux_loss).

    Global-view path: call under jit with a mesh context; sharding
    constraints steer XLA's partitioner.  (The pipeline-parallel path is
    ``parallel.pipeline.forward_pipelined`` — manual SPMD.)
    """
    cst = _make_cst(mesh, rules)
    b, s = tokens.shape
    if mesh is not None:
        # One-hot matmul instead of gather: with a ('vocab','embed')-sharded
        # table this lowers to a local matmul + psum over 'tp' — the gather
        # form makes the SPMD partitioner fully rematerialize the table.
        onehot = jax.nn.one_hot(tokens, cfg.vocab_size, dtype=cfg.dtype)
        x = onehot @ params["embed"].astype(cfg.dtype)
    else:
        x = jnp.take(params["embed"], tokens, axis=0).astype(cfg.dtype)
    x = cst(x, ("batch", "seq", "embed"))
    layer_fn = _make_layer_fn(cfg, mesh, rules)
    if cfg.remat:
        layer_fn = jax.checkpoint(layer_fn)
    (x, aux), _ = jax.lax.scan(layer_fn, (x, jnp.zeros((), jnp.float32)),
                               params["layers"])
    x = rms_norm(x, params["final_norm"])
    logits = (x @ params["lm_head"].astype(cfg.dtype)).astype(jnp.float32)
    logits = cst(logits, ("batch", "seq", "vocab"))
    return logits, aux / cfg.num_layers


def _make_cst(mesh, rules):
    if mesh is None:
        return lambda x, ax: x
    return lambda x, ax: with_logical_constraint(x, ax, rules=rules)


def _make_layer_fn(cfg: LlamaConfig, mesh, rules, sp_manual: bool = False):
    """One transformer layer as a scan body over stacked layer params.
    Shapes are read off the activation so the same body serves the full
    batch (forward) and microbatches (forward_pipelined).

    ``sp_manual``: the body runs inside a shard_map that is manual over
    'sp' (the pipeline path — jax/shardy cannot nest manual regions): the
    seq dim is device-local, RoPE uses the rank's global offset, and
    ring/ulysses attention run inline over the bound 'sp' axis.
    """
    cst = _make_cst(mesh, rules)

    def layer_fn(carry, lp):
        x, aux = carry
        b, s = x.shape[0], x.shape[1]
        offset = 0
        if sp_manual:
            offset = jax.lax.axis_index(AXIS_SP) * s
        cos, sin = rope(s, cfg.head_dim, cfg.rope_theta, offset=offset)
        h = rms_norm(x, lp["attn_norm"])
        q = (h @ lp["wq"].astype(cfg.dtype)).reshape(
            b, s, cfg.num_heads, cfg.head_dim)
        k = (h @ lp["wk"].astype(cfg.dtype)).reshape(
            b, s, cfg.num_kv_heads, cfg.head_dim)
        v = (h @ lp["wv"].astype(cfg.dtype)).reshape(
            b, s, cfg.num_kv_heads, cfg.head_dim)
        q = cst(apply_rope(q, cos, sin), ("batch", "seq", "heads", "head_dim"))
        k = cst(apply_rope(k, cos, sin),
                ("batch", "seq", "kv_heads", "head_dim"))
        if sp_manual:
            o = _attention_sp_manual(q, k, v, cfg)
        else:
            o = _attention(q, k, v, cfg, mesh)
        o = o.reshape(b, s, cfg.qkv_dim)
        x = x + cst(o @ lp["wo"].astype(cfg.dtype), ("batch", "seq", "embed"))

        h = rms_norm(x, lp["mlp_norm"])
        if cfg.num_experts:
            flat = h.reshape(b * s, cfg.embed_dim)
            moe = moe_ffn(flat, lp["router"], lp["w_gate"], lp["w_up"],
                          lp["w_down"], num_selected=cfg.num_selected,
                          capacity_factor=cfg.capacity_factor,
                          constrain=cst if mesh is not None else None)
            ff = moe.out.reshape(b, s, cfg.embed_dim)
            aux = aux + moe.aux_loss
        else:
            gate = h @ lp["w_gate"].astype(cfg.dtype)
            up = h @ lp["w_up"].astype(cfg.dtype)
            ff = swiglu(gate, up) @ lp["w_down"].astype(cfg.dtype)
        x = x + cst(ff, ("batch", "seq", "embed"))
        return (x, aux), None

    return layer_fn


def forward_pipelined(params: Dict[str, Any], tokens: jax.Array,
                      cfg: LlamaConfig, *, mesh: Mesh,
                      num_microbatches: int,
                      rules: Optional[LogicalAxisRules] = None
                      ) -> Tuple[jax.Array, jax.Array]:
    """Pipeline-parallel forward: transformer layers split into ``pp``
    stages (parallel.pipeline), embed/head replicated across stages.

    Sequence parallelism composes: with attn_impl ring/ulysses the pipeline
    region is manual over {'pp','sp'} (jax/shardy cannot *nest* manual
    regions) — activations enter seq-sharded, RoPE offsets come from the
    'sp' rank, and attention runs inline over the bound axis.

    MoE aux loss inside pipeline stages is dropped (stage outputs must be
    activation-shaped); use dense FFN or accept coef=0 semantics under pp.
    """
    from ray_tpu.parallel.pipeline import pipeline_apply, split_stages
    from ray_tpu.parallel.mesh import AXIS_PP

    cst = _make_cst(mesh, rules)
    b, s = tokens.shape
    onehot = jax.nn.one_hot(tokens, cfg.vocab_size, dtype=cfg.dtype)
    x = cst(onehot @ params["embed"].astype(cfg.dtype),
            ("batch", "seq", "embed"))

    sp_manual = cfg.attn_impl in ("ring", "ulysses") and \
        mesh.shape[AXIS_SP] > 1
    if sp_manual:
        # Inside the manual region 'seq' is device-local and 'sp' is bound:
        # strip it from the rules GSPMD sees.
        inner_rules = dict(rules if rules is not None else DEFAULT_RULES)
        inner_rules["seq"] = None
        x_spec = P(None, AXIS_SP, None)
        manual_axes = {AXIS_PP, AXIS_SP}
    else:
        inner_rules = rules
        x_spec = P()
        manual_axes = {AXIS_PP}
    layer_fn = _make_layer_fn(cfg, mesh, inner_rules, sp_manual=sp_manual)
    if cfg.remat:
        layer_fn = jax.checkpoint(layer_fn)

    def stage_fn(stage_params, x_mb):
        (y, _), _ = jax.lax.scan(
            layer_fn, (x_mb, jnp.zeros((), jnp.float32)), stage_params)
        return y

    stages = split_stages(params["layers"], mesh.shape[AXIS_PP])
    x = pipeline_apply(stage_fn, stages, x, mesh=mesh,
                       num_microbatches=num_microbatches,
                       manual_axes=manual_axes, x_spec=x_spec)
    x = rms_norm(x, params["final_norm"])
    logits = (x @ params["lm_head"].astype(cfg.dtype)).astype(jnp.float32)
    return cst(logits, ("batch", "seq", "vocab")), jnp.zeros((), jnp.float32)


def pipeline_stage_params(params: Dict[str, Any],
                          num_stages: int) -> list:
    """Stage-sliced construction for the ACTOR pipeline
    (``train.pipeline_actors``): split the stacked layer params into
    ``num_stages`` contiguous slices, folding the embedding into stage
    0 and the final norm + LM head into the last stage — each stage
    actor then owns exactly its stage's tensors, nothing replicated."""
    layers = params["layers"]
    n_layers = next(iter(layers.values())).shape[0]
    if n_layers % num_stages:
        raise ValueError(
            f"{n_layers} layers not divisible by {num_stages} stages")
    per = n_layers // num_stages
    out = []
    for s in range(num_stages):
        sp: Dict[str, Any] = {
            "layers": {k: v[s * per:(s + 1) * per]
                       for k, v in layers.items()}}
        if s == 0:
            sp["embed"] = params["embed"]
        if s == num_stages - 1:
            sp["final_norm"] = params["final_norm"]
            sp["lm_head"] = params["lm_head"]
        out.append(sp)
    return out


def make_pipeline_stage_fn(cfg: LlamaConfig):
    """The uniform per-stage callable for ``train.pipeline_actors``:
    embeds on the stage holding ``embed`` (its input is then raw
    tokens), scans the stage's layer slice, and projects to logits on
    the stage holding ``lm_head``.  Key presence is trace-time static,
    so each stage jits to exactly its own program."""

    def stage_fn(sp, x):
        layer_fn = _make_layer_fn(cfg, None, None)
        if cfg.remat:
            layer_fn = jax.checkpoint(layer_fn)
        if "embed" in sp:
            x = jnp.take(sp["embed"], x, axis=0).astype(cfg.dtype)
        (x, _), _ = jax.lax.scan(
            layer_fn, (x, jnp.zeros((), jnp.float32)), sp["layers"])
        if "lm_head" in sp:
            x = rms_norm(x, sp["final_norm"])
            x = (x @ sp["lm_head"].astype(cfg.dtype)).astype(jnp.float32)
        return x

    return stage_fn


def make_pipeline_loss_fn(cfg: LlamaConfig):
    """Next-token cross-entropy over the last stage's logits — the
    same mean-NLL ``loss_fn`` computes, as a ``(logits, targets)``
    pair for the actor pipeline's loss stage."""

    def pipeline_loss(logits, targets):
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        return jnp.mean(nll)

    return pipeline_loss


def loss_fn(params: Dict[str, Any], batch: Dict[str, jax.Array],
            cfg: LlamaConfig, *, mesh: Optional[Mesh] = None,
            rules: Optional[LogicalAxisRules] = None,
            forward_fn=None) -> Tuple[jax.Array, Dict[str, Any]]:
    """Next-token cross-entropy.  batch: {"tokens": (b, s+1) int32} or
    {"inputs": (b, s), "targets": (b, s)}; returns (loss, metrics).

    ``forward_fn(params, inputs) -> (logits, aux)`` overrides the forward
    pass (e.g. the pipelined path) so there is exactly one loss definition.
    """
    if "tokens" in batch:
        inputs, targets = batch["tokens"][:, :-1], batch["tokens"][:, 1:]
    else:
        inputs, targets = batch["inputs"], batch["targets"]
    if forward_fn is None:
        logits, aux = forward(params, inputs, cfg, mesh=mesh, rules=rules)
    else:
        logits, aux = forward_fn(params, inputs)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    loss = jnp.mean(nll)
    total = loss + cfg.aux_loss_coef * aux
    return total, {"loss": loss, "aux_loss": aux,
                   "perplexity": jnp.exp(loss)}
