"""ray_tpu.models — model families shipped with the framework.

The reference ships model zoos inside RLlib (``rllib/models/``, torch/tf
nets + 299-LoC JAX stubs, SURVEY.md §2.4); the TPU build makes the flagship
an LLM family designed for mesh parallelism from the start.
"""

from ray_tpu.models.llama import (
    LlamaConfig,
    init_params,
    param_logical_axes,
    forward,
    loss_fn,
)

__all__ = ["LlamaConfig", "init_params", "param_logical_axes", "forward",
           "loss_fn"]
