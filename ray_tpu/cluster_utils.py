"""Multi-node cluster testing utilities.

Reference analog: ``python/ray/cluster_utils.py:99`` — ``Cluster`` boots a
real multi-node cluster on one machine (each ``add_node`` starts a separate
raylet + object store sharing the host) so multi-node scheduling, transfer
and failover logic run with no real cluster.

Two node flavours:

- ``add_node()`` — in-process ``NodeState`` (shares the head's object
  store); scheduler-visible only.  Cheapest, used by most tests.
- ``add_node(external=True)`` — a REAL ``node_agent`` subprocess
  (_private/node_agent.py) with its OWN shm directory, registering over
  TCP.  Workers leased there run in processes spawned by the agent, and
  objects move between stores through the transfer path — the honest
  multi-host simulation.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Dict, Optional

import ray_tpu


class Cluster:
    def __init__(self, head_num_cpus: int = 2, head_num_tpus: int = 0,
                 **init_kwargs):
        self.rt = ray_tpu.init(num_cpus=head_num_cpus,
                               num_tpus=head_num_tpus, **init_kwargs)
        self._agents: Dict[str, subprocess.Popen] = {}
        self._agent_dirs: list = []

    def add_node(self, num_cpus: float = 1.0, num_tpus: float = 0.0,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 external: bool = False, wait: bool = True,
                 env_overrides: Optional[Dict[str, str]] = None):
        if not external:
            return self.rt.add_node(num_cpus=num_cpus, num_tpus=num_tpus,
                                    resources=resources, labels=labels)
        r = {"CPU": float(num_cpus)}
        if num_tpus:
            r["TPU"] = float(num_tpus)
        if resources:
            r.update(resources)
        shm_dir = tempfile.mkdtemp(prefix="ray_tpu_node_")
        self._agent_dirs.append(shm_dir)
        env = dict(os.environ)
        if env_overrides:
            env.update(env_overrides)
        env.update({
            "RAY_TPU_HEAD_ADDRESS": self.rt.tcp_address,
            "RAY_TPU_AUTHKEY": self.rt._authkey.hex(),
            "RAY_TPU_AGENT_RESOURCES": json.dumps(r),
            "RAY_TPU_AGENT_SHM_DIR": shm_dir,
            "RAY_TPU_AGENT_LABELS": json.dumps(labels or {}),
        })
        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.node_agent"],
            env=env, cwd=pkg_root)
        before = {n["node_id"] for n in self.rt.list_nodes()}
        if wait:
            deadline = time.time() + 30
            while time.time() < deadline:
                now = [n for n in self.rt.list_nodes()
                       if n["node_id"] not in before and n["alive"]]
                if now:
                    node_id = now[0]["node_id"]
                    self._agents[node_id] = proc
                    return node_id
                time.sleep(0.05)
            raise TimeoutError("node agent did not register within 30s")
        return None

    def remove_node(self, node_id):
        from ray_tpu._private.ids import NodeID
        if isinstance(node_id, str):
            nid = NodeID(bytes.fromhex(node_id))
        else:
            nid = node_id
        self.rt.remove_node(nid)
        proc = self._agents.pop(
            node_id if isinstance(node_id, str) else node_id.hex(), None)
        if proc is not None:
            try:
                proc.wait(timeout=5)
            except Exception:
                proc.kill()

    def kill_agent(self, node_id: str):
        """Hard-kill a node agent process (chaos: reference
        test_utils.py:1687 kill_raylet)."""
        proc = self._agents.pop(node_id, None)
        if proc is not None:
            proc.kill()
            proc.wait(timeout=5)

    def shutdown(self):
        for proc in self._agents.values():
            try:
                proc.terminate()
            except Exception:
                pass
        ray_tpu.shutdown()
        for proc in self._agents.values():
            try:
                proc.wait(timeout=3)
            except Exception:
                proc.kill()
        self._agents.clear()
        import shutil
        for d in self._agent_dirs:
            shutil.rmtree(d, ignore_errors=True)
