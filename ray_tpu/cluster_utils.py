"""Multi-node cluster testing utilities.

Reference analog: ``python/ray/cluster_utils.py:99`` — ``Cluster`` boots a
real multi-node cluster on one machine (each ``add_node`` starts a separate
raylet + object store sharing the host) so multi-node scheduling, transfer
and failover logic run with no real cluster.

Three node/head flavours:

- ``add_node()`` — in-process ``NodeState`` (shares the head's object
  store); scheduler-visible only.  Cheapest, used by most tests.
- ``add_node(external=True)`` — a REAL ``node_agent`` subprocess
  (_private/node_agent.py) with its OWN shm directory, registering over
  TCP.  Workers leased there run in processes spawned by the agent, and
  objects move between stores through the transfer path — the honest
  multi-host simulation.
- ``Cluster(external_head=True)`` — the HEAD itself runs as a
  subprocess (_private/head_main.py) on a fixed port/authkey with GCS
  snapshotting armed, and this process attaches as a CLIENT.  This is
  the head-failover drill geometry: ``kill_head()`` SIGKILLs it,
  ``restart_head()`` re-runs it with ``gcs_restore`` — surviving
  agents, workers and this client reconnect-and-replay across the blip.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import threading
import time
from typing import Dict, Optional

import ray_tpu


class Cluster:
    def __init__(self, head_num_cpus: int = 2, head_num_tpus: int = 0,
                 external_head: bool = False,
                 head_env: Optional[Dict[str, str]] = None,
                 **init_kwargs):
        self._agents: Dict[str, subprocess.Popen] = {}
        self._agent_dirs: list = []
        self.head_proc: Optional[subprocess.Popen] = None
        self._external_head = external_head
        self._head_tail: list = []
        if not external_head:
            self.rt = ray_tpu.init(num_cpus=head_num_cpus,
                                   num_tpus=head_num_tpus, **init_kwargs)
            self._head_address = self.rt.tcp_address
            self._authkey_hex = self.rt._authkey.hex()
            return
        import socket

        sysconf = dict(init_kwargs.pop("_system_config", None) or {})
        if init_kwargs:
            raise ValueError(
                f"external_head supports configuration only via "
                f"_system_config / head_env; got {sorted(init_kwargs)}")
        if not sysconf.get("listen_port"):
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                sysconf["listen_port"] = s.getsockname()[1]
        sysconf.setdefault("authkey_hex", os.urandom(16).hex())
        if not sysconf.get("gcs_snapshot_path"):
            fd, snap = tempfile.mkstemp(prefix="ray_tpu_gcs_")
            os.close(fd)
            os.unlink(snap)  # the head writes it atomically
            sysconf["gcs_snapshot_path"] = snap
        sysconf.setdefault("gcs_snapshot_interval_s", 0.2)
        self._head_cfg = sysconf
        self._head_num_cpus = head_num_cpus
        self._head_num_tpus = head_num_tpus
        self._head_env = dict(head_env or {})
        self._start_head(restore=False)
        self._head_address = f"tcp://127.0.0.1:{sysconf['listen_port']}"
        self._authkey_hex = sysconf["authkey_hex"]
        self.rt = ray_tpu.init(address=self._head_address,
                               _authkey=self._authkey_hex)

    # ------------------------------------------------------ head lifecycle
    def _start_head(self, restore: bool):
        cfg = dict(self._head_cfg)
        cfg["gcs_restore"] = restore
        env = dict(os.environ)
        env.update(self._head_env)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["RAY_TPU_HEAD_NUM_CPUS"] = str(self._head_num_cpus)
        env["RAY_TPU_HEAD_NUM_TPUS"] = str(self._head_num_tpus)
        env["RAY_TPU_HEAD_SYSTEM_CONFIG"] = json.dumps(cfg)
        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get(
            "PYTHONPATH", "")
        proc = subprocess.Popen(
            [sys.executable, "-u", "-m", "ray_tpu._private.head_main"],
            env=env, cwd=pkg_root, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT)
        deadline = time.time() + 60
        while time.time() < deadline:
            line = proc.stdout.readline()
            if b"RAY_TPU_HEAD_READY" in line:
                break
            if proc.poll() is not None:
                raise AssertionError(
                    f"head process exited rc={proc.poll()}: {line!r}")
        else:
            proc.kill()
            raise TimeoutError("head process never printed READY")
        # Keep the pipe drained (worker-log reprints would otherwise
        # fill it and wedge the head); retain a bounded tail for
        # debugging.
        tail = self._head_tail

        def _drain(stream=proc.stdout):
            for ln in iter(stream.readline, b""):
                tail.append(ln)
                del tail[:-200]

        threading.Thread(target=_drain, daemon=True,
                         name="ray_tpu-head-drain").start()
        self.head_proc = proc

    @property
    def head_pid(self) -> Optional[int]:
        return self.head_proc.pid if self.head_proc is not None else None

    def kill_head(self) -> Optional[int]:
        """SIGKILL the external head — no atexit, no final snapshot, no
        graceful anything: the ``os._exit``-class crash the failover
        battery drills.  Returns the dead pid."""
        if self.head_proc is None:
            raise RuntimeError("kill_head needs Cluster(external_head"
                               "=True)")
        pid = self.head_proc.pid
        self.head_proc.kill()
        self.head_proc.wait(timeout=30)
        return pid

    def restart_head(self) -> Optional[int]:
        """Re-run the head on the SAME port/authkey with gcs_restore:
        agents, workers, and this cluster's client reconnect on their
        own.  Returns the new head pid."""
        if not self._external_head:
            raise RuntimeError("restart_head needs Cluster(external_head"
                               "=True)")
        self._start_head(restore=True)
        return self.head_proc.pid

    # ------------------------------------------------------------- nodes
    def add_node(self, num_cpus: float = 1.0, num_tpus: float = 0.0,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 external: bool = False, wait: bool = True,
                 env_overrides: Optional[Dict[str, str]] = None):
        if not external:
            return self.rt.add_node(num_cpus=num_cpus, num_tpus=num_tpus,
                                    resources=resources, labels=labels)
        r = {"CPU": float(num_cpus)}
        if num_tpus:
            r["TPU"] = float(num_tpus)
        if resources:
            r.update(resources)
        shm_dir = tempfile.mkdtemp(prefix="ray_tpu_node_")
        self._agent_dirs.append(shm_dir)
        env = dict(os.environ)
        if env_overrides:
            env.update(env_overrides)
        env.update({
            "RAY_TPU_HEAD_ADDRESS": self._head_address,
            "RAY_TPU_AUTHKEY": self._authkey_hex,
            "RAY_TPU_AGENT_RESOURCES": json.dumps(r),
            "RAY_TPU_AGENT_SHM_DIR": shm_dir,
            "RAY_TPU_AGENT_LABELS": json.dumps(labels or {}),
        })
        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.node_agent"],
            env=env, cwd=pkg_root)
        before = {n["node_id"] for n in self.rt.list_nodes()}
        if wait:
            deadline = time.time() + 30
            while time.time() < deadline:
                now = [n for n in self.rt.list_nodes()
                       if n["node_id"] not in before and n["alive"]]
                if now:
                    node_id = now[0]["node_id"]
                    self._agents[node_id] = proc
                    return node_id
                time.sleep(0.05)
            raise TimeoutError("node agent did not register within 30s")
        return None

    def remove_node(self, node_id):
        from ray_tpu._private.ids import NodeID
        if isinstance(node_id, str):
            nid = NodeID(bytes.fromhex(node_id))
        else:
            nid = node_id
        self.rt.remove_node(nid)
        proc = self._agents.pop(
            node_id if isinstance(node_id, str) else node_id.hex(), None)
        if proc is not None:
            try:
                proc.wait(timeout=5)
            except Exception:
                proc.kill()

    def kill_agent(self, node_id: str):
        """Hard-kill a node agent process (chaos: reference
        test_utils.py:1687 kill_raylet)."""
        proc = self._agents.pop(node_id, None)
        if proc is not None:
            proc.kill()
            proc.wait(timeout=5)

    def shutdown(self):
        # Snapshot: a concurrent remove_node (an autoscaler's off-thread
        # scale-down concluding mid-teardown) pops from _agents.
        for proc in list(self._agents.values()):
            try:
                proc.terminate()
            except Exception:
                pass
        ray_tpu.shutdown()
        if self.head_proc is not None:
            try:
                self.head_proc.terminate()
                self.head_proc.wait(timeout=10)
            except Exception:
                try:
                    self.head_proc.kill()
                except Exception:
                    pass
            snap = self._head_cfg.get("gcs_snapshot_path")
            if snap:
                try:
                    os.unlink(snap)
                except OSError:
                    pass
        for proc in list(self._agents.values()):
            try:
                proc.wait(timeout=3)
            except Exception:
                proc.kill()
        self._agents.clear()
        import shutil
        for d in self._agent_dirs:
            shutil.rmtree(d, ignore_errors=True)
