"""Host-side serving memory plane: block allocator, prefix cache, and
the paged-KV admission engine for the continuous batcher.

Reference: vLLM's PagedAttention block manager (SOSP'23).  Device KV
memory is carved into fixed-size blocks; every live request owns a
*block table* (its ordered list of physical block ids) instead of a
``max_seq_len`` reservation, so a replica's admission capacity is
bounded by tokens actually resident, not by the worst-case sequence
length.  Three cooperating pieces:

``BlockAllocator``
    A free-list of physical block ids with per-block refcounts.
    ``alloc`` is all-or-nothing (admission either fully fits or parks);
    a block returns to the free list when its last reference drops.

``PrefixCache``
    Prompt-prefix hash -> block-chain map with refcounts: N requests
    sharing a system prompt map the SAME physical blocks.  Entries are
    registered only AFTER the owning request's prefill materialized the
    block contents (an entry must never point at unfilled blocks), keyed
    at every block boundary of the prompt plus its full length so a
    longer prompt can reuse a shorter prompt's chain.  Entries hold
    their own references; LRU entries are reclaimed when admission runs
    dry.  Divergence inside a shared partial block is handled by
    copy-on-write: the uniform rule is "a write into a block with
    refcount > 1 moves to a fresh copy" (``plan_writes``), which is
    sound because canonical prefill always lands in refcount-1 blocks.

``PagedKVEngine``
    Glues both into the ``_ContinuousBatcher`` admission path and keeps
    the serving-memory counters (prefix_hits / prefix_blocks_shared /
    cow_copies / spec_proposed / spec_accepted / tokens_emitted /
    admission_parks).

LOCK ORDER: the engine is EXTERNALLY SYNCHRONIZED by the batcher's
documented leaf lock.  ``*_locked`` methods assume the caller (the
batcher's admission/retire/stats paths) already holds it; the public
step-side methods (``plan_writes`` / ``register_prefix`` /
``note_tokens`` / ``note_spec``) acquire the SAME lock via ``bind()``.
The engine never creates a lock of its own and never calls out while
the guard is held, so the batcher lock keeps its zero-outgoing-edge
leaf pin (tests/test_lockcheck.py).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple


class RequestTooLarge(ValueError):
    """A request's whole block budget exceeds the TOTAL pool: it could
    never be admitted even against an empty cache, so parking it would
    wedge the FIFO queue head forever.  Raised to the submitting caller;
    the batcher keeps draining the requests behind it."""


class BlockAllocator:
    """Free-list allocator over ``num_blocks`` fixed-size KV blocks.

    Externally synchronized (see module docstring).
    """

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 1 or block_size < 1:
            raise ValueError("num_blocks and block_size must be >= 1")
        self.num_blocks = num_blocks
        self.block_size = block_size
        # LIFO free list: recently freed blocks are re-used first (their
        # device pages are the warmest).
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        self._ref = [0] * num_blocks

    @property
    def available(self) -> int:
        return len(self._free)

    @property
    def used(self) -> int:
        return self.num_blocks - len(self._free)

    def ref(self, block: int) -> int:
        return self._ref[block]

    def alloc(self, n: int) -> Optional[List[int]]:
        """All-or-nothing: ``n`` fresh blocks (refcount 1) or ``None``."""
        if n < 0:
            raise ValueError("negative block request")
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._ref[b] = 1
        return out

    def incref(self, block: int) -> None:
        if self._ref[block] <= 0:
            raise ValueError(f"incref of free block {block}")
        self._ref[block] += 1

    def free(self, blocks) -> None:
        """Drop one reference per block; refcount 0 returns it to the
        free list."""
        for b in blocks:
            r = self._ref[b] - 1
            if r < 0:
                raise ValueError(f"double free of block {b}")
            self._ref[b] = r
            if r == 0:
                self._free.append(b)


class _PrefixEntry:
    __slots__ = ("blocks", "n_tokens")

    def __init__(self, blocks: Tuple[int, ...], n_tokens: int):
        self.blocks = blocks
        self.n_tokens = n_tokens


class PrefixCache:
    """Prompt-prefix -> block-chain map (see module docstring).

    Keys are the prefix token tuples themselves (python hashing); an
    entry covering ``L`` tokens holds ``ceil(L / block_size)`` block
    references, the last block possibly partial.
    """

    def __init__(self, allocator: BlockAllocator):
        self._alloc = allocator
        self._entries: "OrderedDict[tuple, _PrefixEntry]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._entries)

    def lookup(self, prompt: Tuple[int, ...]) -> Tuple[List[int], int]:
        """Longest cached prefix of ``prompt``: ``(blocks, n_tokens)``
        with references ALREADY taken on the returned blocks (the
        caller's admission owns them), or ``([], 0)``."""
        bs = self._alloc.block_size
        n = len(prompt)
        # Candidate lengths, longest first: the full prompt, then every
        # block boundary below it (registration inserts exactly these
        # forms, plus foreign prompts' full lengths — probed implicitly
        # when they sit at our boundaries; a non-boundary foreign match
        # is found via the full-prompt probe of ITS length only when
        # lengths coincide, which is fine: boundary-granular reuse is
        # the contract, the full-length probe is opportunistic).
        cands = [n] + list(range((n // bs) * bs - (0 if n % bs else bs),
                                 0, -bs))
        for L in cands:
            e = self._entries.get(tuple(prompt[:L]))
            if e is None:
                continue
            self._entries.move_to_end(tuple(prompt[:L]))
            for b in e.blocks:
                self._alloc.incref(b)
            return list(e.blocks), e.n_tokens
        return [], 0

    def insert(self, prompt: Tuple[int, ...], blocks: List[int]) -> int:
        """Register the (already prefilled) chain for ``prompt`` under
        its full length and every block boundary.  Existing keys are
        kept (first writer wins — identical prefix tokens imply
        identical block contents).  Returns entries added."""
        bs = self._alloc.block_size
        n = len(prompt)
        added = 0
        lengths = list(range(bs, n + 1, bs))
        if not lengths or lengths[-1] != n:
            lengths.append(n)
        for L in lengths:
            key = tuple(prompt[:L])
            if key in self._entries:
                self._entries.move_to_end(key)
                continue
            chain = tuple(blocks[: -(-L // bs)])
            for b in chain:
                self._alloc.incref(b)
            self._entries[key] = _PrefixEntry(chain, L)
            added += 1
        return added

    def reclaim(self, need: int) -> int:
        """Drop LRU entries (releasing their block references) until the
        allocator can satisfy ``need`` free blocks or the cache is
        empty.  Returns entries dropped."""
        dropped = 0
        while self._alloc.available < need and self._entries:
            _, e = self._entries.popitem(last=False)
            self._alloc.free(e.blocks)
            dropped += 1
        return dropped

    def clear(self) -> None:
        while self._entries:
            _, e = self._entries.popitem(last=False)
            self._alloc.free(e.blocks)


class SlotKV:
    """Per-admitted-request paged-memory plan, attached as ``slot.kv``."""

    __slots__ = ("blocks", "prompt", "max_new", "n_cached", "spares",
                 "registered", "freed")

    def __init__(self, blocks: List[int], prompt: Tuple[int, ...],
                 max_new: int, n_cached: int,
                 spares: Optional[List[int]] = None):
        self.blocks = blocks          # physical chain, mutated by CoW
        self.prompt = prompt
        self.max_new = max_new
        self.n_cached = n_cached      # positions [0, n_cached) shared
        # Copy-on-write reserve, allocated WITH the admission budget so
        # a divergence inside a shared partial block can never fail
        # mid-decode (the pool may be fully committed to other slots).
        self.spares = spares or []
        self.registered = False
        self.freed = False


class ChainExport:
    """A finished prefill's block chain pinned for streaming.

    Created by ``PagedKVEngine.export_chain`` AT retirement time on the
    prefill side of a disaggregated tier: the export takes its OWN
    reference on every prompt block (shared-prefix blocks export by
    reference-into-the-chain — no copy, the incref alone keeps them),
    so the chain's device pages stay immutable while the pusher streams
    them even after the slot itself retires.  Copy-on-write preserves
    the content guarantee: any later writer into one of these blocks
    sees refcount > 1 and diverges to a fresh copy, never mutating the
    exported pages.  ``release_export`` drops the references
    (idempotent — the streaming path releases in a ``finally`` and the
    chaos path may release again on teardown).
    """

    __slots__ = ("blocks", "prompt", "released")

    def __init__(self, blocks: Tuple[int, ...], prompt: Tuple[int, ...]):
        self.blocks = blocks
        self.prompt = prompt
        self.released = False


class PagedKVEngine:
    """Admission gate + memory accounting for one paged batcher.

    ``tokens_for(request) -> (prompt_tokens, max_new_tokens)`` is the
    deployment's sizing hook: admission reserves
    ``ceil((len(prompt) + max_new + spec_slack) / block_size)`` blocks
    up front (alloc on admit / free on retire — a mid-decode request can
    therefore never run out), counting cached prefix blocks as free
    reuse, plus one copy-on-write reserve block whenever prefix caching
    is on.
    """

    def __init__(self, num_blocks: int, block_size: int, *,
                 tokens_for: Callable[[Any], Tuple[tuple, int]],
                 prefix_caching: bool = True,
                 max_slots: Optional[int] = None,
                 spec_slack: int = 0):
        self.allocator = BlockAllocator(num_blocks, block_size)
        self.prefix: Optional[PrefixCache] = (
            PrefixCache(self.allocator) if prefix_caching else None)
        self._tokens_for = tokens_for
        self.block_size = block_size
        self.spec_slack = max(0, int(spec_slack))
        # Hard cap on live slots; blocks are the real bound, this keeps
        # padded device batches sane.
        self.max_slots = max_slots if max_slots else num_blocks
        # Guard: REPLACED by the owning batcher's leaf lock at bind().
        self._guard = threading.Lock()  # lock-order: leaf
        # Counters (mutated under the guard; int reads are GIL-atomic).
        self.prefix_hits = 0
        self.prefix_blocks_shared = 0
        self.cow_copies = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.tokens_emitted = 0
        self.admission_parks = 0
        self.admission_rejects = 0
        # Disaggregation counters: chains exported/imported by THIS
        # engine plus the bytes its exports streamed over the put path.
        # All three stay zero when disaggregated_serving is off (the
        # export/import verbs are only driven by the split tier).
        self.kv_chains_exported = 0
        self.kv_chains_imported = 0
        self.kv_chain_bytes_streamed = 0
        # Live (unreleased) ChainExports — the chaos tests' leak gauge.
        self.exports_outstanding = 0
        # Park EPISODES, not boundary re-checks: the continuous loop
        # re-tries the parked queue head every boundary, and counting
        # each retry would inflate the counter by ~steps-parked.
        self._last_parked: Any = None

    # -- batcher-side (caller holds the batcher leaf lock) ----------------
    def bind(self, lock) -> None:
        """Adopt the owning batcher's leaf lock as the step-side guard:
        one lock then covers admission, retirement, and step-side write
        planning — the 'admission re-checks availability under the
        batcher leaf lock' convention."""
        self._guard = lock

    def try_admit_locked(self, slot) -> bool:
        """Reserve the request's whole block budget.  On exhaustion,
        reclaim idle prefix-cache entries; if still short, PARK (return
        False).  The one exception: a budget larger than the TOTAL pool
        can never fit and raises ``RequestTooLarge`` (parking it would
        wedge the FIFO head forever)."""
        prompt, max_new = self._tokens_for(slot.request)
        prompt = tuple(prompt)
        total = len(prompt) + max_new + self.spec_slack
        n_blocks = -(-max(1, total) // self.block_size)
        # Worst-case FRESH need across cache states: no hit costs
        # n_blocks (+1 spare for a partial prompt block); a mid-block
        # hit adds the second spare but always offsets it with >= 1
        # shared (non-allocated) block.  If even a fully drained pool
        # could not hold that, fail fast to the caller.
        worst = n_blocks + (1 if self.prefix is not None
                            and len(prompt) % self.block_size else 0)
        if worst > self.allocator.num_blocks:
            self.admission_rejects += 1
            raise RequestTooLarge(
                f"request needs {worst} KV blocks "
                f"({total} tokens @ block_size={self.block_size}) but "
                f"the pool holds {self.allocator.num_blocks}")
        shared: List[int] = []
        n_cached = 0
        if self.prefix is not None:
            shared, n_cached = self.prefix.lookup(prompt)
        # Slot-owned CoW reserve: one spare per potential divergence —
        # the prefill write into a shared PARTIAL prefix block, and the
        # first generated-token write into the slot's own partial
        # prompt block after registration re-shares it.  Reserved with
        # the admission budget (the pool may be fully committed to
        # other slots by the time the write happens) so plan_writes is
        # failure-free mid-decode.
        bs = self.block_size
        n_spares = 0
        if self.prefix is not None:
            if n_cached % bs and n_cached < len(prompt):
                n_spares += 1
            if len(prompt) % bs:
                n_spares += 1
        n_fresh = n_blocks - len(shared)
        need = n_fresh + n_spares
        if self.allocator.available < need and self.prefix is not None:
            self.prefix.reclaim(need)
        fresh = self.allocator.alloc(need)
        if fresh is None:
            if shared:
                self.allocator.free(shared)
            if slot is not self._last_parked:
                self.admission_parks += 1
                self._last_parked = slot
            return False
        self._last_parked = None
        if shared:
            self.prefix_hits += 1
            self.prefix_blocks_shared += len(shared)
        slot.kv = SlotKV(shared + fresh[:n_fresh], prompt, max_new,
                         n_cached, spares=fresh[n_fresh:])
        return True

    def retire_locked(self, slot) -> None:
        kv = getattr(slot, "kv", None)
        if kv is None or kv.freed:
            return
        kv.freed = True
        self.allocator.free(kv.blocks)
        if kv.spares:
            self.allocator.free(kv.spares)

    def stats_locked(self) -> Dict[str, Any]:
        total = self.allocator.num_blocks
        used = self.allocator.used
        return {
            "kv_blocks_total": total,
            "kv_blocks_used": used,
            "kv_occupancy": round(used / total, 3) if total else 0.0,
            "prefix_hits": self.prefix_hits,
            "prefix_blocks_shared": self.prefix_blocks_shared,
            "cow_copies": self.cow_copies,
            "spec_proposed": self.spec_proposed,
            "spec_accepted": self.spec_accepted,
            "tokens_emitted": self.tokens_emitted,
            "admission_parks": self.admission_parks,
            "admission_rejects": self.admission_rejects,
            "kv_chains_exported": self.kv_chains_exported,
            "kv_chains_imported": self.kv_chains_imported,
            "kv_chain_bytes_streamed": self.kv_chain_bytes_streamed,
        }

    # -- step-side (called from the step function, no lock held) ----------
    def plan_writes(self, slot, start: int,
                    count: int) -> Tuple[List[Tuple[int, int]],
                                         List[Tuple[int, int]]]:
        """Physical ``(block, offset)`` targets for token positions
        ``[start, start + count)`` of this slot, applying copy-on-write:
        a target block with refcount > 1 (shared through the prefix
        cache) is swapped for a fresh block first.  Returns
        ``(writes, cow_pairs)``; for every ``(old, new)`` in
        ``cow_pairs`` the caller must copy the device block old -> new
        BEFORE issuing the writes."""
        bs = self.block_size
        with self._guard:
            kv = slot.kv
            writes: List[Tuple[int, int]] = []
            cow: List[Tuple[int, int]] = []
            for p in range(start, start + count):
                j = p // bs
                blk = kv.blocks[j]
                if self.allocator.ref(blk) > 1:
                    if kv.spares:
                        new = kv.spares.pop()
                    else:
                        # Admission reserves one spare per potential
                        # divergence, so this fallback is only for
                        # engines driven outside that contract.
                        repl = self.allocator.alloc(1)
                        if repl is None:
                            raise MemoryError(
                                "paged KV: copy-on-write with no free "
                                "block (admission reserve accounting "
                                "bug)")
                        new = repl[0]
                    self.allocator.free([blk])
                    kv.blocks[j] = new
                    cow.append((blk, new))
                    self.cow_copies += 1
                    blk = new
                writes.append((blk, p % bs))
            return writes, cow

    def block_table(self, slot) -> List[int]:
        with self._guard:
            return list(slot.kv.blocks)

    def register_prefix(self, slot) -> None:
        """Publish this slot's (fully prefilled) prompt chain into the
        prefix cache.  Call AFTER the prefill writes landed on device —
        an entry must never alias unwritten blocks."""
        if self.prefix is None:
            return
        with self._guard:
            kv = slot.kv
            if kv.registered or not kv.prompt or kv.freed:
                return
            kv.registered = True
            self.prefix.insert(kv.prompt, kv.blocks)

    # -- disaggregated chain handoff (step-side, no lock held) ------------
    def export_chain(self, slot) -> Optional[ChainExport]:
        """Pin this slot's prompt block chain for streaming to a decode
        replica.  Takes one export-owned reference per prompt block, so
        the chain survives the slot's retirement (retire frees the
        SLOT's references; the export's keep the pages resident and,
        via the CoW rule, immutable).  Returns ``None`` when the slot
        has no live paged state.  Call after the prefill writes landed
        (same ordering contract as ``register_prefix``)."""
        with self._guard:
            kv = getattr(slot, "kv", None)
            if kv is None or kv.freed or not kv.prompt:
                return None
            chain = tuple(kv.blocks[: -(-len(kv.prompt)
                                        // self.block_size)])
            for b in chain:
                self.allocator.incref(b)
            self.kv_chains_exported += 1
            self.exports_outstanding += 1
            return ChainExport(chain, kv.prompt)

    def release_export(self, exp: Optional[ChainExport]) -> None:
        """Drop an export's block references (idempotent)."""
        if exp is None:
            return
        with self._guard:
            if exp.released:
                return
            exp.released = True
            self.exports_outstanding -= 1
            self.allocator.free(exp.blocks)

    def note_chain_streamed(self, nbytes: int) -> None:
        """Account one export's segment image leaving this replica."""
        with self._guard:
            self.kv_chain_bytes_streamed += nbytes

    def note_chain_imported(self) -> None:
        """Account one streamed chain adopted under THIS allocator (the
        decode-side join path wrote its pages into normally-admitted
        blocks, so ownership/CoW rules apply unchanged)."""
        with self._guard:
            self.kv_chains_imported += 1

    def note_tokens(self, n: int) -> None:
        with self._guard:
            self.tokens_emitted += n

    def note_spec(self, proposed: int, accepted: int) -> None:
        with self._guard:
            self.spec_proposed += proposed
            self.spec_accepted += accepted
