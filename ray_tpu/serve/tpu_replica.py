"""TPU-resident mesh-sharded decode replica.

The serving capability target (SURVEY §7 step 9): a deployment whose
weights LIVE on the device mesh across requests, with a jitted,
NamedSharding-annotated decode step driven by the continuous-batching
engine (continuous.py) — the SNIPPETS [1]/[3] pattern: build a logical
device mesh with named axes, annotate tensors with
``NamedSharding(mesh, PartitionSpec(...))``, and let ``jax.jit`` insert
the collectives.  On a single CPU device the mesh degrades to ``(1,)``
and everything still runs — which is how the test tree exercises it.

Decode state is DEVICE-RESIDENT: the ``(MAX_BATCH, embed)`` hidden
matrix never round-trips the host between steps — each jitted step
consumes the previous step's output array directly.  The host touches
the device exactly twice per iteration, both overlapped with compute:

1. Joining requests' initial hidden vectors go up as a masked
   ``(MAX_BATCH, embed)`` update issued BEFORE the previous step's
   tokens are forced — the host→device copy for step *t+1*'s joiners is
   double-buffered against running step *t* (jax dispatch is async).
2. The PREVIOUS step's token vector is forced (device→host) to retire
   finished requests; the step just dispatched keeps the device busy
   behind it.

Because tokens are forced one step late, a request finishes one batcher
step after its last token was computed — the classic pipeline-latency
trade for keeping the device hot.  A retiring request's row may
additionally run one speculative step; the overshoot is dropped at
retire time.

Weights are integer-valued float32 (drawn once from ``seed``, rounded):
every matmul below float32's 2^24 integer window is EXACT, so the
decoded chains are bit-independent of BLAS/XLA reduction order and the
test tree can pin them against a plain host-side reference loop.

PAGED DECODE MODE (``paged_kv`` knob; reference: vLLM PagedAttention
SOSP'23 + Leviathan et al. ICML'23): per-request decode state moves
from a dense ``(MAX_BATCH, embed)`` row reservation into a pool of
fixed-size KV blocks (``kv_cache.PagedKVEngine``) — admission is then
bounded by blocks (tokens actually resident), not slots, and the
batcher packs skewed-length batches.  Each position's value row is the
emitted token's embedding; every step reads the live requests' LAST
rows back THROUGH the paged cache with the ``ops.paged_attention``
pallas kernel (``window=1`` — softmax over one position is exactly 1.0,
so the gather is bitwise) and advances each chain with the same
integer-exact ``x @ W`` argmax the dense path uses: greedy chains stay
bitwise-identical to ``reference_decode``.  On top of it:

- Shared-prefix reuse (``prefix_caching``): prompt token lists are
  prefilled once; block chains are registered per prompt-prefix hash
  and later requests map the SAME physical blocks (copy-on-write on
  first divergence inside a shared partial block).
- Speculative decoding (``speculative_k=k``): a draft model (a
  perturbed integer copy of the projection — cheap, mostly-agreeing)
  proposes k tokens per step host-side; the target verifies all of
  them in ONE batched forward and the accepted prefix plus the
  correction token retire together — multiple tokens per replica step,
  bitwise-unchanged greedy output because acceptance is exact-match.

DISAGGREGATED SERVING (``disaggregated_serving`` knob; reference:
DistServe OSDI'24 / Splitwise ISCA'24): the same class serves both
halves of a split tier.  A PREFILL replica admits requests tagged
``_prefill_only`` — prompt blocks are written, the chain registered,
and the slot finishes the SAME step with a pinned ``ChainExport``
(max_new = 0: prefill replicas never run decode phases).
``prefill_export`` then lays the chain out as a segment image (pages +
block table metadata) and streams it into the decode replica's node
store over the ``reserve_put``/``put_range``/``commit_put`` verbs.  A
DECODE replica (``disagg_generate``) adopts the streamed chain: the
join path writes the imported PAGE ROWS (not recomputed embeddings)
into normally-admitted blocks, so ownership/CoW/prefix-registration
rules apply unchanged and the decoded chain stays bitwise-identical to
the monolithic engine.  With the knob off nothing here runs — the
monolithic paths above are byte-identical and every chain counter
stays zero.

Request format: ``{"prompt": int | [int, ...], "tokens": int}`` → list
of ``tokens`` greedily decoded token ids (the dense path takes the
``int`` form only; decode continues from the LAST prompt token).
Requests carrying ``"_timing": True`` (+ a client ``"_t0"`` wall
clock) finish with ``{"tokens": [...], "ttft": seconds}`` instead —
the bench's time-to-first-token probe.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.serve.batching import batch

MAX_BATCH = 8


class MeshShardedDecoder:
    """Deployment-ready greedy decoder with mesh-resident weights."""

    def __init__(self, embed: int = 32, vocab: int = 64, seed: int = 0,
                 paged: Optional[bool] = None, kv_blocks: int = 32,
                 kv_block_size: int = 8, max_slots: int = 16,
                 speculative_k: Optional[int] = None,
                 prefix_caching: Optional[bool] = None,
                 use_kernel: bool = True,
                 prefill_ms_per_token: float = 0.0):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import Mesh, NamedSharding
        from jax.sharding import PartitionSpec as P

        self._np = np
        self._jax = jax
        devs = np.asarray(jax.devices())
        n = len(devs)
        # Logical 1-D "model" mesh over every visible device; the vocab
        # (output) dimension shards across it.
        self._mesh = Mesh(devs.reshape(-1), ("model",))
        vocab = ((vocab + n - 1) // n) * n  # divisible over the axis
        kw, ke = jax.random.split(jax.random.PRNGKey(seed))
        w = jnp.round(jax.random.normal(kw, (embed, vocab)) * 4.0)
        emb = jnp.round(jax.random.normal(ke, (vocab, embed)) * 4.0)
        # RESIDENT across requests: the projection is sharded over the
        # model axis, the embedding table replicated (it is read by
        # token id — gather-heavy, cheap to mirror).
        self._w = jax.device_put(
            w.astype(jnp.float32),
            NamedSharding(self._mesh, P(None, "model")))
        self._emb = jax.device_put(
            emb.astype(jnp.float32), NamedSharding(self._mesh, P()))
        self._in_sharding = NamedSharding(self._mesh, P())
        # Host mirrors for slot-state init and the reference loop.
        self._w_host = np.asarray(self._w)
        self._emb_host = np.asarray(self._emb)
        self._embed = embed
        self._vocab = vocab

        @jax.jit
        def step(w, emb_t, x, join_x, join_mask):
            # Joining rows overwrite their hidden state; x is otherwise
            # the previous step's device output.  Logits shard over
            # "model" via w's sharding — the compiler inserts the
            # gather for the argmax reduction.
            x = jnp.where(join_mask, join_x, x)
            logits = x @ w
            tok = jnp.argmax(logits, axis=-1)
            nxt = emb_t[tok]
            return tok, nxt

        self._step = step
        # Device-resident hidden states, one row per batch slot.
        self._dev_x = jax.device_put(
            np.zeros((MAX_BATCH, embed), np.float32), self._in_sharding)
        # row -> owning Slot (host-side occupancy map).
        self._rows: List[Optional[Any]] = [None] * MAX_BATCH
        # Last dispatched step: (token device array, [(row, slot)]).
        self._pending = None

        # -- paged decode mode (serving memory plane) ------------------
        from ray_tpu._private.config import GLOBAL_CONFIG as _CFG

        self._paged = _CFG.paged_kv if paged is None else paged
        self._spec_k = max(0, (_CFG.speculative_k if speculative_k is None
                               else speculative_k))
        self._use_kernel = use_kernel
        # Synthetic prefill cost (seconds per 1000 prompt tokens written
        # by RECOMPUTED prefill — imported chains pay nothing, their
        # cost was paid on the prefill replica).  0 = off; the bench
        # turns it on to make the monolithic interleave stall
        # measurable.
        self._prefill_ms = max(0.0, float(prefill_ms_per_token))
        # Disaggregated-serving bookkeeping: the pool tag the controller
        # assigned, a cached ingest descriptor, and handoff fallback
        # counters.  The lock is a documented LEAF (pinned in
        # tests/test_lockcheck.py): it guards only these dict/attr
        # mutations and never wraps an out-call.
        self._serve_role: Optional[str] = None
        self._ingest_info: Optional[Dict[str, Any]] = None
        self._chain_stats = {"inline_fallbacks": 0, "handoff_retries": 0}
        self._chain_lock = threading.Lock()  # lock-order: leaf
        if self._paged:
            from ray_tpu.serve.kv_cache import PagedKVEngine

            self._kv_engine = PagedKVEngine(
                kv_blocks, kv_block_size, tokens_for=self._tokens_for,
                prefix_caching=(_CFG.prefix_caching if prefix_caching
                                is None else prefix_caching),
                max_slots=max_slots)
            # The batching decorator picks this attribute up and wires
            # block-gated admission into the continuous batcher.
            self.serve_kv_engine = self._kv_engine
            # Device-resident paged value cache: one (block_size, 1,
            # embed) page per block, replicated over the mesh (read by
            # position — gather-heavy, like the embedding table).
            self._kv_cache = jax.device_put(
                np.zeros((kv_blocks, kv_block_size, 1, embed),
                         np.float32), self._in_sharding)
            # Draft model: a perturbed integer copy of the projection —
            # mostly agrees with the target (that is the whole game of
            # speculative decoding), still integer-exact.
            kd = jax.random.PRNGKey(seed + 1)
            self._wd_host = np.asarray(
                self._w_host
                + np.asarray(jnp.round(
                    jax.random.normal(kd, self._w_host.shape) * 0.7)),
                np.float32)

    # -- paged-mode helpers -------------------------------------------------
    def _tokens_for(self, request) -> Any:
        """Admission sizing hook: (prompt token tuple, max new tokens).
        Prefill-only requests (disaggregated handoff) reserve ZERO
        decode tokens — their slot finishes at the end of its own join
        step."""
        body = request or {}
        prompt = body.get("prompt", 0)
        if isinstance(prompt, (list, tuple)):
            ids = tuple(int(t) % self._vocab for t in prompt) or (0,)
        else:
            ids = (int(prompt) % self._vocab,)
        if body.get("_prefill_only"):
            return ids, 0
        return ids, max(1, int(body.get("tokens", 1)))

    # -- continuous decode step (called by the batching engine) ------------
    def _force_pending(self):
        """Force the previously dispatched step's tokens (device→host),
        append them to their slots and finish slots that reached their
        requested length."""
        np = self._np
        if self._pending is None:
            return
        tok_dev, rows = self._pending
        self._pending = None
        tok = np.asarray(tok_dev)
        for r, slot in rows:
            if slot.finished:
                continue  # speculative overshoot for a retired slot
            st = slot.state
            st["out"].append(int(tok[r]))
            if len(st["out"]) >= st["need"]:
                slot.finish(list(st["out"][:st["need"]]))

    # -- paged decode step --------------------------------------------------
    def _apply_cache_writes(self, cow_pairs, blocks, offs, vals):
        """Device updates for one phase: copy-on-write block copies
        FIRST (they must preserve shared content before private writes
        land), then one scatter of the new value rows."""
        import jax.numpy as jnp
        np = self._np
        if cow_pairs:
            olds = jnp.asarray([o for o, _ in cow_pairs], jnp.int32)
            news = jnp.asarray([n for _, n in cow_pairs], jnp.int32)
            self._kv_cache = self._kv_cache.at[news].set(
                self._kv_cache[olds])
        if blocks:
            self._kv_cache = self._kv_cache.at[
                jnp.asarray(blocks, jnp.int32),
                jnp.asarray(offs, jnp.int32), 0].set(
                    jnp.asarray(np.stack(vals)))

    def _read_last(self, live):
        """Gather every live request's LAST value row back through the
        paged cache — the ops.paged_attention block-table data path.
        ``window=1`` makes the softmax exactly 1.0, so the result is
        bitwise the stored row (= emb[last token])."""
        import jax.numpy as jnp

        from ray_tpu.ops.paged_attention import (
            paged_attention, paged_attention_reference)
        np = self._np
        eng = self._kv_engine
        tables = [eng.block_table(s) for s in live]
        width = max(len(t) for t in tables)
        bt = np.zeros((len(live), width), np.int32)
        for i, t in enumerate(tables):
            bt[i, : len(t)] = t
        cl = np.asarray([s.state["pos"] for s in live], np.int32)
        q = np.zeros((len(live), 1, self._embed), np.float32)
        fn = paged_attention if self._use_kernel \
            else paged_attention_reference
        out = fn(jnp.asarray(q), self._kv_cache, self._kv_cache,
                 jnp.asarray(bt), jnp.asarray(cl), window=1)
        return np.asarray(out)[:, 0, :]

    def _paged_step(self, slots):
        """One iteration of the paged engine: prefill joiners into their
        blocks (skipping shared-prefix positions), read last rows via
        the paged kernel, draft + verify ``spec_k`` tokens in one
        batched forward, and retire the accepted prefix."""
        import jax.numpy as jnp
        np = self._np
        eng = self._kv_engine
        k = self._spec_k
        # Phase 1: join + prefill.  Positions [0, n_cached) are mapped
        # from the prefix cache and never rewritten; the rest of the
        # prompt scatters into this request's (fresh or CoW'd) blocks.
        cow, wb, wo, wv = [], [], [], []
        joiners = []
        n_prefill_toks = 0
        for s in slots:
            if s.state is not None:
                continue
            kvp = s.kv
            body = s.request or {}
            imp = body.get("_import")
            s.state = {"pos": len(kvp.prompt), "out": [],
                       "need": kvp.max_new,
                       "last": (int(imp["last"]) if imp is not None
                                else kvp.prompt[-1])}
            lo = kvp.n_cached
            if lo < len(kvp.prompt):
                writes, cw = eng.plan_writes(s, lo, len(kvp.prompt) - lo)
                cow += cw
                if imp is not None:
                    # Streamed-chain adoption: value rows come from the
                    # prefill replica's exported PAGES, not recomputed
                    # embeddings — the handoff genuinely rides the data
                    # plane (bitwise-identical here because each page
                    # row IS the token's embedding row).
                    pages, sbs = imp["pages"], int(imp["src_bs"])
                    for (blk, off), p in zip(
                            writes, range(lo, len(kvp.prompt))):
                        wb.append(blk)
                        wo.append(off)
                        wv.append(pages[p // sbs, p % sbs, 0])
                    eng.note_chain_imported()
                else:
                    for (blk, off), tok in zip(writes, kvp.prompt[lo:]):
                        wb.append(blk)
                        wo.append(off)
                        wv.append(self._emb_host[tok])
                    n_prefill_toks += len(kvp.prompt) - lo
            joiners.append(s)
        if n_prefill_toks and self._prefill_ms:
            # Synthetic prefill compute: the whole step stalls behind it
            # — exactly the monolithic interleave cost the split moves
            # off the decode replicas.
            time.sleep(self._prefill_ms * n_prefill_toks / 1000.0)
        self._apply_cache_writes(cow, wb, wo, wv)
        for s in joiners:
            # Publish AFTER the prefill scatter: a prefix-cache entry
            # must never alias unwritten blocks.
            eng.register_prefix(s)
            if (s.request or {}).get("_prefill_only") and not s.finished:
                # Prefill-only slots finish NOW with their chain pinned
                # for streaming: they never reach the decode phases, so
                # a prefill replica runs prompt-only steps.
                s.finish(eng.export_chain(s))
        live = [s for s in slots if not s.finished]
        if not live:
            return
        # Phase 2: last rows through the paged cache (bitwise gather).
        last = self._read_last(live)                       # (B, embed)
        # Phase 3: draft k tokens per request (host, integer-exact),
        # then verify ALL of them in ONE batched target forward:
        # position j's logits come from token j-1's value row, so row 0
        # is the cache-gathered last row and rows 1..k are the drafts'
        # embeddings.
        drafts = []
        for s in live:
            t = s.state["last"]
            chain = []
            for _ in range(k):
                t = int(np.argmax(self._emb_host[t] @ self._wd_host))
                chain.append(t)
            drafts.append(chain)
        verify = np.empty((len(live), k + 1, self._embed), np.float32)
        verify[:, 0, :] = last
        for i, chain in enumerate(drafts):
            for j, t in enumerate(chain):
                verify[i, j + 1] = self._emb_host[t]
        logits = jnp.asarray(verify) @ self._w     # sharded over "model"
        target = np.asarray(jnp.argmax(logits, axis=-1))   # (B, k+1)
        # Phase 4: exact-match acceptance — emitted tokens are the
        # matching draft prefix plus the target's correction token,
        # which is by construction the plain greedy chain.
        cow, wb, wo, wv = [], [], [], []
        for i, s in enumerate(live):
            st = s.state
            room = st["need"] - len(st["out"])
            usable = min(k, room - 1)
            m = 0
            while m < usable and drafts[i][m] == int(target[i, m]):
                m += 1
            emit = drafts[i][:m] + [int(target[i, m])]
            if k:
                eng.note_spec(usable, m)
            writes, cw = eng.plan_writes(s, st["pos"], len(emit))
            cow += cw
            for (blk, off), tok in zip(writes, emit):
                wb.append(blk)
                wo.append(off)
                wv.append(self._emb_host[tok])
            if not st["out"] and (s.request or {}).get("_timing"):
                st["t_first"] = time.time()
            st["out"] += emit
            st["pos"] += len(emit)
            st["last"] = emit[-1]
            eng.note_tokens(len(emit))
            if len(st["out"]) >= st["need"]:
                toks = list(st["out"][: st["need"]])
                if (s.request or {}).get("_timing"):
                    t0 = float((s.request or {}).get(
                        "_t0", st.get("t_first", 0.0)))
                    s.finish({"tokens": toks,
                              "ttft": st.get("t_first", t0) - t0})
                else:
                    s.finish(toks)
        self._apply_cache_writes(cow, wb, wo, wv)

    @batch(mode="continuous", max_batch_size=MAX_BATCH,
           batch_wait_timeout_s=0.002)
    def _decode(self, slots):
        # Paged dispatch requires the batcher to have wired the engine
        # (slots then carry SlotKV plans): with the paged_kv knob off
        # the batcher ignores serve_kv_engine and admission is dense, so
        # a paged=True instance must fall back to the dense path too.
        if self._paged and slots and slots[0].kv is not None:
            return self._paged_step(slots)
        jax, np = self._jax, self._np
        # Retired slots free their rows at the boundary (their final
        # token was forced LAST step; the batcher has already refilled
        # the batch, so freed rows and joiners line up).
        for r, s in enumerate(self._rows):
            if s is not None and s.finished:
                self._rows[r] = None
        join_x = np.zeros((MAX_BATCH, self._embed), np.float32)
        join_mask = np.zeros((MAX_BATCH, 1), np.bool_)
        for s in slots:
            if s.state is None:
                body = s.request or {}
                prompt = body.get("prompt", 0)
                if isinstance(prompt, (list, tuple)):
                    # Token-list form: dense decode continues from the
                    # LAST prompt token (reference_decode semantics).
                    prompt = prompt[-1] if prompt else 0
                prompt = int(prompt) % self._vocab
                s.state = {"row": None, "out": [],
                           "need": max(1, int(body.get("tokens", 1))),
                           "prompt": prompt}
            if s.state["row"] is None:
                r = self._rows.index(None)  # capacity == max_batch_size
                self._rows[r] = s
                s.state["row"] = r
                join_x[r] = self._emb_host[s.state["prompt"]]
                join_mask[r] = True
        # 1. Joiners' hidden states → device (ASYNC h2d, overlapping
        #    the still-running previous step).
        dev_join = jax.device_put(join_x, self._in_sharding)
        dev_mask = jax.device_put(join_mask, self._in_sharding)
        # 2. Previous step's tokens (its compute ran behind us).
        self._force_pending()
        # 3. Dispatch this step (async); forced on the NEXT call.
        live = [(r, s) for r, s in enumerate(self._rows)
                if s is not None and not s.finished]
        if live:
            tok, self._dev_x = self._step(
                self._w, self._emb, self._dev_x, dev_join, dev_mask)
            self._pending = (tok, live)

    def __call__(self, body: Dict[str, Any]) -> List[int]:
        return self._decode(body)

    # -- disaggregated serving (prefill/decode pool split) ------------------
    def set_serve_role(self, role: Optional[str]) -> None:
        """Pool tag from the controller (``ReplicaWrapper`` calls this
        at replica construction): ``"prefill"`` / ``"decode"`` / None
        (monolithic)."""
        self._serve_role = role

    def kv_ingest_info(self) -> Optional[Dict[str, Any]]:
        """Where prefill replicas should stream chains for THIS
        replica: the node store id (the pusher resolves address +
        capabilities itself).  None outside a runtime (plain-process
        tests) — the handoff then degrades to inline descriptors."""
        with self._chain_lock:
            if self._ingest_info is not None:
                return dict(self._ingest_info)
        try:
            from ray_tpu._private import api_internal

            rt = api_internal.require_runtime()
            info = {"store": rt.store_id}
        except Exception:
            return None
        with self._chain_lock:
            self._ingest_info = info
            return dict(info)

    def kv_debug(self) -> Dict[str, Any]:
        """Allocator + handoff gauges for tests (the chaos suite's
        leak assertions): live block count, unreleased exports, and the
        fallback/retry bookkeeping."""
        with self._chain_lock:
            chain = dict(self._chain_stats)
        eng = getattr(self, "_kv_engine", None)
        if eng is None:
            return {"paged": False, "role": self._serve_role,
                    "chain": chain}
        with eng._guard:
            st = eng.stats_locked()
        st.update({"paged": True, "role": self._serve_role,
                   "used": eng.allocator.used,
                   "available": eng.allocator.available,
                   "exports_outstanding": eng.exports_outstanding,
                   "chain": chain})
        return st

    def prefill_export(self, body: Dict[str, Any],
                       ingest: Optional[Dict[str, Any]] = None) -> tuple:
        """Prompt-only admission of ``body`` on THIS (prefill) replica,
        then the chain handoff: block pages + table metadata laid out
        as one segment image and streamed into ``ingest``'s node store
        over the put verbs (``reserve_put`` → ``put_range``* →
        ``commit_put``), falling back to an inline descriptor when no
        data plane is reachable.  Returns ``(block_chain_descr,
        sampler_state)``."""
        import jax.numpy as jnp

        from ray_tpu.serve.kv_cache import ChainExport

        np = self._np
        if not self._paged:
            raise RuntimeError(
                "disaggregated prefill requires the paged KV engine "
                "(paged_kv knob)")
        exp = self._decode({**(body or {}), "_prefill_only": True})
        if not isinstance(exp, ChainExport):
            raise RuntimeError(
                f"prefill produced no chain (got {type(exp).__name__}: "
                "paged admission not wired?)")
        eng = self._kv_engine
        try:
            pages = np.asarray(
                self._kv_cache[jnp.asarray(exp.blocks, jnp.int32)])
            sampler = {"last": int(exp.prompt[-1]),
                       "pos": len(exp.prompt)}
            payload = {"src_bs": eng.block_size,
                       "n_tokens": len(exp.prompt),
                       "pages": pages, **sampler}
            descr = self._stream_chain(payload, ingest)
            if descr[0] == "inline":
                with self._chain_lock:
                    self._chain_stats["inline_fallbacks"] += 1
            else:
                eng.note_chain_streamed(int(descr[2]))
            return descr, sampler
        finally:
            eng.release_export(exp)

    def _stream_chain(self, payload: Dict[str, Any],
                      ingest: Optional[Dict[str, Any]]) -> tuple:
        """Land one chain image in the ingest store.  Returns the
        descriptor ``_open_chain`` consumes: ``(kind, ident, total)``
        for a committed segment in the DECODE replica's node store
        (kind ``"shm"``/``"spilled"``), or ``("inline", payload)`` when
        no put path is reachable (no runtime, or a peer without the put
        verbs) — mirrors the shuffle pusher's hedge shape."""
        store = (ingest or {}).get("store")
        rt = None
        if store:
            try:
                from ray_tpu._private import api_internal

                rt = api_internal.require_runtime()
            except Exception:
                rt = None
        if rt is None:
            return ("inline", payload)
        from ray_tpu._private import object_transfer, serialization
        from ray_tpu._private import shm_store as shm_mod
        from ray_tpu._private.config import GLOBAL_CONFIG as _CFG
        from ray_tpu._private.ids import ObjectID

        res = serialization.dumps_adaptive(payload, 0)  # parts form
        meta, bufs = res[1], res[2]
        oid_bin = ObjectID.for_put().binary()
        try:
            if store != rt.store_id:
                ent = rt.resolve_store_addr(store)
                if ent is None or \
                        not object_transfer.peer_accepts_puts(ent[1]):
                    return ("inline", payload)
                kind, ident, total = rt._pusher.push(
                    store, ent[0], oid_bin, meta, bufs, caps=ent[1],
                    stripe_threshold=_CFG.kv_stream_stripe_threshold)
            else:
                kind, ident, total = shm_mod.put_local(
                    rt.shm, oid_bin, meta, bufs)
        except Exception:
            if store != rt.store_id:
                rt.forget_store_addr(store)
            return ("inline", payload)
        return (kind, ident, total)

    def _open_chain(self, descr: tuple) -> Dict[str, Any]:
        """Adopt a streamed chain on THIS (decode) replica: attach the
        committed segment in the local node store, copy the pages out,
        and release the segment (owner-routed free — ``unlink`` returns
        the node byte accounting the pusher's ``reserve_put`` charged).
        Inline descriptors short-cut."""
        np = self._np
        if descr[0] == "inline":
            payload = dict(descr[1])
            payload["pages"] = np.asarray(payload["pages"])
            return payload
        kind, ident, total = descr[0], descr[1], int(descr[2])
        from ray_tpu._private import api_internal

        rt = api_internal.require_runtime()
        if kind == "spilled":
            seg = rt.shm.attach_path(ident)
        else:
            seg = rt.shm.attach(ident)
        try:
            payload = dict(seg.deserialize())
            # The deserialized pages view aliases the mapping: copy out
            # before the segment goes away.
            payload["pages"] = np.array(payload["pages"], copy=True)
        finally:
            seg.close()
        if kind == "spilled":
            import os

            try:
                os.unlink(ident)
            except OSError:
                pass
        else:
            rt.shm.unlink(ident, total)
        return payload

    def disagg_generate(self, body: Dict[str, Any], prefill=None,
                        pool: str = "") -> Any:
        """Decode-side orchestration of one disaggregated request:
        prefill on the routed prefill replica, stream the chain HERE,
        adopt it, decode locally.  A dead or failing prefill replica is
        retried against the pool's current membership (fetched from the
        controller) — the chaos re-prefill path; any half-received
        chain on this node was already aborted by the put path's
        connection-close cleanup, so a retry starts clean."""
        import ray_tpu as ray

        ingest = self.kv_ingest_info()
        handoff = None
        last_err: Optional[BaseException] = None
        cands = [prefill] if prefill is not None else []
        for attempt in range(2):
            for actor in cands:
                try:
                    handoff = ray.get(actor.call_method.remote(
                        "prefill_export", (body, ingest), {}))
                    break
                except Exception as e:  # noqa: BLE001 — retried below
                    last_err = e
            if handoff is not None or not pool or attempt:
                break
            # Membership may have changed under us (killed replica):
            # re-fetch the prefill pool and re-prefill on a healthy one.
            try:
                from ray_tpu.serve.api import CONTROLLER_NAME

                ctrl = ray.get_actor(CONTROLLER_NAME)
                _, reps, _ = ray.get(ctrl.handle_snapshot.remote(pool))
                cands = list(reps)
                with self._chain_lock:
                    self._chain_stats["handoff_retries"] += 1
            except Exception as e:  # noqa: BLE001 — surfaced below
                last_err = e
                break
        if handoff is None:
            raise RuntimeError(
                f"disaggregated prefill failed: {last_err!r}")
        descr, _sampler = handoff
        imp = self._open_chain(descr)
        return self._decode({**(body or {}), "_import": imp})

    # -- host-side reference (tests pin numerics against this) -------------
    def reference_decode(self, prompt, tokens: int) -> List[int]:
        """Plain sequential greedy decode on the host — exact-integer
        arithmetic makes it bitwise comparable to the device chain.
        ``prompt`` may be an id or a token list (decode continues from
        the LAST prompt token, matching the paged prefill semantics)."""
        np = self._np
        if isinstance(prompt, (list, tuple)):
            prompt = prompt[-1] if prompt else 0
        x = self._emb_host[int(prompt) % self._vocab]
        out = []
        for _ in range(tokens):
            t = int(np.argmax(x @ self._w_host))
            out.append(t)
            x = self._emb_host[t]
        return out
