"""TPU-resident mesh-sharded decode replica.

The serving capability target (SURVEY §7 step 9): a deployment whose
weights LIVE on the device mesh across requests, with a jitted,
NamedSharding-annotated decode step driven by the continuous-batching
engine (continuous.py) — the SNIPPETS [1]/[3] pattern: build a logical
device mesh with named axes, annotate tensors with
``NamedSharding(mesh, PartitionSpec(...))``, and let ``jax.jit`` insert
the collectives.  On a single CPU device the mesh degrades to ``(1,)``
and everything still runs — which is how the test tree exercises it.

Decode state is DEVICE-RESIDENT: the ``(MAX_BATCH, embed)`` hidden
matrix never round-trips the host between steps — each jitted step
consumes the previous step's output array directly.  The host touches
the device exactly twice per iteration, both overlapped with compute:

1. Joining requests' initial hidden vectors go up as a masked
   ``(MAX_BATCH, embed)`` update issued BEFORE the previous step's
   tokens are forced — the host→device copy for step *t+1*'s joiners is
   double-buffered against running step *t* (jax dispatch is async).
2. The PREVIOUS step's token vector is forced (device→host) to retire
   finished requests; the step just dispatched keeps the device busy
   behind it.

Because tokens are forced one step late, a request finishes one batcher
step after its last token was computed — the classic pipeline-latency
trade for keeping the device hot.  A retiring request's row may
additionally run one speculative step; the overshoot is dropped at
retire time.

Weights are integer-valued float32 (drawn once from ``seed``, rounded):
every matmul below float32's 2^24 integer window is EXACT, so the
decoded chains are bit-independent of BLAS/XLA reduction order and the
test tree can pin them against a plain host-side reference loop.

Request format: ``{"prompt": int, "tokens": int}`` → list of ``tokens``
greedily decoded token ids.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ray_tpu.serve.batching import batch

MAX_BATCH = 8


class MeshShardedDecoder:
    """Deployment-ready greedy decoder with mesh-resident weights."""

    def __init__(self, embed: int = 32, vocab: int = 64, seed: int = 0):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import Mesh, NamedSharding
        from jax.sharding import PartitionSpec as P

        self._np = np
        self._jax = jax
        devs = np.asarray(jax.devices())
        n = len(devs)
        # Logical 1-D "model" mesh over every visible device; the vocab
        # (output) dimension shards across it.
        self._mesh = Mesh(devs.reshape(-1), ("model",))
        vocab = ((vocab + n - 1) // n) * n  # divisible over the axis
        kw, ke = jax.random.split(jax.random.PRNGKey(seed))
        w = jnp.round(jax.random.normal(kw, (embed, vocab)) * 4.0)
        emb = jnp.round(jax.random.normal(ke, (vocab, embed)) * 4.0)
        # RESIDENT across requests: the projection is sharded over the
        # model axis, the embedding table replicated (it is read by
        # token id — gather-heavy, cheap to mirror).
        self._w = jax.device_put(
            w.astype(jnp.float32),
            NamedSharding(self._mesh, P(None, "model")))
        self._emb = jax.device_put(
            emb.astype(jnp.float32), NamedSharding(self._mesh, P()))
        self._in_sharding = NamedSharding(self._mesh, P())
        # Host mirrors for slot-state init and the reference loop.
        self._w_host = np.asarray(self._w)
        self._emb_host = np.asarray(self._emb)
        self._embed = embed
        self._vocab = vocab

        @jax.jit
        def step(w, emb_t, x, join_x, join_mask):
            # Joining rows overwrite their hidden state; x is otherwise
            # the previous step's device output.  Logits shard over
            # "model" via w's sharding — the compiler inserts the
            # gather for the argmax reduction.
            x = jnp.where(join_mask, join_x, x)
            logits = x @ w
            tok = jnp.argmax(logits, axis=-1)
            nxt = emb_t[tok]
            return tok, nxt

        self._step = step
        # Device-resident hidden states, one row per batch slot.
        self._dev_x = jax.device_put(
            np.zeros((MAX_BATCH, embed), np.float32), self._in_sharding)
        # row -> owning Slot (host-side occupancy map).
        self._rows: List[Optional[Any]] = [None] * MAX_BATCH
        # Last dispatched step: (token device array, [(row, slot)]).
        self._pending = None

    # -- continuous decode step (called by the batching engine) ------------
    def _force_pending(self):
        """Force the previously dispatched step's tokens (device→host),
        append them to their slots and finish slots that reached their
        requested length."""
        np = self._np
        if self._pending is None:
            return
        tok_dev, rows = self._pending
        self._pending = None
        tok = np.asarray(tok_dev)
        for r, slot in rows:
            if slot.finished:
                continue  # speculative overshoot for a retired slot
            st = slot.state
            st["out"].append(int(tok[r]))
            if len(st["out"]) >= st["need"]:
                slot.finish(list(st["out"][:st["need"]]))

    @batch(mode="continuous", max_batch_size=MAX_BATCH,
           batch_wait_timeout_s=0.002)
    def _decode(self, slots):
        jax, np = self._jax, self._np
        # Retired slots free their rows at the boundary (their final
        # token was forced LAST step; the batcher has already refilled
        # the batch, so freed rows and joiners line up).
        for r, s in enumerate(self._rows):
            if s is not None and s.finished:
                self._rows[r] = None
        join_x = np.zeros((MAX_BATCH, self._embed), np.float32)
        join_mask = np.zeros((MAX_BATCH, 1), np.bool_)
        for s in slots:
            if s.state is None:
                body = s.request or {}
                prompt = int(body.get("prompt", 0)) % self._vocab
                s.state = {"row": None, "out": [],
                           "need": max(1, int(body.get("tokens", 1))),
                           "prompt": prompt}
            if s.state["row"] is None:
                r = self._rows.index(None)  # capacity == max_batch_size
                self._rows[r] = s
                s.state["row"] = r
                join_x[r] = self._emb_host[s.state["prompt"]]
                join_mask[r] = True
        # 1. Joiners' hidden states → device (ASYNC h2d, overlapping
        #    the still-running previous step).
        dev_join = jax.device_put(join_x, self._in_sharding)
        dev_mask = jax.device_put(join_mask, self._in_sharding)
        # 2. Previous step's tokens (its compute ran behind us).
        self._force_pending()
        # 3. Dispatch this step (async); forced on the NEXT call.
        live = [(r, s) for r, s in enumerate(self._rows)
                if s is not None and not s.finished]
        if live:
            tok, self._dev_x = self._step(
                self._w, self._emb, self._dev_x, dev_join, dev_mask)
            self._pending = (tok, live)

    def __call__(self, body: Dict[str, Any]) -> List[int]:
        return self._decode(body)

    # -- host-side reference (tests pin numerics against this) -------------
    def reference_decode(self, prompt: int, tokens: int) -> List[int]:
        """Plain sequential greedy decode on the host — exact-integer
        arithmetic makes it bitwise comparable to the device chain."""
        np = self._np
        x = self._emb_host[prompt % self._vocab]
        out = []
        for _ in range(tokens):
            t = int(np.argmax(x @ self._w_host))
            out.append(t)
            x = self._emb_host[t]
        return out
