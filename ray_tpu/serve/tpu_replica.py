"""TPU-resident mesh-sharded decode replica.

The serving capability target (SURVEY §7 step 9): a deployment whose
weights LIVE on the device mesh across requests, with a jitted,
NamedSharding-annotated decode step driven by the continuous-batching
engine (continuous.py) — the SNIPPETS [1]/[3] pattern: build a logical
device mesh with named axes, annotate tensors with
``NamedSharding(mesh, PartitionSpec(...))``, and let ``jax.jit`` insert
the collectives.  On a single CPU device the mesh degrades to ``(1,)``
and everything still runs — which is how the test tree exercises it.

Decode state is DEVICE-RESIDENT: the ``(MAX_BATCH, embed)`` hidden
matrix never round-trips the host between steps — each jitted step
consumes the previous step's output array directly.  The host touches
the device exactly twice per iteration, both overlapped with compute:

1. Joining requests' initial hidden vectors go up as a masked
   ``(MAX_BATCH, embed)`` update issued BEFORE the previous step's
   tokens are forced — the host→device copy for step *t+1*'s joiners is
   double-buffered against running step *t* (jax dispatch is async).
2. The PREVIOUS step's token vector is forced (device→host) to retire
   finished requests; the step just dispatched keeps the device busy
   behind it.

Because tokens are forced one step late, a request finishes one batcher
step after its last token was computed — the classic pipeline-latency
trade for keeping the device hot.  A retiring request's row may
additionally run one speculative step; the overshoot is dropped at
retire time.

Weights are integer-valued float32 (drawn once from ``seed``, rounded):
every matmul below float32's 2^24 integer window is EXACT, so the
decoded chains are bit-independent of BLAS/XLA reduction order and the
test tree can pin them against a plain host-side reference loop.

PAGED DECODE MODE (``paged_kv`` knob; reference: vLLM PagedAttention
SOSP'23 + Leviathan et al. ICML'23): per-request decode state moves
from a dense ``(MAX_BATCH, embed)`` row reservation into a pool of
fixed-size KV blocks (``kv_cache.PagedKVEngine``) — admission is then
bounded by blocks (tokens actually resident), not slots, and the
batcher packs skewed-length batches.  Each position's value row is the
emitted token's embedding; every step reads the live requests' LAST
rows back THROUGH the paged cache with the ``ops.paged_attention``
pallas kernel (``window=1`` — softmax over one position is exactly 1.0,
so the gather is bitwise) and advances each chain with the same
integer-exact ``x @ W`` argmax the dense path uses: greedy chains stay
bitwise-identical to ``reference_decode``.  On top of it:

- Shared-prefix reuse (``prefix_caching``): prompt token lists are
  prefilled once; block chains are registered per prompt-prefix hash
  and later requests map the SAME physical blocks (copy-on-write on
  first divergence inside a shared partial block).
- Speculative decoding (``speculative_k=k``): a draft model (a
  perturbed integer copy of the projection — cheap, mostly-agreeing)
  proposes k tokens per step host-side; the target verifies all of
  them in ONE batched forward and the accepted prefix plus the
  correction token retire together — multiple tokens per replica step,
  bitwise-unchanged greedy output because acceptance is exact-match.

Request format: ``{"prompt": int | [int, ...], "tokens": int}`` → list
of ``tokens`` greedily decoded token ids (the dense path takes the
``int`` form only; decode continues from the LAST prompt token).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ray_tpu.serve.batching import batch

MAX_BATCH = 8


class MeshShardedDecoder:
    """Deployment-ready greedy decoder with mesh-resident weights."""

    def __init__(self, embed: int = 32, vocab: int = 64, seed: int = 0,
                 paged: Optional[bool] = None, kv_blocks: int = 32,
                 kv_block_size: int = 8, max_slots: int = 16,
                 speculative_k: Optional[int] = None,
                 prefix_caching: Optional[bool] = None,
                 use_kernel: bool = True):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import Mesh, NamedSharding
        from jax.sharding import PartitionSpec as P

        self._np = np
        self._jax = jax
        devs = np.asarray(jax.devices())
        n = len(devs)
        # Logical 1-D "model" mesh over every visible device; the vocab
        # (output) dimension shards across it.
        self._mesh = Mesh(devs.reshape(-1), ("model",))
        vocab = ((vocab + n - 1) // n) * n  # divisible over the axis
        kw, ke = jax.random.split(jax.random.PRNGKey(seed))
        w = jnp.round(jax.random.normal(kw, (embed, vocab)) * 4.0)
        emb = jnp.round(jax.random.normal(ke, (vocab, embed)) * 4.0)
        # RESIDENT across requests: the projection is sharded over the
        # model axis, the embedding table replicated (it is read by
        # token id — gather-heavy, cheap to mirror).
        self._w = jax.device_put(
            w.astype(jnp.float32),
            NamedSharding(self._mesh, P(None, "model")))
        self._emb = jax.device_put(
            emb.astype(jnp.float32), NamedSharding(self._mesh, P()))
        self._in_sharding = NamedSharding(self._mesh, P())
        # Host mirrors for slot-state init and the reference loop.
        self._w_host = np.asarray(self._w)
        self._emb_host = np.asarray(self._emb)
        self._embed = embed
        self._vocab = vocab

        @jax.jit
        def step(w, emb_t, x, join_x, join_mask):
            # Joining rows overwrite their hidden state; x is otherwise
            # the previous step's device output.  Logits shard over
            # "model" via w's sharding — the compiler inserts the
            # gather for the argmax reduction.
            x = jnp.where(join_mask, join_x, x)
            logits = x @ w
            tok = jnp.argmax(logits, axis=-1)
            nxt = emb_t[tok]
            return tok, nxt

        self._step = step
        # Device-resident hidden states, one row per batch slot.
        self._dev_x = jax.device_put(
            np.zeros((MAX_BATCH, embed), np.float32), self._in_sharding)
        # row -> owning Slot (host-side occupancy map).
        self._rows: List[Optional[Any]] = [None] * MAX_BATCH
        # Last dispatched step: (token device array, [(row, slot)]).
        self._pending = None

        # -- paged decode mode (serving memory plane) ------------------
        from ray_tpu._private.config import GLOBAL_CONFIG as _CFG

        self._paged = _CFG.paged_kv if paged is None else paged
        self._spec_k = max(0, (_CFG.speculative_k if speculative_k is None
                               else speculative_k))
        self._use_kernel = use_kernel
        if self._paged:
            from ray_tpu.serve.kv_cache import PagedKVEngine

            self._kv_engine = PagedKVEngine(
                kv_blocks, kv_block_size, tokens_for=self._tokens_for,
                prefix_caching=(_CFG.prefix_caching if prefix_caching
                                is None else prefix_caching),
                max_slots=max_slots)
            # The batching decorator picks this attribute up and wires
            # block-gated admission into the continuous batcher.
            self.serve_kv_engine = self._kv_engine
            # Device-resident paged value cache: one (block_size, 1,
            # embed) page per block, replicated over the mesh (read by
            # position — gather-heavy, like the embedding table).
            self._kv_cache = jax.device_put(
                np.zeros((kv_blocks, kv_block_size, 1, embed),
                         np.float32), self._in_sharding)
            # Draft model: a perturbed integer copy of the projection —
            # mostly agrees with the target (that is the whole game of
            # speculative decoding), still integer-exact.
            kd = jax.random.PRNGKey(seed + 1)
            self._wd_host = np.asarray(
                self._w_host
                + np.asarray(jnp.round(
                    jax.random.normal(kd, self._w_host.shape) * 0.7)),
                np.float32)

    # -- paged-mode helpers -------------------------------------------------
    def _tokens_for(self, request) -> Any:
        """Admission sizing hook: (prompt token tuple, max new tokens)."""
        body = request or {}
        prompt = body.get("prompt", 0)
        if isinstance(prompt, (list, tuple)):
            ids = tuple(int(t) % self._vocab for t in prompt) or (0,)
        else:
            ids = (int(prompt) % self._vocab,)
        return ids, max(1, int(body.get("tokens", 1)))

    # -- continuous decode step (called by the batching engine) ------------
    def _force_pending(self):
        """Force the previously dispatched step's tokens (device→host),
        append them to their slots and finish slots that reached their
        requested length."""
        np = self._np
        if self._pending is None:
            return
        tok_dev, rows = self._pending
        self._pending = None
        tok = np.asarray(tok_dev)
        for r, slot in rows:
            if slot.finished:
                continue  # speculative overshoot for a retired slot
            st = slot.state
            st["out"].append(int(tok[r]))
            if len(st["out"]) >= st["need"]:
                slot.finish(list(st["out"][:st["need"]]))

    # -- paged decode step --------------------------------------------------
    def _apply_cache_writes(self, cow_pairs, blocks, offs, vals):
        """Device updates for one phase: copy-on-write block copies
        FIRST (they must preserve shared content before private writes
        land), then one scatter of the new value rows."""
        import jax.numpy as jnp
        np = self._np
        if cow_pairs:
            olds = jnp.asarray([o for o, _ in cow_pairs], jnp.int32)
            news = jnp.asarray([n for _, n in cow_pairs], jnp.int32)
            self._kv_cache = self._kv_cache.at[news].set(
                self._kv_cache[olds])
        if blocks:
            self._kv_cache = self._kv_cache.at[
                jnp.asarray(blocks, jnp.int32),
                jnp.asarray(offs, jnp.int32), 0].set(
                    jnp.asarray(np.stack(vals)))

    def _read_last(self, live):
        """Gather every live request's LAST value row back through the
        paged cache — the ops.paged_attention block-table data path.
        ``window=1`` makes the softmax exactly 1.0, so the result is
        bitwise the stored row (= emb[last token])."""
        import jax.numpy as jnp

        from ray_tpu.ops.paged_attention import (
            paged_attention, paged_attention_reference)
        np = self._np
        eng = self._kv_engine
        tables = [eng.block_table(s) for s in live]
        width = max(len(t) for t in tables)
        bt = np.zeros((len(live), width), np.int32)
        for i, t in enumerate(tables):
            bt[i, : len(t)] = t
        cl = np.asarray([s.state["pos"] for s in live], np.int32)
        q = np.zeros((len(live), 1, self._embed), np.float32)
        fn = paged_attention if self._use_kernel \
            else paged_attention_reference
        out = fn(jnp.asarray(q), self._kv_cache, self._kv_cache,
                 jnp.asarray(bt), jnp.asarray(cl), window=1)
        return np.asarray(out)[:, 0, :]

    def _paged_step(self, slots):
        """One iteration of the paged engine: prefill joiners into their
        blocks (skipping shared-prefix positions), read last rows via
        the paged kernel, draft + verify ``spec_k`` tokens in one
        batched forward, and retire the accepted prefix."""
        import jax.numpy as jnp
        np = self._np
        eng = self._kv_engine
        k = self._spec_k
        # Phase 1: join + prefill.  Positions [0, n_cached) are mapped
        # from the prefix cache and never rewritten; the rest of the
        # prompt scatters into this request's (fresh or CoW'd) blocks.
        cow, wb, wo, wv = [], [], [], []
        joiners = []
        for s in slots:
            if s.state is not None:
                continue
            kvp = s.kv
            s.state = {"pos": len(kvp.prompt), "out": [],
                       "need": kvp.max_new, "last": kvp.prompt[-1]}
            lo = kvp.n_cached
            if lo < len(kvp.prompt):
                writes, cw = eng.plan_writes(s, lo, len(kvp.prompt) - lo)
                cow += cw
                for (blk, off), tok in zip(writes, kvp.prompt[lo:]):
                    wb.append(blk)
                    wo.append(off)
                    wv.append(self._emb_host[tok])
            joiners.append(s)
        self._apply_cache_writes(cow, wb, wo, wv)
        for s in joiners:
            # Publish AFTER the prefill scatter: a prefix-cache entry
            # must never alias unwritten blocks.
            eng.register_prefix(s)
        live = [s for s in slots if not s.finished]
        if not live:
            return
        # Phase 2: last rows through the paged cache (bitwise gather).
        last = self._read_last(live)                       # (B, embed)
        # Phase 3: draft k tokens per request (host, integer-exact),
        # then verify ALL of them in ONE batched target forward:
        # position j's logits come from token j-1's value row, so row 0
        # is the cache-gathered last row and rows 1..k are the drafts'
        # embeddings.
        drafts = []
        for s in live:
            t = s.state["last"]
            chain = []
            for _ in range(k):
                t = int(np.argmax(self._emb_host[t] @ self._wd_host))
                chain.append(t)
            drafts.append(chain)
        verify = np.empty((len(live), k + 1, self._embed), np.float32)
        verify[:, 0, :] = last
        for i, chain in enumerate(drafts):
            for j, t in enumerate(chain):
                verify[i, j + 1] = self._emb_host[t]
        logits = jnp.asarray(verify) @ self._w     # sharded over "model"
        target = np.asarray(jnp.argmax(logits, axis=-1))   # (B, k+1)
        # Phase 4: exact-match acceptance — emitted tokens are the
        # matching draft prefix plus the target's correction token,
        # which is by construction the plain greedy chain.
        cow, wb, wo, wv = [], [], [], []
        for i, s in enumerate(live):
            st = s.state
            room = st["need"] - len(st["out"])
            usable = min(k, room - 1)
            m = 0
            while m < usable and drafts[i][m] == int(target[i, m]):
                m += 1
            emit = drafts[i][:m] + [int(target[i, m])]
            if k:
                eng.note_spec(usable, m)
            writes, cw = eng.plan_writes(s, st["pos"], len(emit))
            cow += cw
            for (blk, off), tok in zip(writes, emit):
                wb.append(blk)
                wo.append(off)
                wv.append(self._emb_host[tok])
            st["out"] += emit
            st["pos"] += len(emit)
            st["last"] = emit[-1]
            eng.note_tokens(len(emit))
            if len(st["out"]) >= st["need"]:
                s.finish(list(st["out"][: st["need"]]))
        self._apply_cache_writes(cow, wb, wo, wv)

    @batch(mode="continuous", max_batch_size=MAX_BATCH,
           batch_wait_timeout_s=0.002)
    def _decode(self, slots):
        # Paged dispatch requires the batcher to have wired the engine
        # (slots then carry SlotKV plans): with the paged_kv knob off
        # the batcher ignores serve_kv_engine and admission is dense, so
        # a paged=True instance must fall back to the dense path too.
        if self._paged and slots and slots[0].kv is not None:
            return self._paged_step(slots)
        jax, np = self._jax, self._np
        # Retired slots free their rows at the boundary (their final
        # token was forced LAST step; the batcher has already refilled
        # the batch, so freed rows and joiners line up).
        for r, s in enumerate(self._rows):
            if s is not None and s.finished:
                self._rows[r] = None
        join_x = np.zeros((MAX_BATCH, self._embed), np.float32)
        join_mask = np.zeros((MAX_BATCH, 1), np.bool_)
        for s in slots:
            if s.state is None:
                body = s.request or {}
                prompt = body.get("prompt", 0)
                if isinstance(prompt, (list, tuple)):
                    # Token-list form: dense decode continues from the
                    # LAST prompt token (reference_decode semantics).
                    prompt = prompt[-1] if prompt else 0
                prompt = int(prompt) % self._vocab
                s.state = {"row": None, "out": [],
                           "need": max(1, int(body.get("tokens", 1))),
                           "prompt": prompt}
            if s.state["row"] is None:
                r = self._rows.index(None)  # capacity == max_batch_size
                self._rows[r] = s
                s.state["row"] = r
                join_x[r] = self._emb_host[s.state["prompt"]]
                join_mask[r] = True
        # 1. Joiners' hidden states → device (ASYNC h2d, overlapping
        #    the still-running previous step).
        dev_join = jax.device_put(join_x, self._in_sharding)
        dev_mask = jax.device_put(join_mask, self._in_sharding)
        # 2. Previous step's tokens (its compute ran behind us).
        self._force_pending()
        # 3. Dispatch this step (async); forced on the NEXT call.
        live = [(r, s) for r, s in enumerate(self._rows)
                if s is not None and not s.finished]
        if live:
            tok, self._dev_x = self._step(
                self._w, self._emb, self._dev_x, dev_join, dev_mask)
            self._pending = (tok, live)

    def __call__(self, body: Dict[str, Any]) -> List[int]:
        return self._decode(body)

    # -- host-side reference (tests pin numerics against this) -------------
    def reference_decode(self, prompt, tokens: int) -> List[int]:
        """Plain sequential greedy decode on the host — exact-integer
        arithmetic makes it bitwise comparable to the device chain.
        ``prompt`` may be an id or a token list (decode continues from
        the LAST prompt token, matching the paged prefill semantics)."""
        np = self._np
        if isinstance(prompt, (list, tuple)):
            prompt = prompt[-1] if prompt else 0
        x = self._emb_host[int(prompt) % self._vocab]
        out = []
        for _ in range(tokens):
            t = int(np.argmax(x @ self._w_host))
            out.append(t)
            x = self._emb_host[t]
        return out
