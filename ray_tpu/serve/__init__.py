"""ray_tpu.serve — model serving (Ray Serve equivalent).

Reference: ``python/ray/serve/`` (SURVEY.md §2.3, 36k LoC) — control plane:
``ServeController`` actor (controller.py:69) reconciling DeploymentState
into replica actors; data plane: per-node HTTP proxies + handles routing to
replicas (``_private/router.py:298``), rolling updates, autoscaling.

Condensation: the controller is a real actor owning replica lifecycle and
reconciliation (scale up/down, dead-replica replacement); handles
round-robin over replicas; the HTTP proxy is an aiohttp server thread in
the driver routing to handles.  TPU twist: a deployment created with
``num_tpus=k`` gets TPU-resident replicas — the scheduler pins chips per
replica actor, the Serve layer needs no device code.
"""

from ray_tpu.serve.api import (
    Deployment,
    DeploymentHandle,
    HTTPProxyActor,
    ProxiedDeploymentHandle,
    RequestProxy,
    deployment,
    get_deployment_handle,
    run,
    serving_stats,
    shutdown,
    start,
    start_http_proxy,
)
from ray_tpu.serve.batching import batch
from ray_tpu.serve.continuous import Slot

__all__ = ["deployment", "Deployment", "DeploymentHandle", "run",
           "get_deployment_handle", "shutdown", "start",
           "start_http_proxy", "HTTPProxyActor", "RequestProxy",
           "ProxiedDeploymentHandle", "serving_stats", "batch", "Slot"]
