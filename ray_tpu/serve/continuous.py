"""Continuous (iteration-level) batching for decode-style deployments.

Reference: Orca (OSDI'22) iteration-level scheduling — the serving
engine admits queued requests into the RUNNING batch at step
boundaries instead of waiting for the whole batch to finish, and
retires each request the step it completes, refilling its slot the
same step.  The legacy ``@serve.batch`` window (batching.py) is
all-or-nothing: a batch of requests enters together, the wrapped
function runs ONCE, and every caller waits for the full batch — fine
for single-shot inference, pathological for decode loops where
request lengths vary (the whole batch runs at the LONGEST request's
step count while finished slots sit empty and queued requests wait).

``@serve.batch(mode="continuous")`` turns the wrapped function into a
STEP function: it is called once per iteration with the list of live
:class:`Slot` objects (one per admitted request).  Each slot carries
``request`` (the caller's payload), ``state`` (arbitrary per-request
state the step function owns across iterations; ``None`` on the
joining step), and ``steps`` (iterations survived so far).  The step
function advances every live request by one iteration and calls
``slot.finish(result)`` on the ones that completed; the scheduler
retires finished slots, wakes their callers, and refills the freed
slots from the queue before the next step.

One scheduler thread per batcher drives the loop; caller threads just
queue and wait, so a replica's ``max_concurrency`` bounds concurrent
CALLERS, not batch occupancy.  With ``RAY_TPU_CONTINUOUS_BATCHING=0``
(config ``continuous_batching``) the same decorator degrades to
one-shot driving of the step function — a fixed batch is admitted,
stepped until EVERY slot finishes, and only then is the next batch
admitted — which is the measured A/B baseline for the bench row and
the byte-identical-behavior escape hatch.

PREFILL-ONLY SLOTS (disaggregated serving): a prefill-pool replica
rides this same scheduler — its requests carry ``_prefill_only`` and
the step function calls ``slot.finish(...)`` on the PROMPT step, the
same iteration the KV chain materializes, so the slot never survives
into a decode iteration.  The contract is ordinary ``finish``: the
batcher needs no mode flag, prefill requests retire like zero-decode
requests, and the finish VALUE (the exported chain) reaches the
parked caller (``prefill_export``) through the normal result path.

LOCK ORDER: ``_ContinuousBatcher._lock`` is a documented independent
LEAF (pinned in tests/test_lockcheck.py): it guards only the admission
queue and counters; the step function runs with NO lock held (user
code may submit, log, or take its own locks), and slot events are set
outside it.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional


class SlotCancelled(RuntimeError):
    """Raised to a caller whose request died with the batcher (scheduler
    teardown, step-function crash)."""


class Slot:
    """One live request inside the running batch.

    The step function reads ``request``, owns ``state`` across
    iterations, and calls :meth:`finish` when the request completes.
    Everything else is scheduler-internal.
    """

    __slots__ = ("request", "state", "steps", "kv", "_done",
                 "_result", "_error", "_event", "_owner")

    def __init__(self, request: Any):
        self.request = request
        self.state: Any = None   # per-request state, carried across steps
        self.steps = 0           # iterations this request has been live
        # Paged-KV plan (kv_cache.SlotKV), set at admission when the
        # batcher carries a PagedKVEngine; None on the dense path.
        self.kv: Any = None
        self._done = False
        self._result: Any = None
        self._error: Optional[BaseException] = None
        self._event = threading.Event()
        # The scheduler thread that admitted this slot into its live
        # batch (set at admission, under the batcher lock).  The caller
        # backstop probes ITS liveness: a slot owned by a dead scheduler
        # is unrecoverable even if a respawned scheduler is running —
        # the dead thread's live list (and this slot's place in it)
        # died with it.
        self._owner: Optional[threading.Thread] = None

    def finish(self, result: Any) -> None:
        """Mark this request complete; the scheduler retires the slot
        and wakes the caller after the current step returns."""
        self._result = result
        self._done = True

    @property
    def finished(self) -> bool:
        return self._done

    def _fail(self, err: BaseException) -> None:
        self._error = err
        self._done = True
        self._event.set()


class _ContinuousBatcher:
    """Iteration-level scheduler around one step function.

    ``continuous=False`` keeps the admission/step/retire machinery but
    admits only into an EMPTY batch and never refills mid-flight — the
    legacy one-shot window semantics expressed over the same step
    function (the bench/acceptance A/B baseline).
    """

    # Follower backstop cadence: how often a waiting caller re-checks
    # that the scheduler thread is still alive (a dead scheduler can
    # never fire its event).
    _BACKSTOP_S = 1.0

    def __init__(self, fn: Callable, instance, max_batch_size: int,
                 batch_wait_timeout_s: float, continuous: bool = True,
                 kv=None):
        self._fn = fn
        self._instance = instance
        self._max = max(1, int(max_batch_size))
        self._timeout = batch_wait_timeout_s
        self._continuous = continuous
        # Paged-KV admission engine (kv_cache.PagedKVEngine) or None.
        # With an engine attached, admission is bounded by free KV
        # BLOCKS (plus the engine's slot cap) instead of
        # max_batch_size: a request is admitted when its whole block
        # budget fits, and parks at the queue head otherwise.  The
        # engine adopts THIS batcher's leaf lock as its guard, so block
        # accounting and admission re-checks happen under one lock.
        self._kv = kv
        # LEAF lock (see module docstring): queue + counters + block
        # accounting only.
        self._lock = threading.Lock()  # lock-order: leaf
        if kv is not None:
            kv.bind(self._lock)
        self._queue: deque = deque()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # True between electing a new scheduler thread (under _lock)
        # and its start() (outside _lock — thread startup acquires
        # interpreter-internal locks, and this lock is a leaf).
        self._spawning = False
        # Observability (serving_stats): cumulative step count, occupied
        # slot-steps (occupancy = occupied/steps), admissions/retires.
        self._steps = 0
        self._occupied_slot_steps = 0
        self._admitted = 0
        self._retired = 0
        self._step_errors = 0

    # ------------------------------------------------------------- caller --
    def submit(self, item: Any) -> Any:
        slot = Slot(item)
        start = None
        with self._lock:
            self._queue.append(slot)
            self._admitted += 1
            t = self._thread
            if (t is None or not t.is_alive()) and not self._spawning:
                self._spawning = True
                start = self._thread = threading.Thread(
                    target=self._loop, daemon=True,
                    name=f"serve-cbatch-{getattr(self._fn, '__name__', '?')}")
        if start is not None:
            # start() outside the (leaf) lock: thread startup takes
            # interpreter-internal locks.
            try:
                start.start()
            finally:
                with self._lock:
                    self._spawning = False
        self._wake.set()
        # Wait with a liveness backstop: the scheduler thread catches
        # step-function errors, so the only way the event can never fire
        # is the scheduler itself dying (interpreter teardown, hard
        # kill) — detectable, unlike an arbitrarily long step.
        while not slot._event.wait(self._BACKSTOP_S):
            dead = False
            with self._lock:
                if slot._event.is_set():
                    break
                # Probe the thread RESPONSIBLE for this slot: its
                # admitting scheduler once admitted, else the current
                # (queue-draining) scheduler — a respawned scheduler
                # cannot revive a dead predecessor's live batch.
                t = slot._owner if slot._owner is not None \
                    else self._thread
                if slot._owner is None and self._spawning:
                    continue
                if t is not None and t.is_alive():
                    continue
                # Scheduler dead: drain our own slot (and let the next
                # submit start a fresh scheduler for the rest).
                try:
                    self._queue.remove(slot)
                except ValueError:
                    pass
                dead = True
            if dead:
                # Event fires OUTSIDE the (leaf) lock.
                slot._fail(SlotCancelled(
                    "continuous-batch scheduler died before this "
                    "request completed"))
        if slot._error is not None:
            raise slot._error
        return slot._result

    # ---------------------------------------------------------- scheduler --
    def _admit_locked(self, live: List[Slot]) -> List[tuple]:
        me = threading.current_thread()
        # Paged admission: bounded by free KV BLOCKS + the engine's slot
        # cap, not max_batch_size.  Availability is (re-)checked under
        # this leaf lock at every boundary; a request whose block budget
        # does not fit PARKS at the queue head (FIFO — retiring requests
        # free blocks and the next boundary re-checks) instead of
        # erroring.  The one exception: a budget no pool state could
        # ever satisfy (RequestTooLarge) is popped and returned for the
        # caller to FAIL outside this (leaf) lock — parking it would
        # wedge the queue head forever.
        doomed: List[tuple] = []
        cap = self._kv.max_slots if self._kv is not None else self._max
        while self._queue and len(live) < cap:
            s = self._queue[0]
            if self._kv is not None:
                try:
                    if not self._kv.try_admit_locked(s):
                        break
                except Exception as err:  # noqa: BLE001 — a malformed
                    # request (sizing hook blew up) or an oversized one
                    # must doom THAT slot, not kill the scheduler: the
                    # bad slot would stay at the queue head and every
                    # respawned scheduler would die on it again.
                    self._queue.popleft()
                    doomed.append((s, err))
                    continue
            self._queue.popleft()
            s._owner = me
            live.append(s)
        return doomed

    def _loop(self) -> None:
        live: List[Slot] = []
        while True:
            doomed = []
            with self._lock:
                if self._continuous or not live:
                    # Continuous: refill freed slots every boundary.
                    # One-shot: admit only into an empty batch.
                    doomed = self._admit_locked(live)
            for s, err in doomed:  # events fire OUTSIDE the leaf lock
                s._fail(err)
            if not live:
                # Idle: park until a request arrives (clear-then-check
                # so a submit racing this window still wakes us).
                self._wake.clear()
                with self._lock:
                    empty = not self._queue
                if empty:
                    self._wake.wait()
                continue
            cap = self._kv.max_slots if self._kv is not None else self._max
            if not self._continuous and self._timeout > 0 \
                    and live and live[0].steps == 0 \
                    and len(live) < cap:
                # Legacy window: a fresh one-shot batch below max waits
                # out the batching window for followers before step 0.
                deadline = time.monotonic() + self._timeout
                while len(live) < cap:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        break
                    self._wake.wait(left)
                    self._wake.clear()
                    with self._lock:
                        doomed = self._admit_locked(live)
                    for s, err in doomed:
                        s._fail(err)
            try:
                if self._instance is not None:
                    self._fn(self._instance, live)
                else:
                    self._fn(live)
            except BaseException as err:  # noqa: BLE001 — fan out, keep loop
                with self._lock:
                    self._step_errors += 1
                    self._steps += 1
                    if self._kv is not None:
                        # Failed slots free their KV blocks too — a
                        # crashing step function must not leak the pool.
                        for s in live:
                            self._kv.retire_locked(s)
                for s in live:
                    s._fail(err)
                live = []
                continue
            finished = [s for s in live if s._done]
            live = [s for s in live if not s._done]
            for s in live:
                s.steps += 1
            with self._lock:
                self._steps += 1
                self._occupied_slot_steps += len(live) + len(finished)
                self._retired += len(finished)
                if self._kv is not None:
                    # Free on retire, under the same leaf lock the
                    # admission check runs under: the next boundary's
                    # block-availability re-check sees these blocks.
                    for s in finished:
                        self._kv.retire_locked(s)
            # Events fire OUTSIDE the lock (leaf convention).
            for s in finished:
                s._event.set()

    # ------------------------------------------------------------- stats ---
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            steps = self._steps
            occ = (self._occupied_slot_steps / steps) if steps else 0.0
            out = {
                "mode": "continuous" if self._continuous else "oneshot",
                "steps": steps,
                "batch_occupancy": round(occ, 3),
                "max_batch_size": self._max,
                "admitted": self._admitted,
                "retired": self._retired,
                "queued": len(self._queue),
                "step_errors": self._step_errors,
            }
            if self._kv is not None:
                # Serving-memory plane: block occupancy, prefix reuse,
                # and speculative-decode counters ride the same stats
                # dict (rolled up per deployment by the controller).
                out["mode"] += "+paged"
                kv = self._kv.stats_locked()
                out.update(kv)
                out["tokens_per_step"] = round(
                    kv["tokens_emitted"] / steps, 3) if steps else 0.0
            return out
