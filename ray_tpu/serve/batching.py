"""Request batching inside replicas.

Reference: ``python/ray/serve/batching.py`` — ``@serve.batch`` collects
concurrent calls into one invocation of the wrapped function, which
receives a LIST of the single-call arguments and returns a list of
results (positional).  The reference batches on the replica's asyncio
loop; our replicas are threaded (``max_concurrency``), so batching
rendezvouses caller threads: the first caller of a batch becomes the
leader, waits up to ``batch_wait_timeout_s`` for followers (or until
``max_batch_size``), runs the underlying function once, and distributes
results.
"""

from __future__ import annotations

import functools
import threading
from typing import Any, Callable, List, Optional


class _Entry:
    __slots__ = ("item", "event", "result", "error")

    def __init__(self, item):
        self.item = item
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None


class _Batcher:
    def __init__(self, fn: Callable, instance, max_batch_size: int,
                 batch_wait_timeout_s: float):
        self._fn = fn
        self._instance = instance
        self._max = max_batch_size
        self._timeout = batch_wait_timeout_s
        self._lock = threading.Lock()
        self._pending: List[_Entry] = []
        self._full = threading.Event()

    def submit(self, item):
        entry = _Entry(item)
        with self._lock:
            self._pending.append(entry)
            leader = len(self._pending) == 1
            if len(self._pending) >= self._max:
                self._full.set()
        if leader:
            self._full.wait(self._timeout)
            with self._lock:
                batch, self._pending = self._pending, []
                self._full.clear()
            self._run(batch)
        else:
            entry.event.wait()
        if entry.error is not None:
            raise entry.error
        return entry.result

    def _run(self, batch: List[_Entry]):
        try:
            items = [e.item for e in batch]
            if self._instance is not None:
                results = self._fn(self._instance, items)
            else:
                results = self._fn(items)
            if len(results) != len(items):
                raise ValueError(
                    f"@serve.batch function returned {len(results)} "
                    f"results for {len(items)} inputs")
            for e, r in zip(batch, results):
                e.result = r
        except BaseException as err:  # noqa: BLE001 — fan the error out
            for e in batch:
                e.error = err
        finally:
            for e in batch:
                e.event.set()


def batch(_func: Optional[Callable] = None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: the wrapped fn must take a LIST of requests and return
    a list of results.  Callers still pass a single request each
    (reference: serve/batching.py @serve.batch)."""

    def deco(fn):
        # No lock/batcher captured in the closure: the deployment class
        # (and this wrapper with it) crosses the wire via cloudpickle,
        # and thread locks don't pickle.  The batcher attaches to the
        # replica-side instance (or the wrapper itself for plain
        # functions) on first call.
        attr = f"__serve_batcher_{fn.__name__}"

        @functools.wraps(fn)
        def wrapper(*args):
            if len(args) == 2:
                instance, item = args
            elif len(args) == 1:
                instance, item = None, args[0]
            else:
                raise TypeError(
                    "@serve.batch methods take exactly one request "
                    "argument")
            holder = instance if instance is not None else wrapper
            b = getattr(holder, attr, None)
            if b is None:
                # GIL-atomic setdefault: a racing thread's extra
                # _Batcher is discarded, the winner is shared.
                b = holder.__dict__.setdefault(
                    attr, _Batcher(fn, instance, max_batch_size,
                                   batch_wait_timeout_s))
            return b.submit(item)

        wrapper.__wrapped__ = fn
        return wrapper

    if _func is not None:
        return deco(_func)
    return deco
