"""Request batching inside replicas.

Reference: ``python/ray/serve/batching.py`` — ``@serve.batch`` collects
concurrent calls into one invocation of the wrapped function, which
receives a LIST of the single-call arguments and returns a list of
results (positional).  The reference batches on the replica's asyncio
loop; our replicas are threaded (``max_concurrency``), so batching
rendezvouses caller threads: the first caller of a batch becomes the
leader, waits up to ``batch_wait_timeout_s`` for followers (or until
``max_batch_size``), runs the underlying function once, and distributes
results.

``mode="continuous"`` switches to the iteration-level engine
(continuous.py): the wrapped function becomes a per-step function over
live request slots, with queued requests admitted at step boundaries —
see the Orca-style scheduler there.  ``RAY_TPU_CONTINUOUS_BATCHING=0``
degrades continuous-mode decorators to one-shot driving of the same
step function (the measured A/B baseline); the default list-in/list-out
mode here is untouched by the switch.

LOCK ORDER: ``_Batcher._lock`` is a documented independent LEAF (pinned
in tests/test_lockcheck.py): it guards only the pending list and
counters; the wrapped function runs with no lock held and entry events
are set outside it.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Any, Callable, Dict, List, Optional


class _Entry:
    __slots__ = ("item", "event", "result", "error", "leader")

    def __init__(self, item):
        self.item = item
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        # The thread running this entry's batch, recorded at collection
        # time — the follower backstop's liveness probe.
        self.leader: Optional[threading.Thread] = None


class _Batcher:
    # Follower backstop cadence: a waiting follower re-checks this often
    # that its batch leader is still alive.  Liveness — not a bound on
    # the wrapped function's runtime (a live leader waits forever).
    _BACKSTOP_S = 1.0

    def __init__(self, fn: Callable, instance, max_batch_size: int,
                 batch_wait_timeout_s: float):
        self._fn = fn
        self._instance = instance
        self._max = max_batch_size
        self._timeout = batch_wait_timeout_s
        self._lock = threading.Lock()  # lock-order: leaf
        self._pending: List[_Entry] = []
        self._full = threading.Event()
        # Pre-collection leader (elected at first append; cleared when it
        # collects its batch).  Followers use it to detect a leader that
        # died before collecting — their entries would otherwise pend
        # forever.
        self._leader: Optional[threading.Thread] = None
        # Observability (serving_stats).
        self._batches = 0
        self._items = 0
        self._retired = 0        # items that got a RESULT
        self._error_batches = 0  # batches whose wrapped fn raised

    def submit(self, item):
        entry = _Entry(item)
        with self._lock:
            self._pending.append(entry)
            leader = len(self._pending) == 1
            if leader:
                self._leader = threading.current_thread()
            full = len(self._pending) >= self._max
        if full:
            self._full.set()  # outside the (leaf) lock
        if leader:
            self._lead(entry)
        else:
            self._follow(entry)
        if entry.error is not None:
            raise entry.error
        return entry.result

    def _lead(self, entry: _Entry):
        """Leader path.  Every exit — normal, wrapped-fn error, or an
        async exception landing in this thread mid-window — leaves NO
        entry without its event set: a batch collected but not yet run
        is failed wholesale, and one never collected is failed out of
        the pending list (a follower-turned-rescue-leader covers the
        remaining hard-kill window)."""
        batch: Optional[List[_Entry]] = None
        try:
            self._window_wait()
            with self._lock:
                batch, self._pending = self._pending, []
                self._leader = None
                for e in batch:
                    e.leader = threading.current_thread()
            self._run(batch)
        except BaseException as err:  # noqa: BLE001 — fail followers, re-raise
            if batch is None:
                with self._lock:
                    batch, self._pending = self._pending, []
                    if self._leader is threading.current_thread():
                        self._leader = None
            for e in batch:
                if not e.event.is_set():
                    e.error = RuntimeError(
                        f"batch leader failed before the batch ran: "
                        f"{err!r}")
                    e.event.set()
            raise

    def _window_wait(self):
        """Leader's batching window: wait until pending reaches
        max_batch_size or the window times out.  The full-event is only
        a WAKE hint — fullness is re-validated under the lock after
        every wake, so a stale set left over from a previous batch (the
        event fires outside the leaf lock; a preempted follower can set
        it after that batch was already collected) costs one spurious
        loop iteration, never a premature undersized batch."""
        deadline = time.monotonic() + self._timeout
        while True:
            with self._lock:
                if len(self._pending) >= self._max:
                    return
            left = deadline - time.monotonic()
            if left <= 0:
                return
            self._full.wait(left)
            self._full.clear()

    def _follow(self, entry: _Entry):
        """Follower path with a liveness backstop: if the leader thread
        died without firing our event (hard kill — the leader's own
        exception paths fail entries explicitly), a still-pending batch
        is rescued and run by this thread; an entry the dead leader had
        already collected is failed (its batch state died with the
        leader)."""
        while not entry.event.wait(self._BACKSTOP_S):
            rescue: Optional[List[_Entry]] = None
            orphaned = False
            with self._lock:
                if entry.event.is_set():
                    break
                t = entry.leader if entry.leader is not None \
                    else self._leader
                if t is not None and t.is_alive():
                    continue
                if entry in self._pending:
                    rescue, self._pending = self._pending, []
                    self._leader = None
                    for e in rescue:
                        e.leader = threading.current_thread()
                else:
                    entry.error = RuntimeError(
                        "batch leader died before distributing results")
                    orphaned = True
            # Event/rescue work runs OUTSIDE the (leaf) lock.
            if orphaned:
                entry.event.set()
                break
            if rescue is not None:
                self._run(rescue)

    def _run(self, batch: List[_Entry]):
        failed = False
        try:
            items = [e.item for e in batch]
            if self._instance is not None:
                results = self._fn(self._instance, items)
            else:
                results = self._fn(items)
            if len(results) != len(items):
                raise ValueError(
                    f"@serve.batch function returned {len(results)} "
                    f"results for {len(items)} inputs")
            for e, r in zip(batch, results):
                e.result = r
        except BaseException as err:  # noqa: BLE001 — fan the error out
            failed = True
            for e in batch:
                e.error = err
        finally:
            with self._lock:
                self._batches += 1
                self._items += len(batch)
                if failed:
                    self._error_batches += 1
                else:
                    self._retired += len(batch)
            for e in batch:
                e.event.set()

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            batches = self._batches
            occ = (self._items / batches) if batches else 0.0
            return {
                "mode": "oneshot",
                "steps": batches,
                "batch_occupancy": round(occ, 3),
                "max_batch_size": self._max,
                "admitted": self._items,
                "retired": self._retired,
                "queued": len(self._pending),
                "step_errors": self._error_batches,
            }


def batch(_func: Optional[Callable] = None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01, mode: str = "oneshot"):
    """Decorator.  Default mode: the wrapped fn takes a LIST of requests
    and returns a list of results; callers pass a single request each
    (reference: serve/batching.py @serve.batch).  ``mode="continuous"``:
    the wrapped fn is a STEP function over live request slots (see
    continuous.py) — admission happens at step boundaries, finished
    requests retire and their slots refill the same step."""
    if mode not in ("oneshot", "continuous"):
        raise ValueError(f"unknown @serve.batch mode {mode!r}")

    def deco(fn):
        # No lock/batcher captured in the closure: the deployment class
        # (and this wrapper with it) crosses the wire via cloudpickle,
        # and thread locks don't pickle.  The batcher attaches to the
        # replica-side instance (or the wrapper itself for plain
        # functions) on first call.
        attr = f"__serve_batcher_{fn.__name__}"

        def make_batcher(instance):
            if mode == "continuous":
                from ray_tpu._private.config import GLOBAL_CONFIG
                from ray_tpu.serve.continuous import _ContinuousBatcher

                # The switches are read in the REPLICA process (they
                # ride _worker_config_env): continuous off = one-shot
                # driving of the same step function, the measured A/B
                # baseline.  paged_kv on + an instance-attached
                # PagedKVEngine (the ``serve_kv_engine`` attribute)
                # switches admission from max_batch_size slots to KV
                # blocks; with the knob off the engine is ignored and
                # the batcher is byte-identical to the dense PR 8 one.
                kv = None
                if GLOBAL_CONFIG.paged_kv:
                    holder = instance if instance is not None else fn
                    kv = getattr(holder, "serve_kv_engine", None)
                return _ContinuousBatcher(
                    fn, instance, max_batch_size, batch_wait_timeout_s,
                    continuous=GLOBAL_CONFIG.continuous_batching, kv=kv)
            return _Batcher(fn, instance, max_batch_size,
                            batch_wait_timeout_s)

        @functools.wraps(fn)
        def wrapper(*args):
            if len(args) == 2:
                instance, item = args
            elif len(args) == 1:
                instance, item = None, args[0]
            else:
                raise TypeError(
                    "@serve.batch methods take exactly one request "
                    "argument")
            holder = instance if instance is not None else wrapper
            b = getattr(holder, attr, None)
            if b is None:
                # GIL-atomic setdefault: a racing thread's extra
                # batcher is discarded, the winner is shared.
                b = holder.__dict__.setdefault(attr, make_batcher(instance))
            return b.submit(item)

        wrapper.__wrapped__ = fn
        return wrapper

    if _func is not None:
        return deco(_func)
    return deco
