"""Serve control + data plane.

Reference call path (SURVEY.md §3.5): serve.run -> controller actor ->
DeploymentState reconciliation -> replica actors; request path: proxy/handle
-> router -> replica.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu as ray

CONTROLLER_NAME = "SERVE_CONTROLLER"


class ReplicaWrapper:
    """Runs the user callable inside a replica actor process."""

    def __init__(self, cls_or_fn, init_args, init_kwargs):
        if isinstance(cls_or_fn, type):
            self._callable = cls_or_fn(*init_args, **init_kwargs)
        else:
            self._callable = cls_or_fn

    def handle_request(self, args, kwargs):
        fn = self._callable
        if not callable(fn):
            fn = fn.__call__
        return fn(*args, **kwargs)

    def call_method(self, method, args, kwargs):
        return getattr(self._callable, method)(*args, **kwargs)

    def health_check(self):
        if hasattr(self._callable, "check_health"):
            self._callable.check_health()
        return True


@ray.remote
class ServeController:
    """Reference: serve/controller.py:69 + _private/deployment_state.py:998
    (DeploymentState reconciliation loop, here reconcile())."""

    def __init__(self):
        self._deployments: Dict[str, Dict[str, Any]] = {}
        self._replicas: Dict[str, List[Any]] = {}

    def deploy(self, name: str, payload: Dict[str, Any]):
        """payload: cls_or_fn, init_args/kwargs, num_replicas, resources."""
        self._deployments[name] = payload
        self.reconcile()
        return True

    def delete_deployment(self, name: str):
        self._deployments.pop(name, None)
        for r in self._replicas.pop(name, []):
            try:
                ray.kill(r)
            except Exception:
                pass
        return True

    def _spawn(self, name: str):
        d = self._deployments[name]
        opts = {"num_cpus": d.get("num_cpus", 1)}
        if d.get("num_tpus"):
            opts["num_tpus"] = d["num_tpus"]
        remote_cls = ray.remote(ReplicaWrapper)
        return remote_cls.options(**opts).remote(
            d["cls_or_fn"], d.get("init_args", ()),
            d.get("init_kwargs", {}))

    def reconcile(self):
        """Drive actual replica sets toward target counts; replace dead
        replicas (controller-driven health checks,
        _private/deployment_state.py)."""
        for name, d in self._deployments.items():
            reps = self._replicas.setdefault(name, [])
            alive = []
            for r in reps:
                try:
                    ray.get(r.health_check.remote(), timeout=5)
                    alive.append(r)
                except Exception:
                    pass
            target = d.get("num_replicas", 1)
            while len(alive) < target:
                alive.append(self._spawn(name))
            while len(alive) > target:
                doomed = alive.pop()
                try:
                    ray.kill(doomed)
                except Exception:
                    pass
            self._replicas[name] = alive
        return {n: len(r) for n, r in self._replicas.items()}

    def get_replicas(self, name: str):
        return list(self._replicas.get(name, []))

    def list_deployments(self):
        return {n: {"num_replicas": d.get("num_replicas", 1)}
                for n, d in self._deployments.items()}

    def scale(self, name: str, num_replicas: int):
        self._deployments[name]["num_replicas"] = num_replicas
        self.reconcile()
        return True


class DeploymentHandle:
    """Round-robin router over replicas (reference:
    _private/router.py:262 ReplicaSet / handle API).

    The replica set is re-fetched from the controller on a short TTL (the
    reference pushes updates via LongPollClient, _private/long_poll.py:68 —
    TTL polling is the condensation) so scaling and dead-replica
    replacement propagate to existing handles.
    """

    _TTL = 2.0

    def __init__(self, name: str, controller):
        self._name = name
        self._controller = controller
        self._replicas: List[Any] = []
        self._fetched_at = 0.0
        self._rr = itertools.count()
        self._lock = threading.Lock()
        self._refresh()

    def _refresh(self):
        self._replicas = ray.get(
            self._controller.get_replicas.remote(self._name))
        self._fetched_at = time.monotonic()

    def _pick(self):
        with self._lock:
            if not self._replicas or                     time.monotonic() - self._fetched_at > self._TTL:
                self._refresh()
            if not self._replicas:
                raise RuntimeError(
                    f"deployment {self._name} has no replicas")
            return self._replicas[next(self._rr) % len(self._replicas)]

    def remote(self, *args, **kwargs):
        return self._pick().handle_request.remote(args, kwargs)

    def method(self, method_name: str):
        handle = self

        class _M:
            def remote(self, *args, **kwargs):
                return handle._pick().call_method.remote(
                    method_name, args, kwargs)

        return _M()


class Deployment:
    """Result of @serve.deployment — bind/deploy surface (reference:
    serve/deployment.py)."""

    def __init__(self, cls_or_fn, name: str, num_replicas: int = 1,
                 num_cpus: float = 1, num_tpus: int = 0,
                 route_prefix: Optional[str] = None):
        self._cls_or_fn = cls_or_fn
        self.name = name
        self.num_replicas = num_replicas
        self.num_cpus = num_cpus
        self.num_tpus = num_tpus
        self.route_prefix = route_prefix or f"/{name}"
        self._init_args = ()
        self._init_kwargs = {}

    def options(self, **kw) -> "Deployment":
        d = Deployment(self._cls_or_fn, kw.get("name", self.name),
                       kw.get("num_replicas", self.num_replicas),
                       kw.get("num_cpus", self.num_cpus),
                       kw.get("num_tpus", self.num_tpus),
                       kw.get("route_prefix", self.route_prefix))
        d._init_args = self._init_args
        d._init_kwargs = self._init_kwargs
        return d

    def bind(self, *args, **kwargs) -> "Deployment":
        d = self.options()
        d._init_args = args
        d._init_kwargs = kwargs
        return d


def deployment(cls_or_fn=None, *, name: Optional[str] = None,
               num_replicas: int = 1, num_cpus: float = 1,
               num_tpus: int = 0, route_prefix: Optional[str] = None):
    """@serve.deployment (reference: serve/api.py deployment)."""

    def wrap(target):
        return Deployment(target, name or target.__name__, num_replicas,
                          num_cpus, num_tpus, route_prefix)

    if cls_or_fn is not None:
        return wrap(cls_or_fn)
    return wrap


_state: Dict[str, Any] = {"controller": None, "proxy": None,
                          "handles": {}, "routes": {}}


def _get_controller():
    if _state["controller"] is None:
        _state["controller"] = ServeController.options(
            name=CONTROLLER_NAME).remote()
    return _state["controller"]


def run(target: Deployment, *, name: Optional[str] = None
        ) -> DeploymentHandle:
    """Deploy + return a handle (reference: serve.run, api.py:458)."""
    controller = _get_controller()
    dep_name = name or target.name
    ray.get(controller.deploy.remote(dep_name, {
        "cls_or_fn": target._cls_or_fn,
        "init_args": target._init_args,
        "init_kwargs": target._init_kwargs,
        "num_replicas": target.num_replicas,
        "num_cpus": target.num_cpus,
        "num_tpus": target.num_tpus,
    }))
    handle = DeploymentHandle(dep_name, controller)
    _state["handles"][dep_name] = handle
    _state["routes"][target.route_prefix] = handle
    return handle


def get_deployment_handle(name: str) -> DeploymentHandle:
    h = _state["handles"].get(name)
    if h is None:
        h = DeploymentHandle(name, _get_controller())
        _state["handles"][name] = h
    return h


def start_http_proxy(host: str = "127.0.0.1", port: int = 8000):
    """HTTP ingress (reference: HTTPProxyActor, _private/http_proxy.py:415).
    Runs an aiohttp server on a driver thread; routes by path prefix."""
    import asyncio

    from aiohttp import web

    async def handle(request: web.Request):
        path = "/" + request.path.strip("/").split("/")[0]
        h = _state["routes"].get(path)
        if h is None:
            return web.json_response({"error": "no such route"}, status=404)
        try:
            body = await request.json() if request.can_read_body else {}
        except Exception:
            body = {}
        loop = asyncio.get_event_loop()
        ref = h.remote(body)
        result = await loop.run_in_executor(None, lambda: ray.get(ref))
        return web.json_response({"result": result})

    app = web.Application()
    app.router.add_route("*", "/{tail:.*}", handle)
    runner = web.AppRunner(app)
    ready = threading.Event()
    state: Dict[str, Any] = {}

    def serve_thread():
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, host, port)
        loop.run_until_complete(site.start())
        state["loop"] = loop
        ready.set()
        loop.run_forever()

    t = threading.Thread(target=serve_thread, daemon=True,
                         name="serve-http-proxy")
    t.start()
    ready.wait(10)
    _state["proxy"] = (t, runner, state)
    return f"http://{host}:{port}"


def shutdown():
    if _state["controller"] is not None:
        try:
            for name in list(
                    ray.get(_state["controller"].list_deployments.remote())):
                ray.get(_state["controller"].delete_deployment.remote(name))
            ray.kill(_state["controller"])
        except Exception:
            pass
    proxy = _state.get("proxy")
    if proxy:
        try:
            proxy[2]["loop"].call_soon_threadsafe(proxy[2]["loop"].stop)
        except Exception:
            pass
    _state.update({"controller": None, "proxy": None, "handles": {},
                   "routes": {}})
