"""Serve control + data plane.

Reference call path (SURVEY.md §3.5): serve.run -> controller actor ->
DeploymentState reconciliation -> replica actors; request path: proxy/handle
-> router -> replica.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import ray_tpu as ray
from ray_tpu.remote_function import _bulk_submit

CONTROLLER_NAME = "SERVE_CONTROLLER"
# Disaggregated serving: the prefill pool of logical deployment ``name``
# is a controller-level twin deployment named ``name + PREFILL_SUFFIX``
# — all replica machinery (health checks, rolling updates, long-polled
# handle snapshots, draining) applies to it unchanged.
PREFILL_SUFFIX = "@prefill"


def _disagg_capable(cls_or_fn) -> bool:
    """A deployment class that can serve a split tier: it exports the
    prefill handoff AND the decode-side adoption verbs."""
    return (isinstance(cls_or_fn, type)
            and hasattr(cls_or_fn, "prefill_export")
            and hasattr(cls_or_fn, "disagg_generate"))


def _active_config():
    """The effective config: the runtime's (carries ``_system_config``
    overrides) when one is up, else the env-derived global.  The DRIVER
    reads knobs here — its module-level GLOBAL_CONFIG predates
    ray.init; worker-side readers (controller, proxies, replicas) get
    the same values via _worker_config_env."""
    from ray_tpu._private import api_internal
    from ray_tpu._private.config import GLOBAL_CONFIG

    rt = api_internal.get_runtime()
    return getattr(rt, "config", None) or GLOBAL_CONFIG


class ReplicaWrapper:
    """Runs the user callable inside a replica actor process."""

    def __init__(self, cls_or_fn, init_args, init_kwargs, role=None):
        if isinstance(cls_or_fn, type):
            self._callable = cls_or_fn(*init_args, **init_kwargs)
        else:
            self._callable = cls_or_fn
        # Pool tag for the disaggregated tier ("prefill"/"decode"; None
        # = monolithic).  Passed through to the callable so replicas
        # can specialize (tpu_replica.MeshShardedDecoder records it).
        self._role = role
        if role and hasattr(self._callable, "set_serve_role"):
            try:
                self._callable.set_serve_role(role)
            except Exception:
                pass

    def handle_request(self, args, kwargs):
        fn = self._callable
        if not callable(fn):
            fn = fn.__call__
        return fn(*args, **kwargs)

    def call_method(self, method, args, kwargs):
        return getattr(self._callable, method)(*args, **kwargs)

    def health_check(self):
        if hasattr(self._callable, "check_health"):
            self._callable.check_health()
        return True

    def serving_stats(self):
        """Batching observability: one stats dict per batcher attached
        to the user callable (legacy one-shot and continuous engines
        share the shape — steps/batch_occupancy/queued/admitted/
        retired), aggregated per deployment by the controller.  Each
        row is tagged with this replica's pool role so the controller
        can roll the saturation signals up PER POOL."""
        from ray_tpu.serve.batching import _Batcher
        from ray_tpu.serve.continuous import _ContinuousBatcher

        out = []
        holder = self._callable
        for v in list(vars(holder).values()) if hasattr(holder, "__dict__") \
                else []:
            if isinstance(v, (_Batcher, _ContinuousBatcher)):
                row = v.stats()
                row["role"] = self._role or "all"
                out.append(row)
        return out


@ray.remote
class ServeController:
    """Reference: serve/controller.py:69 + _private/deployment_state.py
    (DeploymentStateManager.update, :1855) — a BACKGROUND reconciliation
    loop continuously drives actual replica sets toward target state:
    dead replicas are replaced with no deploy call, autoscaling targets
    are recomputed from handle-reported queue depth
    (_private/autoscaling_policy.py), and version changes roll replicas
    one per tick (rolling update)."""

    RECONCILE_PERIOD_S = 1.0
    METRIC_LOOK_BACK_S = 3.0

    def __init__(self):
        # Autoscale smoothing window: overridable via _system_config /
        # RAY_TPU_SERVE_METRIC_LOOKBACK_S (the controller runs in a
        # worker, so the knob rides _worker_config_env).
        from ray_tpu._private.config import GLOBAL_CONFIG

        self.METRIC_LOOK_BACK_S = GLOBAL_CONFIG.serve_metric_lookback_s
        self._default_downscale_delay_s = \
            GLOBAL_CONFIG.serve_downscale_delay_s
        self._deployments: Dict[str, Dict[str, Any]] = {}
        # name -> list of {"actor": handle, "version": int}
        self._replicas: Dict[str, List[Dict[str, Any]]] = {}
        # route prefix -> deployment name: controller-resident so EVERY
        # node's proxy serves the same routing table (reference: the
        # proxy's route table long-polled from the controller,
        # _private/http_proxy.py + long_poll.py ROUTE_TABLE key).
        self._routes: Dict[str, str] = {}
        # autoscaling inputs: (name, incarnation, handle_id) -> recent
        # (ongoing, ts) samples.  A short look-back window, not just the
        # last sample: instantaneous queue depth oscillates with
        # sampling phase (scale up -> queue drains faster -> next sample
        # reads low -> scale back down), so decisions smooth over
        # METRIC_LOOK_BACK_S (reference: look_back_period_s in
        # autoscaling_policy.py).  Keyed by the deployment INCARNATION
        # (bumped when a name is deleted and redeployed) so a stale
        # handle from a deleted deployment can never feed the fresh
        # deployment's autoscaler (its samples are dropped at record
        # time).
        self._handle_metrics: Dict[tuple, deque] = {}
        # name -> deploy generation; delete+redeploy under one name
        # yields a new incarnation.
        self._incarnations: Dict[str, int] = {}
        # Pool-saturation windows for the disaggregated tier, keyed
        # (name, metric key) — the SAME peak-over-lookback shape as the
        # handle metric windows: reconcile ticks sample each role
        # pool's replica batchers (admission_parks cumulative,
        # tokens_per_step instantaneous) and _pool_desired reads the
        # fresh samples.  record_pool_metric is also a public actor
        # method so tests can inject samples directly.
        self._pool_metrics: Dict[tuple, deque] = {}
        self._last_scale_up: Dict[str, float] = {}
        # Autoscaling observability: name -> [scale_up_events,
        # scale_down_events] (surfaced via serving_stats()).
        self._scale_events: Dict[str, List[int]] = {}
        # Retired replicas draining before the actual kill: handles stop
        # routing to them immediately (they leave get_replicas), but the
        # process lives past the handle-refresh TTL so in-flight requests
        # finish (reference: graceful_shutdown_wait_loop_s drain).
        self._draining: List[tuple] = []  # (actor, kill_at_monotonic)
        self._lock = threading.RLock()
        # Push-based handle updates (reference: _private/long_poll.py:185
        # LongPollHost): every replica-set mutation bumps the version and
        # wakes blocked wait_replicas calls; handles hold one such call
        # open at all times, so scaling/death/drain propagate in one
        # notify instead of a TTL window.
        self._replica_version: Dict[str, int] = {}
        self._version_cv = threading.Condition(self._lock)
        # Serializes whole reconcile ticks: the background loop thread and
        # an actor-method reconcile (deploy/scale) must not both spawn.
        self._reconcile_lock = threading.Lock()
        self._stopped = False
        threading.Thread(target=self._loop, daemon=True,
                         name="serve-reconcile").start()

    def _loop(self):
        while not self._stopped:
            time.sleep(self.RECONCILE_PERIOD_S)
            try:
                self.reconcile()
            except Exception:
                pass

    def deploy(self, name: str, payload: Dict[str, Any]):
        """payload: cls_or_fn, init_args/kwargs, num_replicas, resources,
        optional autoscaling_config.  A changed payload bumps the version;
        reconcile then rolls replicas over to it."""
        def _same(a, b):
            # Compare by pickled bytes: cls_or_fn crosses the wire by
            # value (cloudpickle), so two deploys of identical code
            # deserialize to distinct class objects that == treats as
            # different.  Byte equality is a sound idempotence check; a
            # false negative merely costs a (safe) rolling restart.
            from ray_tpu._private import serialization as _ser

            keys = ("cls_or_fn", "init_args", "init_kwargs",
                    "num_replicas", "num_cpus", "num_tpus",
                    "autoscaling_config", "ray_actor_options", "role")
            try:
                return all(
                    _ser.dumps_inline(a.get(k)) == _ser.dumps_inline(
                        b.get(k)) for k in keys)
            except Exception:
                return False

        with self._lock:
            prev = self._deployments.get(name)
            if prev is not None and _same(prev, payload):
                return True  # idempotent redeploy: no rolling restart
            version = (prev["version"] + 1) if prev is not None else 1
            payload["version"] = version
            if prev is None and name not in self._incarnations:
                # First-ever deploy of this name.  (A redeploy after a
                # delete keeps the incarnation delete_deployment already
                # bumped — bumping at DELETE time, not redeploy time,
                # also invalidates still-live handles' reports during
                # the deleted window, so they cannot repopulate the
                # purged metric map.)
                self._incarnations[name] = 1
            self._deployments[name] = payload
        # Reconcile outside _lock: the tick takes _reconcile_lock then
        # _lock — holding _lock here would invert the order vs the
        # background loop and deadlock.
        self.reconcile()
        return True

    def delete_deployment(self, name: str):
        # A logical deployment's prefill twin dies with it (the twin is
        # never useful alone — its exports have no decode pool to land
        # in).  Cascade BEFORE taking the lock: the recursive call
        # reconciles on its own.
        if not name.endswith(PREFILL_SUFFIX):
            with self._lock:
                twin = name + PREFILL_SUFFIX in self._deployments
            if twin:
                self.delete_deployment(name + PREFILL_SUFFIX)
        with self._lock:
            self._deployments.pop(name, None)
            for key in [k for k in self._pool_metrics if k[0] == name]:
                self._pool_metrics.pop(key, None)
            # Drop the dead incarnation's autoscale state wholesale —
            # metric windows, scale counters, last-scale-up stamp — so
            # the next same-name deploy starts with a clean slate (a
            # stale _last_scale_up would gate the fresh deployment's
            # first downscale against the DEAD deployment's history).
            for key in [k for k in self._handle_metrics if k[0] == name]:
                self._handle_metrics.pop(key, None)
            self._scale_events.pop(name, None)
            self._last_scale_up.pop(name, None)
            # Bump NOW (not at redeploy): surviving handles' reports go
            # stale immediately and record_handle_metric drops them, so
            # the purge above cannot be undone by a live handle still
            # reporting between the delete and a redeploy.
            self._incarnations[name] = self._incarnations.get(name, 0) + 1
            reps = self._replicas.pop(name, [])
            # Routes to a deleted deployment 404 (proxies refresh the
            # table within their TTL) instead of erroring forever.
            for prefix in [p for p, n in self._routes.items()
                           if n == name]:
                self._routes.pop(prefix, None)
            self._bump_version_locked(name)
        for r in reps:
            try:
                ray.kill(r["actor"])
            except Exception:
                pass
        return True

    def _bump_version_locked(self, name: str):
        self._replica_version[name] = \
            self._replica_version.get(name, 0) + 1
        self._version_cv.notify_all()

    def record_handle_metric(self, name: str, handle_id: str,
                             ongoing: int,
                             incarnation: Optional[int] = None):
        """Handles report their in-flight request count — the autoscaling
        signal (reference: handle-side metrics pushed to the controller,
        _private/router.py + autoscaling_policy.py).  Samples are keyed
        by (name, incarnation, handle_id); a report carrying a stale
        incarnation (the handle predates a delete+redeploy of this name)
        is DROPPED — it describes requests against replicas that no
        longer exist and must not scale the fresh deployment."""
        now = time.monotonic()
        with self._lock:
            cur = self._incarnations.get(name, 0)
            if incarnation is None:
                incarnation = cur  # legacy caller: assume current
            if incarnation != cur:
                return False
            q = self._handle_metrics.get((name, incarnation, handle_id))
            if q is None:
                q = self._handle_metrics[
                    (name, incarnation, handle_id)] = deque(maxlen=32)
            q.append((ongoing, now))
        return True

    def deployment_incarnation(self, name: str) -> int:
        with self._lock:
            return self._incarnations.get(name, 0)

    def handle_snapshot(self, name: str):
        """One-RPC handle bootstrap: (replica_version, replicas,
        incarnation)."""
        with self._lock:
            return (self._replica_version.get(name, 0),
                    [r["actor"] for r in self._replicas.get(name, [])],
                    self._incarnations.get(name, 0))

    def _ongoing_locked(self, name: str, now: float) -> int:
        """Summed per-handle PEAK ongoing inside the look-back window —
        robust to sampling phase while load is sustained; an idle
        handle's samples age out and read 0 (downscale_delay then gates
        the shrink).  Only the CURRENT incarnation's windows count
        (record_handle_metric drops stale reports; windows recorded
        before a delete were purged there).  The single source for both
        the autoscaler and serving_stats()."""
        inc = self._incarnations.get(name, 0)
        ongoing = 0
        for (n, i, _h), samples in self._handle_metrics.items():
            if n != name or i != inc:
                continue
            fresh = [v for v, ts in samples
                     if now - ts < self.METRIC_LOOK_BACK_S]
            if fresh:
                ongoing += max(fresh)
        return ongoing

    def _spawn(self, d: Dict[str, Any], version: int):
        # Threaded replicas: concurrent requests are what @serve.batch
        # coalesces (reference: replicas default to many concurrent
        # queries, max_concurrent_queries).
        opts = {"num_cpus": d.get("num_cpus", 1),
                "max_concurrency": d.get("max_concurrency", 8)}
        if d.get("num_tpus"):
            opts["num_tpus"] = d["num_tpus"]
        # Extra actor options (elastic pods: a preemption-tolerant
        # deployment sets {"max_restarts": -1, "max_task_retries": -1}
        # so replicas ride the PR 9 restart + in-flight replay path
        # instead of failing requests at the controller's replacement
        # latency).
        opts.update(d.get("ray_actor_options") or {})
        remote_cls = ray.remote(ReplicaWrapper)
        actor = remote_cls.options(**opts).remote(
            d["cls_or_fn"], d.get("init_args", ()),
            d.get("init_kwargs", {}), d.get("role"))
        return {"actor": actor, "version": version}

    def record_pool_metric(self, name: str, key: str, value: float):
        """One pool-saturation sample ((value, ts) into the (name, key)
        window).  Fed by the reconcile tick's replica polls; public so
        tests can drive the pool autoscaler without real traffic."""
        now = time.monotonic()
        with self._lock:
            q = self._pool_metrics.get((name, key))
            if q is None:
                q = self._pool_metrics[(name, key)] = deque(maxlen=32)
            q.append((float(value), now))
        return True

    def _sample_pool_metrics(self, name: str, reps: List[Dict[str, Any]]):
        """Sample a role pool's saturation signals from its replica
        batchers (parallel, one short shared deadline — a wedged
        replica must not stall the reconcile tick)."""
        refs = []
        for r in reps:
            try:
                refs.append(r["actor"].serving_stats.remote())
            except Exception:
                pass
        done = ray.wait(refs, num_returns=len(refs),
                        timeout=1)[0] if refs else []
        parks = steps = toks = 0
        got = False
        for ref in done:
            try:
                rows = ray.get(ref, timeout=1)
            except Exception:
                continue
            for b in rows:
                got = True
                parks += b.get("admission_parks", 0)
                steps += b.get("steps", 0)
                toks += b.get("tokens_emitted", 0)
        if got:
            self.record_pool_metric(name, "admission_parks", parks)
            self.record_pool_metric(
                name, "tokens_per_step", toks / steps if steps else 0.0)

    def _pool_desired(self, name: str, d: Dict[str, Any],
                      cfg: Dict[str, Any], desired: int,
                      now: float) -> int:
        """Disaggregated pool-saturation scaling on top of the
        handle-ongoing target: a PREFILL pool grows while admission
        parks GREW inside the look-back window (requests are queuing on
        KV admission, not on request count), a DECODE pool grows while
        its tokens_per_step peak sits at/above the configured
        saturation target.  Both only raise ``desired`` — shrinking
        stays with the ongoing-based target + downscale delay."""
        role = d.get("role")
        if not role:
            return desired
        with self._lock:
            cur = len(self._replicas.get(name, []))

            def fresh(key):
                q = self._pool_metrics.get((name, key), ())
                return [v for v, ts in q
                        if now - ts < self.METRIC_LOOK_BACK_S]

            parks = fresh("admission_parks")
            tps = fresh("tokens_per_step")
        if role == "prefill" and cfg.get("scale_on_parks"):
            if len(parks) >= 2 and max(parks) > min(parks):
                desired = max(desired, cur + 1)
        if role == "decode" and cfg.get("target_tokens_per_step"):
            if tps and max(tps) >= float(cfg["target_tokens_per_step"]):
                desired = max(desired, cur + 1)
        return desired

    def _autoscale_target(self, name: str, d: Dict[str, Any]) -> int:
        cfg = d.get("autoscaling_config")
        if not cfg:
            return d.get("num_replicas", 1)
        now = time.monotonic()
        with self._lock:
            ongoing = self._ongoing_locked(name, now)
        target_per = max(cfg.get("target_ongoing_requests", 1), 1e-9)
        import math

        desired = math.ceil(ongoing / target_per)
        desired = self._pool_desired(name, d, cfg, desired, now)
        desired = max(cfg.get("min_replicas", 1),
                      min(cfg.get("max_replicas", 1), desired))
        cur = len(self._replicas.get(name, []))
        if desired > cur:
            fire = False
            with self._lock:
                # Deleted mid-tick: don't repopulate the state the
                # delete-time purge just cleared (a same-name redeploy
                # would inherit the dead deployment's scale-up stamp).
                if name in self._deployments:
                    self._last_scale_up[name] = now
                    self._scale_events.setdefault(name, [0, 0])[0] += 1
                    fire = True
            if fire:
                self._publish_scale_event(name, "up", d)
            return desired
        if desired < cur:
            # Downscale only after a quiet period (reference:
            # downscale_delay_s in autoscaling_policy.py).
            delay = cfg.get("downscale_delay_s",
                            self._default_downscale_delay_s)
            if now - self._last_scale_up.get(name, 0.0) < delay:
                return cur
            fire = False
            with self._lock:
                if name in self._deployments:
                    self._scale_events.setdefault(name, [0, 0])[1] += 1
                    fire = True
            if fire:
                self._publish_scale_event(name, "down", d)
        return desired

    def _publish_scale_event(self, name: str, direction: str,
                             d: Dict[str, Any]):
        """Feed the driver-side node autoscaler (elastic pods): scale
        events ride the worker->driver pubsub ("serve_scale" topic) and
        the head wakes any registered listener, so NODE-level scaling
        reacts to serve-level scaling within one reconcile tick instead
        of a polling interval.  The payload carries the replica resource
        shape for observability; the demand itself reaches the
        autoscaler as the queued replica-creation shapes.  Built and
        sent OUTSIDE the controller lock (socket IO)."""
        try:
            from ray_tpu._private import serialization as _ser
            from ray_tpu._private.worker_main import get_worker_runtime

            rt = get_worker_runtime()
            if rt is None:
                return  # in-process controller (unit tests): no pubsub
            shape = {"CPU": float(d.get("num_cpus", 1))}
            if d.get("num_tpus"):
                shape["TPU"] = float(d["num_tpus"])
            rt.publish_event("serve_scale", _ser.dumps_inline(
                {"deployment": name, "direction": direction,
                 "shape": shape}))
        except Exception:
            pass  # observability only: never fail a reconcile over it

    def reconcile(self):
        """One control-loop tick: health-check, replace dead, scale to
        target (static or autoscaled), roll one outdated replica."""
        with self._reconcile_lock:
            return self._reconcile_once()  # noqa: RTL505 -- the reconcile serializer is strictly OUTER to the controller lock; no path under _lock takes _reconcile_lock

    DRAIN_S = 3.0

    def _retire(self, rep):
        with self._lock:
            self._draining.append(
                (rep["actor"], time.monotonic() + self.DRAIN_S))

    def _reap_draining(self):
        now = time.monotonic()
        with self._lock:
            due = [a for a, t in self._draining if t <= now]
            self._draining = [(a, t) for a, t in self._draining if t > now]
        for a in due:
            try:
                ray.kill(a)
            except Exception:
                pass

    def _reconcile_once(self):
        self._reap_draining()
        with self._lock:
            names = list(self._deployments)
        counts = {}
        for name in names:
            with self._lock:
                d = self._deployments.get(name)
                if d is None:
                    continue
                reps = list(self._replicas.get(name, []))
                version = d["version"]
            alive = []
            for r in reps:
                try:
                    ray.get(r["actor"].health_check.remote(), timeout=5)
                    alive.append(r)
                except Exception:
                    pass  # dead or unhealthy: dropped, replaced below
            if d.get("role") and d.get("autoscaling_config"):
                # Role pools autoscale on batcher saturation too: feed
                # this tick's sample into the pool metric window.
                self._sample_pool_metrics(name, alive)
            target = self._autoscale_target(name, d)
            while len(alive) < target:
                alive.append(self._spawn(d, version))
            while len(alive) > target:
                self._retire(alive.pop())
            # Rolling update: one outdated replica per tick — spawn the
            # replacement first, then retire (drain) the old one, so
            # capacity never dips and in-flight requests finish
            # (reference: rolling updates in deployment_state).
            outdated = [r for r in alive if r["version"] != version]
            if outdated:
                alive.append(self._spawn(d, version))
                old = outdated[0]
                alive.remove(old)
                self._retire(old)
            with self._lock:
                if name in self._deployments:
                    prev_ids = [id(r["actor"])
                                for r in self._replicas.get(name, [])]
                    self._replicas[name] = alive
                    if prev_ids != [id(r["actor"]) for r in alive]:
                        self._bump_version_locked(name)
                    counts[name] = len(alive)
                    continue
            # Deleted mid-tick: nothing tracks these replicas anymore.
            for r in alive:
                try:
                    ray.kill(r["actor"])
                except Exception:
                    pass
        return counts

    def get_replicas(self, name: str):
        with self._lock:
            return [r["actor"] for r in self._replicas.get(name, [])]

    def get_replicas_versioned(self, name: str):
        with self._lock:
            return (self._replica_version.get(name, 0),
                    [r["actor"] for r in self._replicas.get(name, [])])

    def wait_replicas(self, name: str, seen_version: int,
                      timeout: float = 30.0):
        """Long-poll: block until the replica set changes past
        ``seen_version`` (or timeout), then return (version, replicas,
        incarnation) (reference: LongPollHost.listen_for_change,
        _private/long_poll.py:185).  The incarnation rides along so a
        handle surviving a delete+redeploy of its name re-keys its
        metric reports instead of feeding the controller stale-keyed
        samples forever."""
        deadline = time.monotonic() + timeout
        with self._version_cv:
            while self._replica_version.get(name, 0) <= seen_version:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._version_cv.wait(left)
            return (self._replica_version.get(name, 0),
                    [r["actor"] for r in self._replicas.get(name, [])],
                    self._incarnations.get(name, 0))

    def num_replicas(self, name: str) -> int:
        with self._lock:
            return len(self._replicas.get(name, []))

    def list_deployments(self):
        with self._lock:
            return {n: {"num_replicas": d.get("num_replicas", 1),
                        "version": d.get("version", 1),
                        "autoscaling": bool(d.get("autoscaling_config"))}
                    for n, d in self._deployments.items()}

    def serving_stats(self, name: Optional[str] = None):
        """Per-deployment serving observability (the transfer_stats()
        analog for the serve plane): queued/ongoing request counts,
        batch occupancy and step totals aggregated over the replicas'
        batchers, plus the autoscale scale-up/scale-down event pair."""
        now = time.monotonic()
        with self._lock:
            names = [name] if name is not None else list(self._deployments)
            if name is not None and not name.endswith(PREFILL_SUFFIX) \
                    and name + PREFILL_SUFFIX in self._deployments:
                # Single-name queries cover the logical deployment: the
                # prefill twin's pools fold into the base entry below.
                names.append(name + PREFILL_SUFFIX)
            snap = {}
            for n in names:
                ups, downs = self._scale_events.get(n, [0, 0])
                snap[n] = {
                    "replicas": [r["actor"]
                                 for r in self._replicas.get(n, [])],
                    "ongoing": self._ongoing_locked(n, now),
                    "scale_ups": ups,
                    "scale_downs": downs,
                }
        # Serving-memory counters (paged KV plane): summed across every
        # replica batcher; zero when the paged_kv knob is off or no
        # engine is attached (the batcher then omits the keys and
        # .get() keeps the zeros — the knob-off pin).
        _KV_SUM = ("kv_blocks_total", "kv_blocks_used", "prefix_hits",
                   "prefix_blocks_shared", "cow_copies", "spec_proposed",
                   "spec_accepted", "tokens_emitted", "admission_parks",
                   "admission_rejects", "kv_chains_exported",
                   "kv_chains_imported", "kv_chain_bytes_streamed")
        out = {}
        for n, s in snap.items():
            reps = s.pop("replicas")
            agg = {"replicas": len(reps), "queued": 0, "steps": 0,
                   "admitted": 0, "retired": 0, "step_errors": 0,
                   "batch_occupancy": 0.0, "max_batch_size": 0,
                   "kv_occupancy": 0.0, "tokens_per_step": 0.0, **s}
            agg.update({k: 0 for k in _KV_SUM})
            # Per-pool saturation rollup (the autoscaler's observable
            # inputs): replica rows are tagged with their pool role by
            # ReplicaWrapper ("all" when monolithic).
            pools: Dict[str, Dict[str, Any]] = {}
            occ_steps = 0.0
            modes = set()
            # Replica RPCs run OUTSIDE _lock (a saturated replica must
            # not wedge the controller) and are issued in PARALLEL with
            # one shared deadline — N unreachable replicas cost one 5s
            # wait, not N; whoever cannot answer in time is skipped and
            # the aggregate stays partial-but-live.
            refs = []
            for r in reps:
                try:
                    refs.append(r.serving_stats.remote())
                except Exception:
                    pass
            done = ray.wait(refs, num_returns=len(refs),
                            timeout=5)[0] if refs else []
            for ref in done:
                try:
                    rows = ray.get(ref, timeout=1)
                except Exception:
                    continue
                for b in rows:
                    agg["queued"] += b["queued"]
                    agg["steps"] += b["steps"]
                    agg["admitted"] += b["admitted"]
                    agg["retired"] += b["retired"]
                    agg["step_errors"] += b["step_errors"]
                    occ_steps += b["batch_occupancy"] * b["steps"]
                    # The mode string carries the paged flag
                    # ("continuous+paged"), so the rollup's mode/mixed
                    # logic reports the memory plane too.
                    modes.add(b["mode"])
                    agg["max_batch_size"] = max(agg["max_batch_size"],
                                                b["max_batch_size"])
                    for k in _KV_SUM:
                        agg[k] += b.get(k, 0)
                    p = pools.setdefault(b.get("role") or "all", {
                        "replicas": 0, "queued": 0, "steps": 0,
                        "tokens_emitted": 0, "admission_parks": 0,
                        "tokens_per_step": 0.0})
                    p["replicas"] += 1
                    p["queued"] += b["queued"]
                    p["steps"] += b["steps"]
                    p["tokens_emitted"] += b.get("tokens_emitted", 0)
                    p["admission_parks"] += b.get("admission_parks", 0)
            if modes:
                agg["mode"] = modes.pop() if len(modes) == 1 else "mixed"
            if agg["steps"]:
                agg["batch_occupancy"] = round(occ_steps / agg["steps"], 3)
                agg["tokens_per_step"] = round(
                    agg["tokens_emitted"] / agg["steps"], 3)
            if agg["kv_blocks_total"]:
                agg["kv_occupancy"] = round(
                    agg["kv_blocks_used"] / agg["kv_blocks_total"], 3)
            for p in pools.values():
                if p["steps"]:
                    p["tokens_per_step"] = round(
                        p["tokens_emitted"] / p["steps"], 3)
            agg["pools"] = pools
            out[n] = agg
        # Fold each prefill twin into its logical deployment's entry:
        # the twin's pool rollup appears under the base name's "pools"
        # and its chain-handoff stream counters add to the base (chains
        # stream FROM prefill replicas, imports count on decode ones).
        for tn in [k for k in list(out) if k.endswith(PREFILL_SUFFIX)]:
            base = tn[: -len(PREFILL_SUFFIX)]
            if base not in out:
                continue
            twin = out.pop(tn)
            out[base]["pools"].update(twin.get("pools", {}))
            out[base]["prefill_replicas"] = twin.get("replicas", 0)
            for k in ("kv_chains_exported", "kv_chain_bytes_streamed",
                      "admission_parks", "prefix_hits",
                      "prefix_blocks_shared"):
                out[base][k] = out[base].get(k, 0) + twin.get(k, 0)
        return out if name is None else out.get(name, {})

    def set_route(self, prefix: str, name: str):
        with self._lock:
            self._routes[prefix] = name
        return True

    def get_routes(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._routes)

    def scale(self, name: str, num_replicas: int):
        with self._lock:
            self._deployments[name]["num_replicas"] = num_replicas
        self.reconcile()
        return True

    def stop(self):
        self._stopped = True
        return True


class _P2CRouterBase:
    """Shared power-of-two-choices routing state (used by replica
    handles AND proxy handles): live in-flight counts per target,
    incremented at dispatch, decremented by an idempotent weakref
    finalizer when the caller drops the result ref.  Subclasses own
    ``self._lock`` acquisition around the ``_locked`` helpers."""

    def _router_init(self):
        self._rr = itertools.count()
        # The prefill pool's tie-break counter must be SEPARATE: a
        # disagg dispatch ticks both pickers, and a shared counter's
        # stride-2 aliasing over a two-replica pool would propose the
        # same prefill replica on every tie.
        self._prefill_rr = itertools.count()
        self._lock = threading.Lock()
        self._inflight: Dict[int, int] = {}  # target key -> live count
        # Result-ref ids currently counted in _inflight: finalizers
        # decrement only while their ref is still counted, so a ref an
        # external reconcile already pruned cannot decrement twice and
        # erase another request's count.
        self._counted: Dict[bytes, int] = {}  # ref id -> target key
        # (weakref(result_ref), target key) per dispatched request: the
        # periodic ground-truth reconcile — finalizers only fire when
        # the caller DROPS a ref, so a completed-but-held ref would
        # otherwise read as in-flight forever and skew routing.
        self._outstanding: List[tuple] = []
        self._last_reconcile = 0.0
        self._ongoing = 0  # last reconcile's pending-request count
        # Dropped-ref ids queued by the (LOCK-FREE) finalizer, drained
        # under _lock on the next pick/dispatch: CPython runs finalizers
        # synchronously at deallocation, which can happen in a frame
        # that already holds _lock (the reconcile's temporaries can be
        # the last strong reference) — taking the non-reentrant lock
        # there would self-deadlock the router.
        self._dead_refs: List[bytes] = []

    def _pick_two_locked(self, reps: List[Any], rr=None):
        """Two DISTINCT candidates (round-robin first — idle routers
        keep alternating — a random draw second), route to the
        less-loaded one, ties to the round-robin choice."""
        import random

        self._drain_dead_locked()
        i = next(rr if rr is not None else self._rr) % len(reps)
        j = random.randrange(len(reps))
        if j == i:
            j = (j + 1) % len(reps)
        a, b = reps[i], reps[j]
        if self._inflight.get(id(b), 0) < self._inflight.get(id(a), 0):
            return b
        return a

    def _dec_inflight(self, idbin: bytes):
        """Weakref finalizer for a result ref: the caller consumed (and
        dropped) the result — no longer in flight on its target.
        LOCK-FREE (list.append is GIL-atomic): see _dead_refs."""
        self._dead_refs.append(idbin)

    def _drain_dead_locked(self):
        """Apply queued finalizer decrements.  Runs at every pick and
        dispatch, under _lock."""
        while True:
            try:
                idbin = self._dead_refs.pop()
            except IndexError:
                return
            rkey = self._counted.pop(idbin, None)
            if rkey is None:
                continue
            for k in (rkey if isinstance(rkey, tuple) else (rkey,)):
                c = self._inflight.get(k, 0)
                if c <= 1:
                    self._inflight.pop(k, None)
                else:
                    self._inflight[k] = c - 1

    def _count_dispatch_locked(self, idbin: bytes, rkey):
        """``rkey`` is one target key or a tuple of them: a disagg
        dispatch counts against BOTH its decode and prefill picks, so
        p2c over the prefill pool sees a live load signal too."""
        self._drain_dead_locked()
        for k in (rkey if isinstance(rkey, tuple) else (rkey,)):
            self._inflight[k] = self._inflight.get(k, 0) + 1
        self._counted[idbin] = rkey

    # How often dispatch triggers the ground-truth reconcile (also the
    # handle's controller-metric cadence).
    _RECONCILE_PERIOD = 0.5

    def _finalize_on_drop(self, ref):
        import weakref

        weakref.finalize(ref, self._dec_inflight, ref.id().binary())

    def _note_dispatch(self, ref, target) -> bool:
        """Register one dispatched request: weak-track the result ref
        (the router must never pin results), bump the target's live
        count, arm the drop finalizer; every _RECONCILE_PERIOD also run
        the ground-truth reconcile (ongoing count left in
        ``self._ongoing``).  Returns True when the reconcile ran."""
        import weakref

        now = time.monotonic()
        key = (tuple(id(t) for t in target)
               if isinstance(target, tuple) else id(target))
        with self._lock:
            self._outstanding.append((weakref.ref(ref), key))
            self._count_dispatch_locked(ref.id().binary(), key)
            ran = now - self._last_reconcile >= self._RECONCILE_PERIOD
            if ran:
                self._last_reconcile = now
                self._ongoing = self._reconcile_outstanding_locked()
        self._finalize_on_drop(ref)
        return ran

    def _reconcile_outstanding_locked(self) -> int:
        """Ground-truth prune: drop completed/collected refs from the
        outstanding list and rebuild the in-flight counts AND the
        counted-ref map from the actually-pending refs (keeping the
        finalizers idempotent).  Returns the ongoing request count."""
        live = [(w(), k) for w, k in self._outstanding]
        live = [(r, k) for r, k in live if r is not None]
        if live:
            import ray_tpu as _ray

            done, pending = _ray.wait(
                [r for r, _ in live], num_returns=len(live), timeout=0)
            pend_set = {r.id() for r in pending}
            self._outstanding = [
                (w, k) for w, k in self._outstanding
                if (r := w()) is not None and r.id() in pend_set]
        else:
            self._outstanding = []
        counts: Dict[int, int] = {}
        counted: Dict[bytes, Any] = {}
        for w, k in self._outstanding:
            for kk in (k if isinstance(k, tuple) else (k,)):
                counts[kk] = counts.get(kk, 0) + 1
            r = w()
            if r is not None:
                counted[r.id().binary()] = k
        self._inflight = counts
        self._counted = counted
        return len(self._outstanding)


class DeploymentHandle(_P2CRouterBase):
    """Router over replicas (reference: _private/router.py:262
    ReplicaSet / handle API).

    Replica-set changes arrive by PUSH: a background long-poll thread
    keeps one blocking ``wait_replicas`` call open at the controller
    (reference: LongPollClient, _private/long_poll.py:68), so a
    downscaled/drained replica stops receiving traffic the moment the
    controller retires it — no TTL window.  Routing is least-loaded
    power-of-two-choices on LIVE per-replica ongoing-request counts —
    the same metric the handle reports to the controller's autoscaler —
    incremented at dispatch and decremented when the caller's result
    ref dies (weakref finalizer), with the periodic ray.wait prune as
    the ground-truth reconciler (reference: the queue-length-aware
    replica scheduler in _private/router.py).
    """

    # Prefix-affinity granularity: prompts map to their chunk-aligned
    # prefixes; longest-match lookup walks chunk boundaries down.
    _AFFINITY_CHUNK = 8
    # LRU cap on the affinity table (a routing hint, not a registry).
    _AFFINITY_CAP = 512

    def __init__(self, name: str, controller):
        import os

        _CFG = _active_config()
        self._name = name
        self._controller = controller
        self._replicas: List[Any] = []
        self._version = -1
        self._incarnation = 0
        self._router_init()
        # Disaggregated routing state: with the split on, requests
        # divert to decode-orchestrated handoff once the prefill twin
        # has replicas; prefill choice is prefix-affinity over p2c.
        # The affinity lock is a documented LEAF (pinned in
        # tests/test_lockcheck.py): it guards only the table + counters
        # and never wraps an out-call.
        self._disagg = bool(_CFG.disaggregated_serving) \
            and not name.endswith(PREFILL_SUFFIX)
        self._affinity_on = bool(_CFG.prefix_affinity)
        self._prefill_name = name + PREFILL_SUFFIX
        self._prefill_replicas: List[Any] = []
        self._prefill_version = -1
        from collections import OrderedDict as _OD

        self._affinity: "_OD[tuple, bytes]" = _OD()  # chunk key -> actor id
        self._affinity_lock = threading.Lock()  # lock-order: leaf
        self._router_prefix_hits = 0
        self._router_prefix_misses = 0
        # Autoscaling signal: the router's outstanding-ref prune also
        # yields the ongoing count reported to the controller
        # (reference: handle-side num_queued/ongoing metrics feeding
        # autoscaling_policy.py).
        self._handle_id = os.urandom(4).hex()
        self._closed = False
        self._refresh()
        self._poller = threading.Thread(
            target=self._long_poll_loop, daemon=True,
            name=f"serve-handle-{name}")
        self._poller.start()
        if self._disagg:
            self._prefill_poller = threading.Thread(
                target=self._prefill_poll_loop, daemon=True,
                name=f"serve-handle-{name}-prefill")
            self._prefill_poller.start()

    def _refresh(self):
        ver, reps, inc = ray.get(
            self._controller.handle_snapshot.remote(self._name))
        with self._lock:
            self._version = ver
            self._replicas = reps
            self._incarnation = inc
        if self._disagg:
            pver, preps, _inc = ray.get(
                self._controller.handle_snapshot.remote(
                    self._prefill_name))
            with self._lock:
                if pver > self._prefill_version:
                    self._prefill_version = pver
                    self._prefill_replicas = preps

    def _long_poll_loop(self):
        while not self._closed:
            try:
                ver, reps, inc = ray.get(
                    self._controller.wait_replicas.remote(
                        self._name, self._version, 30.0),
                    timeout=40.0)
            except Exception:
                time.sleep(1.0)
                continue
            with self._lock:
                if ver > self._version:
                    self._version = ver
                    self._replicas = reps
                    self._incarnation = inc

    def _prefill_poll_loop(self):
        """Second long-poll, over the prefill twin's replica set: the
        disagg diversion engages only once the twin has replicas, so a
        handle created before the split deployed (or after the twin
        was deleted) keeps serving the monolithic path."""
        while not self._closed:
            try:
                ver, reps, _inc = ray.get(
                    self._controller.wait_replicas.remote(
                        self._prefill_name, self._prefill_version, 30.0),
                    timeout=40.0)
            except Exception:
                time.sleep(1.0)
                continue
            with self._lock:
                if ver > self._prefill_version:
                    self._prefill_version = ver
                    self._prefill_replicas = reps

    def close(self):
        """Stop the long-poll thread (handles replaced by
        get_deployment_handle's stale-swap would otherwise leak a
        poller holding a standing controller RPC forever)."""
        self._closed = True

    def _pick(self):
        with self._lock:
            if not self._replicas:
                pass  # fall through to the blocking refresh below
            else:
                reps = self._replicas
                if len(reps) == 1:
                    return reps[0]
                # Power-of-two-choices on the live ongoing-request
                # counts — the same metric this handle reports to the
                # controller's autoscaler.
                return self._pick_two_locked(reps)
        self._refresh()
        with self._lock:
            if not self._replicas:
                raise RuntimeError(
                    f"deployment {self._name} has no replicas")
            return self._replicas[next(self._rr) % len(self._replicas)]

    def _track(self, ref, replica):
        if self._note_dispatch(ref, replica):
            # Fire-and-forget: the metric must never block the data
            # path.  (_incarnation is a bare int read — a racing
            # long-poll update at worst sends one report the controller
            # drops as stale.)
            self._controller.record_handle_metric.remote(
                self._name, self._handle_id, self._ongoing,
                self._incarnation)
        return ref

    def _pick_prefill(self, prompt):
        """Prefix-affinity choice over the prefill pool: route to the
        replica that most recently served the LONGEST chunk-aligned
        prefix of ``prompt`` (its PrefixCache holds those blocks — the
        prefill there is mostly cache reuse), p2c on miss.  The picked
        replica is registered under every chunk boundary of the prompt
        so longer shared-prefix prompts keep landing with it."""
        with self._lock:
            reps = list(self._prefill_replicas)
        if not reps:
            return None
        by_id = {getattr(r, "_actor_id", id(r)): r for r in reps}
        chunk = self._AFFINITY_CHUNK
        keys: List[tuple] = []
        if isinstance(prompt, (list, tuple)) and prompt:
            keys = [tuple(prompt[: L * chunk])
                    for L in range(1, len(prompt) // chunk + 1)]
        pick = None
        if self._affinity_on and keys:
            with self._affinity_lock:
                for key in reversed(keys):  # longest match first
                    aid = self._affinity.get(key)
                    if aid is None:
                        continue
                    target = by_id.get(aid)
                    if target is None:
                        # Dead/retired replica: prune the stale hint.
                        self._affinity.pop(key, None)
                        continue
                    self._affinity.move_to_end(key)
                    self._router_prefix_hits += 1
                    pick = target
                    break
                else:
                    self._router_prefix_misses += 1
        if pick is None:
            if len(reps) == 1:
                pick = reps[0]
            else:
                with self._lock:
                    pick = self._pick_two_locked(
                        reps, rr=self._prefill_rr)
        if self._affinity_on and keys:
            aid = getattr(pick, "_actor_id", id(pick))
            with self._affinity_lock:
                for key in keys:
                    self._affinity[key] = aid
                    self._affinity.move_to_end(key)
                while len(self._affinity) > self._AFFINITY_CAP:
                    self._affinity.popitem(last=False)
        return pick

    def _remote_disagg(self, body: Dict[str, Any]):
        """Disaggregated dispatch: pick the prefill replica by prefix
        affinity and a decode replica by p2c, then hand the request to
        the DECODE side (``disagg_generate`` orchestrates prefill →
        chain stream → local decode) — the caller still holds exactly
        one result ref, and the chain itself rides the data plane
        between the two replica workers."""
        pre = self._pick_prefill(body.get("prompt"))
        dec = self._pick()
        ref = dec.call_method.remote(
            "disagg_generate", (body, pre, self._prefill_name), {})
        # Count the dispatch against BOTH picks: the prefill leg is a
        # prefix of the request's lifetime, and without a live count
        # p2c over the (decode-traffic-free) prefill pool would tie on
        # zero forever and pile every miss onto one replica.
        return self._track(ref, (dec, pre))

    def router_stats(self) -> Dict[str, int]:
        """Affinity routing counters (zero while the split is off)."""
        with self._affinity_lock:
            return {"router_prefix_hits": self._router_prefix_hits,
                    "router_prefix_misses": self._router_prefix_misses}

    def remote(self, *args, **kwargs):
        if self._disagg and not kwargs and len(args) == 1 \
                and isinstance(args[0], dict):
            with self._lock:
                ready = bool(self._prefill_replicas)
            if ready:
                return self._remote_disagg(args[0])
        replica = self._pick()
        return self._track(replica.handle_request.remote(args, kwargs),
                           replica)

    def method(self, method_name: str):
        handle = self

        class _M:
            def remote(self, *args, **kwargs):
                replica = handle._pick()
                return handle._track(replica.call_method.remote(
                    method_name, args, kwargs), replica)

        return _M()


@ray.remote
class RequestProxy:
    """Data-plane request proxy (the serving twin of the per-node HTTP
    proxies, minus HTTP): a worker-resident actor holding worker-side
    ``DeploymentHandle``s, so every replica call it routes rides the
    DirectCaller actor channels — request/response payloads move over
    the striped object plane and lease-granted dispatch, and steady-
    state serving traffic adds ZERO ``head_brokered_submits`` (the head
    sees only actor resolution + blocked/unblocked control messages).
    Callers reach it through :class:`ProxiedDeploymentHandle`.

    LOCK ORDER: ``_stats_lock`` is an independent leaf (counters only);
    ``_create_lock`` serializes first-request handle construction and
    is held across controller RPCs but never while another local serve
    lock is held.
    """

    def __init__(self):
        self._controller = ray.get_actor(CONTROLLER_NAME)
        self._handles: Dict[str, DeploymentHandle] = {}
        self._create_lock = threading.Lock()
        self._stats_lock = threading.Lock()
        self._routed = 0

    def ping(self):
        return True

    def _handle_for(self, name: str) -> DeploymentHandle:
        h = self._handles.get(name)  # GIL-atomic read; writes below
        if h is not None:
            return h
        with self._create_lock:
            h = self._handles.get(name)
            if h is None:
                h = self._handles[name] = DeploymentHandle(
                    name, self._controller)
        return h

    def handle_request(self, name: str, args, kwargs):
        with self._stats_lock:
            self._routed += 1
        h = self._handle_for(name)
        # Blocking get on a proxy thread (max_concurrency bounds the
        # concurrent request streams): the replica's result payload is
        # pulled over the data plane into this worker's store and
        # returned as this call's own result.
        return ray.get(h.remote(*args, **(kwargs or {})))

    def call_method(self, name: str, method: str, args, kwargs):
        with self._stats_lock:
            self._routed += 1
        h = self._handle_for(name)
        return ray.get(h.method(method).remote(*args, **(kwargs or {})))

    def proxy_stats(self):
        # Router counters summed OUTSIDE _stats_lock: router_stats()
        # takes each handle's affinity leaf lock, and nesting it under
        # _stats_lock would give this proxy's two leaves an ordering.
        hits = misses = 0
        for h in list(self._handles.values()):
            rs = h.router_stats()
            hits += rs["router_prefix_hits"]
            misses += rs["router_prefix_misses"]
        with self._stats_lock:
            return {"routed": self._routed,
                    "deployments": sorted(self._handles),
                    "router_prefix_hits": hits,
                    "router_prefix_misses": misses}


class ProxiedDeploymentHandle(_P2CRouterBase):
    """Caller-side handle that routes requests through the proxy tier
    (``serve.start(num_proxies=N)``) instead of calling replicas
    directly: proxy choice is power-of-two-choices on this handle's
    live in-flight counts, replica choice happens inside the proxy
    (its own p2c handle).  Drivers and external clients thus never
    touch replica actors; their single actor call lands on a proxy
    whose replica traffic stays on the direct data plane."""

    def __init__(self, name: str, proxies: List[Any]):
        if not proxies:
            raise ValueError("proxy tier is empty")
        self._name = name
        self._proxies = list(proxies)
        self._tier_gen = _state.get("proxy_tier_gen", 0)
        self._router_init()

    def _pick(self):
        reps = self._proxies
        if len(reps) == 1:
            return reps[0]
        with self._lock:
            return self._pick_two_locked(reps)

    def _track(self, ref, proxy):
        # Same dispatch bookkeeping as DeploymentHandle, minus the
        # controller metric (proxies report replica-side).
        self._note_dispatch(ref, proxy)
        return ref

    def remote(self, *args, **kwargs):
        p = self._pick()
        return self._track(
            p.handle_request.remote(self._name, args, kwargs), p)

    def method(self, method_name: str):
        handle = self

        class _M:
            def remote(self, *args, **kwargs):
                p = handle._pick()
                return handle._track(p.call_method.remote(
                    handle._name, method_name, args, kwargs), p)

        return _M()


class Deployment:
    """Result of @serve.deployment — bind/deploy surface (reference:
    serve/deployment.py)."""

    def __init__(self, cls_or_fn, name: str, num_replicas: int = 1,
                 num_cpus: float = 1, num_tpus: int = 0,
                 route_prefix: Optional[str] = None,
                 autoscaling_config: Optional[Dict[str, Any]] = None,
                 max_concurrency: int = 8,
                 ray_actor_options: Optional[Dict[str, Any]] = None,
                 role: Optional[str] = None,
                 prefill_replicas: int = 0):
        if role not in (None, "prefill", "decode"):
            raise ValueError(
                f"role must be 'prefill', 'decode' or None, got {role!r}")
        self._cls_or_fn = cls_or_fn
        self.name = name
        self.num_replicas = num_replicas
        self.num_cpus = num_cpus
        self.num_tpus = num_tpus
        self.route_prefix = route_prefix or f"/{name}"
        # Disaggregated serving: role pins this deployment to one side
        # of the prefill/decode split; prefill_replicas sizes the
        # auto-created prefill twin when serve.run splits a role-less
        # deployment under GLOBAL_CONFIG.disaggregated_serving.
        self.role = role
        self.prefill_replicas = prefill_replicas
        # {min_replicas, max_replicas, target_ongoing_requests,
        #  downscale_delay_s} (reference: serve AutoscalingConfig)
        self.autoscaling_config = autoscaling_config
        # Concurrent request threads per replica (reference:
        # max_concurrent_queries).  A continuous-batching replica wants
        # this ABOVE max_batch_size: callers park in the batcher, so
        # the thread pool bounds admission, not batch occupancy.
        self.max_concurrency = max_concurrency
        # Extra @ray.remote options for the replica actors (reference:
        # serve's ray_actor_options).  Elastic pods: {"max_restarts":
        # -1, "max_task_retries": -1} makes replicas preemption-
        # tolerant (restart + in-flight call replay).
        self.ray_actor_options = ray_actor_options
        self._init_args = ()
        self._init_kwargs = {}

    def options(self, **kw) -> "Deployment":
        d = Deployment(self._cls_or_fn, kw.get("name", self.name),
                       kw.get("num_replicas", self.num_replicas),
                       kw.get("num_cpus", self.num_cpus),
                       kw.get("num_tpus", self.num_tpus),
                       kw.get("route_prefix", self.route_prefix),
                       kw.get("autoscaling_config",
                              self.autoscaling_config),
                       kw.get("max_concurrency", self.max_concurrency),
                       kw.get("ray_actor_options",
                              self.ray_actor_options),
                       kw.get("role", self.role),
                       kw.get("prefill_replicas", self.prefill_replicas))
        d._init_args = self._init_args
        d._init_kwargs = self._init_kwargs
        return d

    def bind(self, *args, **kwargs) -> "Deployment":
        d = self.options()
        d._init_args = args
        d._init_kwargs = kwargs
        return d


def deployment(cls_or_fn=None, *, name: Optional[str] = None,
               num_replicas: int = 1, num_cpus: float = 1,
               num_tpus: int = 0, route_prefix: Optional[str] = None,
               autoscaling_config: Optional[Dict[str, Any]] = None,
               max_concurrency: int = 8,
               ray_actor_options: Optional[Dict[str, Any]] = None,
               role: Optional[str] = None, prefill_replicas: int = 0):
    """@serve.deployment (reference: serve/api.py deployment)."""

    def wrap(target):
        return Deployment(target, name or target.__name__, num_replicas,
                          num_cpus, num_tpus, route_prefix,
                          autoscaling_config, max_concurrency,
                          ray_actor_options, role, prefill_replicas)

    if cls_or_fn is not None:
        return wrap(cls_or_fn)
    return wrap


_state: Dict[str, Any] = {"controller": None, "proxy": None,
                          "handles": {}, "routes": {}}


def _get_controller():
    if _state["controller"] is None:
        _state["controller"] = ServeController.options(
            name=CONTROLLER_NAME, max_concurrency=64).remote()
    return _state["controller"]


def run(target: Deployment, *, name: Optional[str] = None
        ) -> DeploymentHandle:
    """Deploy + return a handle (reference: serve.run, api.py:458).

    Disaggregated split: with ``GLOBAL_CONFIG.disaggregated_serving``
    on and a role-less, disagg-capable target, ONE serve.run call
    deploys TWO pools behind the logical name — the base deployment
    becomes the decode pool and a ``<name>@prefill`` twin (sized by
    ``prefill_replicas``, default 1) runs prompt-only steps.  The
    returned handle routes requests decode-side with prefix-affinity
    prefill choice; an explicit ``role="prefill"`` deployment lands
    directly under the twin name (manual pool management)."""
    _CFG = _active_config()
    controller = _get_controller()
    dep_name = name or target.name
    role = target.role
    split = (_CFG.disaggregated_serving and role is None
             and _disagg_capable(target._cls_or_fn))
    if role == "prefill" and not dep_name.endswith(PREFILL_SUFFIX):
        dep_name = dep_name + PREFILL_SUFFIX
    payload = {
        "cls_or_fn": target._cls_or_fn,
        "init_args": target._init_args,
        "init_kwargs": target._init_kwargs,
        "num_replicas": target.num_replicas,
        "num_cpus": target.num_cpus,
        "num_tpus": target.num_tpus,
        "autoscaling_config": target.autoscaling_config,
        "max_concurrency": target.max_concurrency,
        "ray_actor_options": target.ray_actor_options,
        "role": "decode" if split else role,
    }
    ray.get(controller.deploy.remote(dep_name, payload))
    if split:
        twin = dict(payload)
        twin["role"] = "prefill"
        twin["num_replicas"] = target.prefill_replicas or 1
        ray.get(controller.deploy.remote(
            dep_name + PREFILL_SUFFIX, twin))
    # Route registered at the CONTROLLER so every node's proxy serves it
    # (the driver-thread proxy keeps its local copy too).
    ray.get(controller.set_route.remote(target.route_prefix, dep_name))
    old = _state["handles"].get(dep_name)
    if isinstance(old, DeploymentHandle):
        old.close()  # a redeploy replaces the cached handle: stop its poller
    handle = _make_handle(dep_name, controller)
    _state["handles"][dep_name] = handle
    _state["routes"][target.route_prefix] = handle
    return handle


def _make_handle(name: str, controller):
    """Proxy-tier routing when serve.start(num_proxies=N) ran; direct
    replica routing otherwise."""
    proxies = _state.get("request_proxies")
    if proxies:
        return ProxiedDeploymentHandle(name, proxies)
    return DeploymentHandle(name, controller)


def get_deployment_handle(name: str):
    h = _state["handles"].get(name)
    proxies = _state.get("request_proxies")
    stale = (proxies and isinstance(h, DeploymentHandle)) or \
        (not proxies and isinstance(h, ProxiedDeploymentHandle)) or \
        (isinstance(h, ProxiedDeploymentHandle)
         and h._tier_gen != _state.get("proxy_tier_gen", 0))
    if h is None or stale:
        if isinstance(h, DeploymentHandle):
            h.close()  # stop the replaced handle's long-poll thread
        nh = _make_handle(name, _get_controller())
        _state["handles"][name] = nh
        # The routes table may hold the SAME object (serve.run stores
        # one handle in both); the HTTP proxy reads routes directly, so
        # swap it there too — a closed handle's replica set is frozen.
        for prefix, rh in list(_state["routes"].items()):
            if rh is h:
                _state["routes"][prefix] = nh
        h = nh
    return h


def serving_stats(name: Optional[str] = None) -> Dict[str, Any]:
    """Per-deployment serving observability snapshot (the serve-plane
    analog of Runtime.transfer_stats()): replicas, queued/ongoing
    requests, batch occupancy + step totals from the replica batchers,
    autoscale scale-up/scale-down counters, and — when the proxy tier
    is running — per-proxy routed counts."""
    controller = _get_controller()
    out = ray.get(controller.serving_stats.remote(name))
    # Prefix-affinity routing counters live ROUTER-side (each handle
    # owns its table), so the rollup sums every router this driver can
    # see: its own direct handles plus the proxy tier's.
    r_hits = r_misses = 0
    for h in list(_state["handles"].values()):
        if isinstance(h, DeploymentHandle):
            rs = h.router_stats()
            r_hits += rs["router_prefix_hits"]
            r_misses += rs["router_prefix_misses"]
    proxies = _state.get("request_proxies")
    if proxies and name is None:
        # Parallel with ONE shared deadline (same pattern as the
        # controller's replica polls): N unreachable proxies cost one
        # 5s wait, not N serialized timeouts.
        refs = [p.proxy_stats.remote() for p in proxies]
        done = set(ray.wait(refs, num_returns=len(refs), timeout=5)[0])
        routed = []
        for ref in refs:
            try:
                ps = ray.get(ref, timeout=1) if ref in done else None
            except Exception:
                ps = None
            routed.append(ps["routed"] if ps else None)
            if ps:
                r_hits += ps.get("router_prefix_hits", 0)
                r_misses += ps.get("router_prefix_misses", 0)
        out["_proxies"] = {"count": len(proxies), "routed": routed}
    if name is None:
        out["_router"] = {"prefix_hits": r_hits,
                          "prefix_misses": r_misses}
    return out


def start_http_proxy(host: str = "127.0.0.1", port: int = 8000):
    """HTTP ingress (reference: HTTPProxyActor, _private/http_proxy.py:415).
    Runs an aiohttp server on a driver thread; routes by path prefix."""
    import asyncio

    from aiohttp import web

    async def handle(request: web.Request):
        path = "/" + request.path.strip("/").split("/")[0]
        h = _state["routes"].get(path)
        if h is None:
            return web.json_response({"error": "no such route"}, status=404)
        try:
            body = await request.json() if request.can_read_body else {}
        except Exception:
            body = {}
        loop = asyncio.get_event_loop()
        ref = h.remote(body)
        result = await loop.run_in_executor(None, lambda: ray.get(ref))
        return web.json_response({"result": result})

    app = web.Application()
    app.router.add_route("*", "/{tail:.*}", handle)
    runner = web.AppRunner(app)
    ready = threading.Event()
    state: Dict[str, Any] = {}

    def serve_thread():
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, host, port)
        loop.run_until_complete(site.start())
        state["loop"] = loop
        ready.set()
        loop.run_forever()

    t = threading.Thread(target=serve_thread, daemon=True,
                         name="serve-http-proxy")
    t.start()
    ready.wait(10)
    _state["proxy"] = (t, runner, state)
    return f"http://{host}:{port}"


@ray.remote
class HTTPProxyActor:
    """Per-node HTTP ingress (reference: one HTTPProxyActor per node,
    _private/http_proxy.py:415 + proxy_state_manager).  Routes come from
    the controller's table; replica routing rides this proxy's own
    DeploymentHandles (push-updated, least-loaded) — requests never
    touch the driver."""

    _ROUTE_TTL_S = 2.0

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        import asyncio

        from aiohttp import web

        self._controller = ray.get_actor(CONTROLLER_NAME)
        self._handles: Dict[str, DeploymentHandle] = {}
        self._routes: Dict[str, str] = {}
        self._routes_ts = 0.0
        self._routes_lock = threading.Lock()

        def call_sync(path: str, body):
            """Route lookup + handle construction + replica call: every
            step may RPC the controller, so the WHOLE chain runs in the
            executor — any blocking call on the event loop would
            serialize this proxy's request stream."""
            dep = self._route_for(path)
            if dep is None:
                return None  # distinct from ("ok", None): a None RESULT
            h = self._handles.get(dep)
            if h is None:
                h = self._handles[dep] = DeploymentHandle(
                    dep, self._controller)
            return ("ok", ray.get(h.remote(body)))

        async def handle(request: web.Request):
            path = "/" + request.path.strip("/").split("/")[0]
            try:
                body = await request.json() if request.can_read_body \
                    else {}
            except Exception:
                body = {}
            loop = asyncio.get_event_loop()
            out = await loop.run_in_executor(None, call_sync, path, body)
            if out is None:
                return web.json_response({"error": "no such route"},
                                         status=404)
            return web.json_response({"result": out[1]})

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", handle)
        runner = web.AppRunner(app)
        ready = threading.Event()
        state: Dict[str, Any] = {}

        def serve_thread():
            try:
                loop = asyncio.new_event_loop()
                asyncio.set_event_loop(loop)
                loop.run_until_complete(runner.setup())
                site = web.TCPSite(runner, host, port)
                loop.run_until_complete(site.start())
                state["port"] = site._server.sockets[0].getsockname()[1]
            except BaseException as e:  # noqa: BLE001 — surfaced below
                state["error"] = e
                ready.set()
                return
            ready.set()
            loop.run_forever()

        threading.Thread(target=serve_thread, daemon=True,
                         name="serve-proxy").start()
        if not ready.wait(15):
            raise RuntimeError("proxy HTTP server failed to start (15s)")
        if "error" in state:
            raise RuntimeError(
                f"proxy HTTP server failed to start on "
                f"{host}:{port}") from state["error"]
        self._url = f"http://{host}:{state['port']}"

    def _route_for(self, path: str) -> Optional[str]:
        now = time.monotonic()
        with self._routes_lock:
            stale = now - self._routes_ts > self._ROUTE_TTL_S
            dep = self._routes.get(path)
        if stale:
            # Refresh on TTL only: unknown paths stay negative-cached
            # until then, so a 404 flood cannot serialize requests on
            # controller RPCs.
            routes = ray.get(self._controller.get_routes.remote())
            with self._routes_lock:
                self._routes = routes
                self._routes_ts = now
                dep = routes.get(path)
        return dep

    def url(self) -> str:
        return self._url

    def node_id(self) -> str:
        import ray_tpu

        return ray_tpu.get_runtime_context().node_id


def start(proxy_location: str = "HeadOnly", http_options: Optional[
        Dict[str, Any]] = None, num_proxies: int = 0) -> List[str]:
    """Start Serve ingress (reference: serve.start(proxy_location=...) —
    ProxyLocation.EveryNode runs one proxy per node).  Returns the proxy
    URLs.

    ``num_proxies=N`` additionally spawns N :class:`RequestProxy`
    actors — the non-HTTP data-plane tier: handles created AFTER this
    (serve.run / get_deployment_handle) route requests through them,
    keeping steady-state request traffic off the head (proxy→replica
    calls ride the DirectCaller actor channels).
    ``proxy_location="Disabled"`` skips HTTP ingress entirely (request
    proxies only)."""
    http_options = http_options or {}
    host = http_options.get("host", "127.0.0.1")
    port = int(http_options.get("port", 0))
    _get_controller()
    if num_proxies > 0:
        # A second start() replaces the tier: the OLD proxies are
        # killed (their handles' pollers would otherwise poll the
        # controller forever) and the tier generation bumps so cached
        # ProxiedDeploymentHandles re-resolve onto the new actors.
        old = _state.get("request_proxies") or []
        proxies = [RequestProxy.options(
            num_cpus=0, max_concurrency=32).remote()
            for _ in range(num_proxies)]
        ray.get(_bulk_submit([(p.ping, (), None) for p in proxies]))
        _state["request_proxies"] = proxies
        _state["proxy_tier_gen"] = _state.get("proxy_tier_gen", 0) + 1
        for p in old:
            try:
                ray.kill(p)
            except Exception:
                pass
        # Re-resolve every cached proxied handle onto the new tier —
        # the HTTP proxy thread reads _state["routes"] directly and
        # would otherwise dispatch onto the killed actors.  (Handles
        # the USER kept from a pre-replacement serve.run go stale;
        # re-fetch via get_deployment_handle after replacing the tier.)
        fresh: Dict[str, ProxiedDeploymentHandle] = {}
        for table in (_state["handles"], _state["routes"]):
            for key, h in list(table.items()):
                if isinstance(h, ProxiedDeploymentHandle):
                    nh = fresh.get(h._name)
                    if nh is None:
                        nh = fresh[h._name] = ProxiedDeploymentHandle(
                            h._name, proxies)
                    table[key] = nh
        # Existing direct handles keep working; fresh ones route through
        # the tier (get_deployment_handle re-resolves cached entries).
    if proxy_location == "Disabled":
        return []
    if proxy_location != "EveryNode":
        return [start_http_proxy(host, port or 8000)]
    proxies = []
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    for n in ray.nodes():
        if not n.get("alive", True):
            continue
        p = HTTPProxyActor.options(
            num_cpus=0,
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                n["node_id"], soft=False)).remote(host, port)
        proxies.append(p)
    urls = ray.get(_bulk_submit([(p.url, (), None) for p in proxies]))
    _state["node_proxies"] = proxies
    return urls


def shutdown():
    for p in _state.pop("node_proxies", []) or []:
        try:
            ray.kill(p)
        except Exception:
            pass
    for p in _state.pop("request_proxies", []) or []:
        try:
            ray.kill(p)
        except Exception:
            pass
    if _state["controller"] is not None:
        try:
            for name in list(
                    ray.get(_state["controller"].list_deployments.remote())):
                ray.get(_state["controller"].delete_deployment.remote(name))
            ray.kill(_state["controller"])
        except Exception:
            pass
    proxy = _state.get("proxy")
    if proxy:
        try:
            proxy[2]["loop"].call_soon_threadsafe(proxy[2]["loop"].stop)
        except Exception:
            pass
    for h in _state["handles"].values():
        if isinstance(h, DeploymentHandle):
            h.close()
    _state.update({"controller": None, "proxy": None, "handles": {},
                   "routes": {}})
