"""Serve control + data plane.

Reference call path (SURVEY.md §3.5): serve.run -> controller actor ->
DeploymentState reconciliation -> replica actors; request path: proxy/handle
-> router -> replica.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import ray_tpu as ray
from ray_tpu.remote_function import _bulk_submit

CONTROLLER_NAME = "SERVE_CONTROLLER"


class ReplicaWrapper:
    """Runs the user callable inside a replica actor process."""

    def __init__(self, cls_or_fn, init_args, init_kwargs):
        if isinstance(cls_or_fn, type):
            self._callable = cls_or_fn(*init_args, **init_kwargs)
        else:
            self._callable = cls_or_fn

    def handle_request(self, args, kwargs):
        fn = self._callable
        if not callable(fn):
            fn = fn.__call__
        return fn(*args, **kwargs)

    def call_method(self, method, args, kwargs):
        return getattr(self._callable, method)(*args, **kwargs)

    def health_check(self):
        if hasattr(self._callable, "check_health"):
            self._callable.check_health()
        return True


@ray.remote
class ServeController:
    """Reference: serve/controller.py:69 + _private/deployment_state.py
    (DeploymentStateManager.update, :1855) — a BACKGROUND reconciliation
    loop continuously drives actual replica sets toward target state:
    dead replicas are replaced with no deploy call, autoscaling targets
    are recomputed from handle-reported queue depth
    (_private/autoscaling_policy.py), and version changes roll replicas
    one per tick (rolling update)."""

    RECONCILE_PERIOD_S = 1.0
    METRIC_LOOK_BACK_S = 3.0

    def __init__(self):
        self._deployments: Dict[str, Dict[str, Any]] = {}
        # name -> list of {"actor": handle, "version": int}
        self._replicas: Dict[str, List[Dict[str, Any]]] = {}
        # route prefix -> deployment name: controller-resident so EVERY
        # node's proxy serves the same routing table (reference: the
        # proxy's route table long-polled from the controller,
        # _private/http_proxy.py + long_poll.py ROUTE_TABLE key).
        self._routes: Dict[str, str] = {}
        # autoscaling inputs: (name, handle_id) -> recent (ongoing, ts)
        # samples.  A short look-back window, not just the last sample:
        # instantaneous queue depth oscillates with sampling phase (scale
        # up -> queue drains faster -> next sample reads low -> scale
        # back down), so decisions smooth over METRIC_LOOK_BACK_S
        # (reference: look_back_period_s in autoscaling_policy.py).
        self._handle_metrics: Dict[tuple, deque] = {}
        self._last_scale_up: Dict[str, float] = {}
        # Retired replicas draining before the actual kill: handles stop
        # routing to them immediately (they leave get_replicas), but the
        # process lives past the handle-refresh TTL so in-flight requests
        # finish (reference: graceful_shutdown_wait_loop_s drain).
        self._draining: List[tuple] = []  # (actor, kill_at_monotonic)
        self._lock = threading.RLock()
        # Push-based handle updates (reference: _private/long_poll.py:185
        # LongPollHost): every replica-set mutation bumps the version and
        # wakes blocked wait_replicas calls; handles hold one such call
        # open at all times, so scaling/death/drain propagate in one
        # notify instead of a TTL window.
        self._replica_version: Dict[str, int] = {}
        self._version_cv = threading.Condition(self._lock)
        # Serializes whole reconcile ticks: the background loop thread and
        # an actor-method reconcile (deploy/scale) must not both spawn.
        self._reconcile_lock = threading.Lock()
        self._stopped = False
        threading.Thread(target=self._loop, daemon=True,
                         name="serve-reconcile").start()

    def _loop(self):
        while not self._stopped:
            time.sleep(self.RECONCILE_PERIOD_S)
            try:
                self.reconcile()
            except Exception:
                pass

    def deploy(self, name: str, payload: Dict[str, Any]):
        """payload: cls_or_fn, init_args/kwargs, num_replicas, resources,
        optional autoscaling_config.  A changed payload bumps the version;
        reconcile then rolls replicas over to it."""
        def _same(a, b):
            # Compare by pickled bytes: cls_or_fn crosses the wire by
            # value (cloudpickle), so two deploys of identical code
            # deserialize to distinct class objects that == treats as
            # different.  Byte equality is a sound idempotence check; a
            # false negative merely costs a (safe) rolling restart.
            from ray_tpu._private import serialization as _ser

            keys = ("cls_or_fn", "init_args", "init_kwargs",
                    "num_replicas", "num_cpus", "num_tpus",
                    "autoscaling_config")
            try:
                return all(
                    _ser.dumps_inline(a.get(k)) == _ser.dumps_inline(
                        b.get(k)) for k in keys)
            except Exception:
                return False

        with self._lock:
            prev = self._deployments.get(name)
            if prev is not None and _same(prev, payload):
                return True  # idempotent redeploy: no rolling restart
            version = (prev["version"] + 1) if prev is not None else 1
            payload["version"] = version
            self._deployments[name] = payload
        # Reconcile outside _lock: the tick takes _reconcile_lock then
        # _lock — holding _lock here would invert the order vs the
        # background loop and deadlock.
        self.reconcile()
        return True

    def delete_deployment(self, name: str):
        with self._lock:
            self._deployments.pop(name, None)
            reps = self._replicas.pop(name, [])
            # Routes to a deleted deployment 404 (proxies refresh the
            # table within their TTL) instead of erroring forever.
            for prefix in [p for p, n in self._routes.items()
                           if n == name]:
                self._routes.pop(prefix, None)
            self._bump_version_locked(name)
        for r in reps:
            try:
                ray.kill(r["actor"])
            except Exception:
                pass
        return True

    def _bump_version_locked(self, name: str):
        self._replica_version[name] = \
            self._replica_version.get(name, 0) + 1
        self._version_cv.notify_all()

    def record_handle_metric(self, name: str, handle_id: str, ongoing: int):
        """Handles report their in-flight request count — the autoscaling
        signal (reference: handle-side metrics pushed to the controller,
        _private/router.py + autoscaling_policy.py)."""
        now = time.monotonic()
        with self._lock:
            q = self._handle_metrics.get((name, handle_id))
            if q is None:
                q = self._handle_metrics[(name, handle_id)] = \
                    deque(maxlen=32)
            q.append((ongoing, now))
        return True

    def _spawn(self, d: Dict[str, Any], version: int):
        # Threaded replicas: concurrent requests are what @serve.batch
        # coalesces (reference: replicas default to many concurrent
        # queries, max_concurrent_queries).
        opts = {"num_cpus": d.get("num_cpus", 1),
                "max_concurrency": d.get("max_concurrency", 8)}
        if d.get("num_tpus"):
            opts["num_tpus"] = d["num_tpus"]
        remote_cls = ray.remote(ReplicaWrapper)
        actor = remote_cls.options(**opts).remote(
            d["cls_or_fn"], d.get("init_args", ()),
            d.get("init_kwargs", {}))
        return {"actor": actor, "version": version}

    def _autoscale_target(self, name: str, d: Dict[str, Any]) -> int:
        cfg = d.get("autoscaling_config")
        if not cfg:
            return d.get("num_replicas", 1)
        now = time.monotonic()
        with self._lock:
            # Per handle: the PEAK ongoing inside the look-back window —
            # robust to sampling phase while load is sustained; an idle
            # handle's samples age out and read 0 (downscale_delay then
            # gates the shrink).
            ongoing = 0
            for (n, _h), samples in self._handle_metrics.items():
                if n != name:
                    continue
                fresh = [v for v, ts in samples
                         if now - ts < self.METRIC_LOOK_BACK_S]
                if fresh:
                    ongoing += max(fresh)
        target_per = max(cfg.get("target_ongoing_requests", 1), 1e-9)
        import math

        desired = math.ceil(ongoing / target_per)
        desired = max(cfg.get("min_replicas", 1),
                      min(cfg.get("max_replicas", 1), desired))
        cur = len(self._replicas.get(name, []))
        if desired > cur:
            self._last_scale_up[name] = now
            return desired
        if desired < cur:
            # Downscale only after a quiet period (reference:
            # downscale_delay_s in autoscaling_policy.py).
            delay = cfg.get("downscale_delay_s", 5.0)
            if now - self._last_scale_up.get(name, 0.0) < delay:
                return cur
        return desired

    def reconcile(self):
        """One control-loop tick: health-check, replace dead, scale to
        target (static or autoscaled), roll one outdated replica."""
        with self._reconcile_lock:
            return self._reconcile_once()

    DRAIN_S = 3.0

    def _retire(self, rep):
        with self._lock:
            self._draining.append(
                (rep["actor"], time.monotonic() + self.DRAIN_S))

    def _reap_draining(self):
        now = time.monotonic()
        with self._lock:
            due = [a for a, t in self._draining if t <= now]
            self._draining = [(a, t) for a, t in self._draining if t > now]
        for a in due:
            try:
                ray.kill(a)
            except Exception:
                pass

    def _reconcile_once(self):
        self._reap_draining()
        with self._lock:
            names = list(self._deployments)
        counts = {}
        for name in names:
            with self._lock:
                d = self._deployments.get(name)
                if d is None:
                    continue
                reps = list(self._replicas.get(name, []))
                version = d["version"]
            alive = []
            for r in reps:
                try:
                    ray.get(r["actor"].health_check.remote(), timeout=5)
                    alive.append(r)
                except Exception:
                    pass  # dead or unhealthy: dropped, replaced below
            target = self._autoscale_target(name, d)
            while len(alive) < target:
                alive.append(self._spawn(d, version))
            while len(alive) > target:
                self._retire(alive.pop())
            # Rolling update: one outdated replica per tick — spawn the
            # replacement first, then retire (drain) the old one, so
            # capacity never dips and in-flight requests finish
            # (reference: rolling updates in deployment_state).
            outdated = [r for r in alive if r["version"] != version]
            if outdated:
                alive.append(self._spawn(d, version))
                old = outdated[0]
                alive.remove(old)
                self._retire(old)
            with self._lock:
                if name in self._deployments:
                    prev_ids = [id(r["actor"])
                                for r in self._replicas.get(name, [])]
                    self._replicas[name] = alive
                    if prev_ids != [id(r["actor"]) for r in alive]:
                        self._bump_version_locked(name)
                    counts[name] = len(alive)
                    continue
            # Deleted mid-tick: nothing tracks these replicas anymore.
            for r in alive:
                try:
                    ray.kill(r["actor"])
                except Exception:
                    pass
        return counts

    def get_replicas(self, name: str):
        with self._lock:
            return [r["actor"] for r in self._replicas.get(name, [])]

    def get_replicas_versioned(self, name: str):
        with self._lock:
            return (self._replica_version.get(name, 0),
                    [r["actor"] for r in self._replicas.get(name, [])])

    def wait_replicas(self, name: str, seen_version: int,
                      timeout: float = 30.0):
        """Long-poll: block until the replica set changes past
        ``seen_version`` (or timeout), then return the fresh set
        (reference: LongPollHost.listen_for_change,
        _private/long_poll.py:185)."""
        deadline = time.monotonic() + timeout
        with self._version_cv:
            while self._replica_version.get(name, 0) <= seen_version:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._version_cv.wait(left)
            return (self._replica_version.get(name, 0),
                    [r["actor"] for r in self._replicas.get(name, [])])

    def num_replicas(self, name: str) -> int:
        with self._lock:
            return len(self._replicas.get(name, []))

    def list_deployments(self):
        with self._lock:
            return {n: {"num_replicas": d.get("num_replicas", 1),
                        "version": d.get("version", 1),
                        "autoscaling": bool(d.get("autoscaling_config"))}
                    for n, d in self._deployments.items()}

    def set_route(self, prefix: str, name: str):
        with self._lock:
            self._routes[prefix] = name
        return True

    def get_routes(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._routes)

    def scale(self, name: str, num_replicas: int):
        with self._lock:
            self._deployments[name]["num_replicas"] = num_replicas
        self.reconcile()
        return True

    def stop(self):
        self._stopped = True
        return True


class DeploymentHandle:
    """Router over replicas (reference: _private/router.py:262
    ReplicaSet / handle API).

    Replica-set changes arrive by PUSH: a background long-poll thread
    keeps one blocking ``wait_replicas`` call open at the controller
    (reference: LongPollClient, _private/long_poll.py:68), so a
    downscaled/drained replica stops receiving traffic the moment the
    controller retires it — no TTL window.  Routing is least-loaded
    power-of-two-choices over the handle's in-flight counts (reference:
    the queue-length-aware replica scheduler in _private/router.py).
    """

    _METRIC_PERIOD = 0.5

    def __init__(self, name: str, controller):
        import os

        self._name = name
        self._controller = controller
        self._replicas: List[Any] = []
        self._version = -1
        self._rr = itertools.count()
        self._lock = threading.Lock()
        # Autoscaling signal: outstanding request refs this handle issued;
        # pruned on each call and reported to the controller (reference:
        # handle-side num_queued/ongoing metrics feeding
        # autoscaling_policy.py).  Entries are (weakref, replica_key) so
        # the same prune also yields per-replica queue depths for
        # least-loaded routing.
        self._handle_id = os.urandom(4).hex()
        self._outstanding: List[tuple] = []
        self._inflight: Dict[int, int] = {}  # replica key -> est. depth
        self._last_report = 0.0
        self._refresh()
        self._poller = threading.Thread(
            target=self._long_poll_loop, daemon=True,
            name=f"serve-handle-{name}")
        self._poller.start()

    def _refresh(self):
        ver, reps = ray.get(
            self._controller.get_replicas_versioned.remote(self._name))
        with self._lock:
            self._version = ver
            self._replicas = reps

    def _long_poll_loop(self):
        while True:
            try:
                ver, reps = ray.get(
                    self._controller.wait_replicas.remote(
                        self._name, self._version, 30.0),
                    timeout=40.0)
            except Exception:
                time.sleep(1.0)
                continue
            with self._lock:
                if ver > self._version:
                    self._version = ver
                    self._replicas = reps

    def _pick(self):
        import random

        with self._lock:
            if not self._replicas:
                pass  # fall through to the blocking refresh below
            else:
                reps = self._replicas
                if len(reps) == 1:
                    return reps[0]
                # Power-of-two-choices on estimated queue depth; round-
                # robin supplies the randomness floor.
                i = next(self._rr) % len(reps)
                j = random.randrange(len(reps))
                a, b = reps[i], reps[j]
                if self._inflight.get(id(b), 0) < \
                        self._inflight.get(id(a), 0):
                    return b
                return a
        self._refresh()
        with self._lock:
            if not self._replicas:
                raise RuntimeError(
                    f"deployment {self._name} has no replicas")
            return self._replicas[next(self._rr) % len(self._replicas)]

    def _track(self, ref, replica):
        import weakref

        rkey = id(replica)
        now = time.monotonic()
        with self._lock:
            # Weak refs: the handle must never pin result objects — an
            # idle handle after a burst would otherwise hold the last
            # batch's outputs alive in the object store forever.
            self._outstanding.append((weakref.ref(ref), rkey))
            self._inflight[rkey] = self._inflight.get(rkey, 0) + 1
            if now - self._last_report < self._METRIC_PERIOD:
                return ref
            self._last_report = now
            live = [(w(), k) for w, k in self._outstanding]
            live = [(r, k) for r, k in live if r is not None]
            if live:
                import ray_tpu as _ray

                done, pending = _ray.wait(
                    [r for r, _ in live], num_returns=len(live), timeout=0)
                pend_set = {r.id() for r in pending}
                self._outstanding = [
                    (w, k) for w, k in self._outstanding
                    if (r := w()) is not None and r.id() in pend_set]
                ongoing = len(self._outstanding)
            else:
                self._outstanding = []
                ongoing = 0
            counts: Dict[int, int] = {}
            for _w, k in self._outstanding:
                counts[k] = counts.get(k, 0) + 1
            self._inflight = counts
        # Fire-and-forget: the metric must never block the data path.
        self._controller.record_handle_metric.remote(
            self._name, self._handle_id, ongoing)
        return ref

    def remote(self, *args, **kwargs):
        replica = self._pick()
        return self._track(replica.handle_request.remote(args, kwargs),
                           replica)

    def method(self, method_name: str):
        handle = self

        class _M:
            def remote(self, *args, **kwargs):
                replica = handle._pick()
                return handle._track(replica.call_method.remote(
                    method_name, args, kwargs), replica)

        return _M()


class Deployment:
    """Result of @serve.deployment — bind/deploy surface (reference:
    serve/deployment.py)."""

    def __init__(self, cls_or_fn, name: str, num_replicas: int = 1,
                 num_cpus: float = 1, num_tpus: int = 0,
                 route_prefix: Optional[str] = None,
                 autoscaling_config: Optional[Dict[str, Any]] = None):
        self._cls_or_fn = cls_or_fn
        self.name = name
        self.num_replicas = num_replicas
        self.num_cpus = num_cpus
        self.num_tpus = num_tpus
        self.route_prefix = route_prefix or f"/{name}"
        # {min_replicas, max_replicas, target_ongoing_requests,
        #  downscale_delay_s} (reference: serve AutoscalingConfig)
        self.autoscaling_config = autoscaling_config
        self._init_args = ()
        self._init_kwargs = {}

    def options(self, **kw) -> "Deployment":
        d = Deployment(self._cls_or_fn, kw.get("name", self.name),
                       kw.get("num_replicas", self.num_replicas),
                       kw.get("num_cpus", self.num_cpus),
                       kw.get("num_tpus", self.num_tpus),
                       kw.get("route_prefix", self.route_prefix),
                       kw.get("autoscaling_config",
                              self.autoscaling_config))
        d._init_args = self._init_args
        d._init_kwargs = self._init_kwargs
        return d

    def bind(self, *args, **kwargs) -> "Deployment":
        d = self.options()
        d._init_args = args
        d._init_kwargs = kwargs
        return d


def deployment(cls_or_fn=None, *, name: Optional[str] = None,
               num_replicas: int = 1, num_cpus: float = 1,
               num_tpus: int = 0, route_prefix: Optional[str] = None,
               autoscaling_config: Optional[Dict[str, Any]] = None):
    """@serve.deployment (reference: serve/api.py deployment)."""

    def wrap(target):
        return Deployment(target, name or target.__name__, num_replicas,
                          num_cpus, num_tpus, route_prefix,
                          autoscaling_config)

    if cls_or_fn is not None:
        return wrap(cls_or_fn)
    return wrap


_state: Dict[str, Any] = {"controller": None, "proxy": None,
                          "handles": {}, "routes": {}}


def _get_controller():
    if _state["controller"] is None:
        _state["controller"] = ServeController.options(
            name=CONTROLLER_NAME, max_concurrency=64).remote()
    return _state["controller"]


def run(target: Deployment, *, name: Optional[str] = None
        ) -> DeploymentHandle:
    """Deploy + return a handle (reference: serve.run, api.py:458)."""
    controller = _get_controller()
    dep_name = name or target.name
    ray.get(controller.deploy.remote(dep_name, {
        "cls_or_fn": target._cls_or_fn,
        "init_args": target._init_args,
        "init_kwargs": target._init_kwargs,
        "num_replicas": target.num_replicas,
        "num_cpus": target.num_cpus,
        "num_tpus": target.num_tpus,
        "autoscaling_config": target.autoscaling_config,
    }))
    # Route registered at the CONTROLLER so every node's proxy serves it
    # (the driver-thread proxy keeps its local copy too).
    ray.get(controller.set_route.remote(target.route_prefix, dep_name))
    handle = DeploymentHandle(dep_name, controller)
    _state["handles"][dep_name] = handle
    _state["routes"][target.route_prefix] = handle
    return handle


def get_deployment_handle(name: str) -> DeploymentHandle:
    h = _state["handles"].get(name)
    if h is None:
        h = DeploymentHandle(name, _get_controller())
        _state["handles"][name] = h
    return h


def start_http_proxy(host: str = "127.0.0.1", port: int = 8000):
    """HTTP ingress (reference: HTTPProxyActor, _private/http_proxy.py:415).
    Runs an aiohttp server on a driver thread; routes by path prefix."""
    import asyncio

    from aiohttp import web

    async def handle(request: web.Request):
        path = "/" + request.path.strip("/").split("/")[0]
        h = _state["routes"].get(path)
        if h is None:
            return web.json_response({"error": "no such route"}, status=404)
        try:
            body = await request.json() if request.can_read_body else {}
        except Exception:
            body = {}
        loop = asyncio.get_event_loop()
        ref = h.remote(body)
        result = await loop.run_in_executor(None, lambda: ray.get(ref))
        return web.json_response({"result": result})

    app = web.Application()
    app.router.add_route("*", "/{tail:.*}", handle)
    runner = web.AppRunner(app)
    ready = threading.Event()
    state: Dict[str, Any] = {}

    def serve_thread():
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, host, port)
        loop.run_until_complete(site.start())
        state["loop"] = loop
        ready.set()
        loop.run_forever()

    t = threading.Thread(target=serve_thread, daemon=True,
                         name="serve-http-proxy")
    t.start()
    ready.wait(10)
    _state["proxy"] = (t, runner, state)
    return f"http://{host}:{port}"


@ray.remote
class HTTPProxyActor:
    """Per-node HTTP ingress (reference: one HTTPProxyActor per node,
    _private/http_proxy.py:415 + proxy_state_manager).  Routes come from
    the controller's table; replica routing rides this proxy's own
    DeploymentHandles (push-updated, least-loaded) — requests never
    touch the driver."""

    _ROUTE_TTL_S = 2.0

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        import asyncio

        from aiohttp import web

        self._controller = ray.get_actor(CONTROLLER_NAME)
        self._handles: Dict[str, DeploymentHandle] = {}
        self._routes: Dict[str, str] = {}
        self._routes_ts = 0.0
        self._routes_lock = threading.Lock()

        def call_sync(path: str, body):
            """Route lookup + handle construction + replica call: every
            step may RPC the controller, so the WHOLE chain runs in the
            executor — any blocking call on the event loop would
            serialize this proxy's request stream."""
            dep = self._route_for(path)
            if dep is None:
                return None  # distinct from ("ok", None): a None RESULT
            h = self._handles.get(dep)
            if h is None:
                h = self._handles[dep] = DeploymentHandle(
                    dep, self._controller)
            return ("ok", ray.get(h.remote(body)))

        async def handle(request: web.Request):
            path = "/" + request.path.strip("/").split("/")[0]
            try:
                body = await request.json() if request.can_read_body \
                    else {}
            except Exception:
                body = {}
            loop = asyncio.get_event_loop()
            out = await loop.run_in_executor(None, call_sync, path, body)
            if out is None:
                return web.json_response({"error": "no such route"},
                                         status=404)
            return web.json_response({"result": out[1]})

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", handle)
        runner = web.AppRunner(app)
        ready = threading.Event()
        state: Dict[str, Any] = {}

        def serve_thread():
            try:
                loop = asyncio.new_event_loop()
                asyncio.set_event_loop(loop)
                loop.run_until_complete(runner.setup())
                site = web.TCPSite(runner, host, port)
                loop.run_until_complete(site.start())
                state["port"] = site._server.sockets[0].getsockname()[1]
            except BaseException as e:  # noqa: BLE001 — surfaced below
                state["error"] = e
                ready.set()
                return
            ready.set()
            loop.run_forever()

        threading.Thread(target=serve_thread, daemon=True,
                         name="serve-proxy").start()
        if not ready.wait(15):
            raise RuntimeError("proxy HTTP server failed to start (15s)")
        if "error" in state:
            raise RuntimeError(
                f"proxy HTTP server failed to start on "
                f"{host}:{port}") from state["error"]
        self._url = f"http://{host}:{state['port']}"

    def _route_for(self, path: str) -> Optional[str]:
        now = time.monotonic()
        with self._routes_lock:
            stale = now - self._routes_ts > self._ROUTE_TTL_S
            dep = self._routes.get(path)
        if stale:
            # Refresh on TTL only: unknown paths stay negative-cached
            # until then, so a 404 flood cannot serialize requests on
            # controller RPCs.
            routes = ray.get(self._controller.get_routes.remote())
            with self._routes_lock:
                self._routes = routes
                self._routes_ts = now
                dep = routes.get(path)
        return dep

    def url(self) -> str:
        return self._url

    def node_id(self) -> str:
        import ray_tpu

        return ray_tpu.get_runtime_context().node_id


def start(proxy_location: str = "HeadOnly", http_options: Optional[
        Dict[str, Any]] = None) -> List[str]:
    """Start Serve ingress (reference: serve.start(proxy_location=...) —
    ProxyLocation.EveryNode runs one proxy per node).  Returns the proxy
    URLs."""
    http_options = http_options or {}
    host = http_options.get("host", "127.0.0.1")
    port = int(http_options.get("port", 0))
    _get_controller()
    if proxy_location != "EveryNode":
        return [start_http_proxy(host, port or 8000)]
    proxies = []
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    for n in ray.nodes():
        if not n.get("alive", True):
            continue
        p = HTTPProxyActor.options(
            num_cpus=0,
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                n["node_id"], soft=False)).remote(host, port)
        proxies.append(p)
    urls = ray.get(_bulk_submit([(p.url, (), None) for p in proxies]))
    _state["node_proxies"] = proxies
    return urls


def shutdown():
    for p in _state.pop("node_proxies", []) or []:
        try:
            ray.kill(p)
        except Exception:
            pass
    if _state["controller"] is not None:
        try:
            for name in list(
                    ray.get(_state["controller"].list_deployments.remote())):
                ray.get(_state["controller"].delete_deployment.remote(name))
            ray.kill(_state["controller"])
        except Exception:
            pass
    proxy = _state.get("proxy")
    if proxy:
        try:
            proxy[2]["loop"].call_soon_threadsafe(proxy[2]["loop"].stop)
        except Exception:
            pass
    _state.update({"controller": None, "proxy": None, "handles": {},
                   "routes": {}})
