"""Paged (block-table) decode attention as a Pallas TPU kernel.

Reference design: vLLM's PagedAttention (SOSP'23) mapped onto the TPU
grid model, next to the contiguous flash kernel in ``attention.py``.
The KV cache is not one contiguous ``(B, S, h, d)`` tensor but a pool
of fixed-size blocks ``(num_blocks, block_size, h, d)``; each sequence
owns a *block table* — the list of physical block ids holding its
context in order.  A decode step computes attention of ONE query token
per sequence against that sequence's gathered context:

- Grid ``(batch, kv_pages)``.  The page dimension is sequential on TPU
  and carries the online-softmax running stats ``(m, l)`` plus the
  output accumulator in VMEM scratch, exactly like the flash kernel's
  kv-block dimension.
- The gather is expressed through the BlockSpec index map: block tables
  and context lengths ride as SCALAR-PREFETCH operands
  (``pltpu.PrefetchScalarGridSpec``), so the index map for the k/v
  blocks reads ``block_tables[b, i]`` — the DMA engine fetches physical
  block ``bt[b, i]`` while the previous page computes.  No materialized
  contiguous copy of the context ever exists.
- Ragged tails: ``context_lens[b]`` masks positions at and past the
  sequence's length inside its last (partial) block with the finite
  ``NEG_INF`` the flash kernel uses; block-table entries past the last
  live page are skipped entirely with ``pl.when`` (their table entries
  may be arbitrary padding).
- ``window=w`` restricts attention to the TRAILING ``w`` positions of
  the context (sliding-window attention).  ``window=1`` degenerates to
  an exact gather of the last position's value row — softmax over a
  single element is exactly 1.0 in floating point, so the output is
  bitwise the stored ``v`` row.  The serving engine's paged decode mode
  (serve/tpu_replica.py) leans on precisely that to keep greedy chains
  bitwise-pinned while the block-table data path does the real work.

Like every op in this package the kernel runs in pallas interpret mode
off-TPU, so the same code path is tested on CPU.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ray_tpu.ops.attention import NEG_INF, _LOG2E, _interpret_default


def paged_attention_reference(q: jax.Array, k_cache: jax.Array,
                              v_cache: jax.Array, block_tables,
                              context_lens, *,
                              sm_scale: Optional[float] = None,
                              window: int = 0) -> jax.Array:
    """Pure-XLA oracle: gather each sequence's context contiguously via
    its block table, then plain softmax attention.  q: ``(B, h, d)``;
    caches ``(num_blocks, block_size, h, d)``; returns ``(B, h, d)``."""
    import numpy as np

    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    qh = np.asarray(q, np.float32)
    kc = np.asarray(k_cache, np.float32)
    vc = np.asarray(v_cache, np.float32)
    bt = np.asarray(block_tables)
    cl = np.asarray(context_lens)
    bs = kc.shape[1]
    out = np.zeros_like(qh)
    for b in range(qh.shape[0]):
        n = int(cl[b])
        pages = bt[b, : -(-n // bs)]
        k = kc[pages].reshape(-1, *kc.shape[2:])[:n]   # (n, h, d)
        v = vc[pages].reshape(-1, *vc.shape[2:])[:n]
        lo = max(0, n - window) if window else 0
        k, v = k[lo:], v[lo:]
        s = np.einsum("hd,khd->hk", qh[b], k) * sm_scale
        s -= s.max(-1, keepdims=True)
        p = np.exp(s)
        p /= p.sum(-1, keepdims=True)
        out[b] = np.einsum("hk,khd->hd", p, v)
    return jnp.asarray(out)


def _paged_kernel(bt_ref, cl_ref, q_ref, k_ref, v_ref, o_ref,
                  m_scr, l_scr, acc_scr, *, block_size, window):
    b, i = pl.program_id(0), pl.program_id(1)
    npages = pl.num_programs(1)
    ctx = cl_ref[b]
    start = jnp.maximum(ctx - window, 0) if window else 0

    @pl.when(i == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    page_lo = i * block_size
    # A page is live iff it overlaps [start, ctx): pages past the
    # context hold arbitrary padding table entries and are skipped.
    live = (page_lo < ctx) & (page_lo + block_size > start)

    @pl.when(live)
    def _compute():
        q = q_ref[0]                                   # (h, d), pre-scaled
        k = k_ref[0]                                   # (bs, h, d)
        v = v_ref[0]
        s = jax.lax.dot_general(                       # (h, bs)
            q, k, (((1,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)
        pos = page_lo + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where((pos >= start) & (pos < ctx), s, NEG_INF)
        m_prev = m_scr[...]                            # (h, 1)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_next = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp2(m_prev - m_next)
        p = jnp.exp2(s - m_next)                       # (h, bs)
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, -1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)        # (h, d)
        m_scr[...] = m_next

    @pl.when(i == npages - 1)
    def _finalize():
        o_ref[0] = (acc_scr[...] / l_scr[...]).astype(o_ref.dtype)


def paged_attention(q: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
                    block_tables: jax.Array, context_lens: jax.Array, *,
                    sm_scale: Optional[float] = None, window: int = 0,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Decode attention over a paged KV cache.

    q: ``(B, h, d)`` — one query token per sequence.
    k_cache/v_cache: ``(num_blocks, block_size, h, d)`` physical pool.
    block_tables: ``(B, max_pages)`` int32 — per-sequence physical block
    ids in context order; entries past ``ceil(context_len/block_size)``
    may be arbitrary valid indices (padding).
    context_lens: ``(B,)`` int32, each >= 1.
    window: attend only to the trailing ``window`` positions (0 = all).
    Returns ``(B, h, d)``.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = _interpret_default()
    B, h, d = q.shape
    bs = k_cache.shape[1]
    max_pages = block_tables.shape[1]
    # Pre-scale into the log2 domain like the flash kernel: the hot loop
    # then uses exp2 directly and the per-tile scale multiply vanishes.
    qs = (q * (sm_scale * _LOG2E)).astype(q.dtype)
    bt = jnp.asarray(block_tables, jnp.int32)
    cl = jnp.asarray(context_lens, jnp.int32)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, max_pages),
        in_specs=[
            pl.BlockSpec((1, h, d), lambda b, i, bt_, cl_: (b, 0, 0)),
            pl.BlockSpec((1, bs, h, d),
                         lambda b, i, bt_, cl_: (bt_[b, i], 0, 0, 0)),
            pl.BlockSpec((1, bs, h, d),
                         lambda b, i, bt_, cl_: (bt_[b, i], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, d), lambda b, i, bt_, cl_: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, d), jnp.float32),
        ],
    )
    kernel = functools.partial(_paged_kernel, block_size=bs, window=window)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, h, d), q.dtype),
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(bt, cl, qs, k_cache, v_cache)
