"""Mixture-of-Experts routing (GShard/Switch-style) for expert parallelism.

Token-choice top-k routing with fixed expert capacity, expressed as dense
dispatch/combine einsums — the idiomatic XLA formulation: static shapes (no
data-dependent gather), and when the expert dimension is sharded over the
'ep' mesh axis the dispatch/combine contractions lower to all-to-alls over
ICI.  The reference has no MoE; its expert-parallel analog would be NCCL
all-to-all via ``ray.util.collective`` (SURVEY.md §2.3) — here the router is
a framework op and the collective is XLA's.

Returns auxiliary load-balancing loss (Switch §2.2 form: E * sum_e f_e * p_e).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ray_tpu.ops.layers import swiglu


class MoEOutput(NamedTuple):
    out: jax.Array        # (tokens, embed)
    aux_loss: jax.Array   # scalar load-balancing loss
    router_probs: jax.Array  # (tokens, experts) — for metrics


def route_topk(router_logits: jax.Array, num_selected: int,
               capacity: int) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Compute (dispatch (T,E,C) f32 0/1, combine (T,E,C) f32, aux_loss).

    Over-capacity tokens are dropped (their combine weights are zero), which
    keeps shapes static — the XLA-native alternative to dynamic routing.
    """
    t, e = router_logits.shape
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    gate_vals, expert_idx = jax.lax.top_k(probs, num_selected)   # (T, k)

    # Position of each (token, choice) in its expert's buffer: running count
    # of earlier assignments to the same expert, counted over the flattened
    # (choice-major) assignment order so k=2 second choices queue after
    # first choices.
    onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.int32)      # (T, k, E)
    flat = onehot.transpose(1, 0, 2).reshape(-1, e)              # (k*T, E)
    pos_flat = jnp.cumsum(flat, axis=0) - flat                   # (k*T, E)
    pos = pos_flat.reshape(num_selected, t, e).transpose(1, 0, 2)  # (T,k,E)
    pos = jnp.sum(pos * onehot, axis=-1)                         # (T, k)
    within = pos < capacity

    disp = jnp.zeros((t, e, capacity), jnp.float32)
    comb = jnp.zeros((t, e, capacity), jnp.float32)
    tok = jnp.arange(t)
    for c in range(num_selected):
        idx = (tok, expert_idx[:, c], jnp.clip(pos[:, c], 0, capacity - 1))
        keep = within[:, c].astype(jnp.float32)
        disp = disp.at[idx].add(keep)
        comb = comb.at[idx].add(keep * gate_vals[:, c])

    # Load-balance loss: fraction of tokens per expert x mean router prob.
    density = jnp.mean(
        jax.nn.one_hot(expert_idx[:, 0], e, dtype=jnp.float32), axis=0)
    aux = e * jnp.sum(density * jnp.mean(probs, axis=0))
    return disp, comb, aux


def moe_ffn(x: jax.Array, router_w: jax.Array, w_gate: jax.Array,
            w_up: jax.Array, w_down: jax.Array, *, num_selected: int = 2,
            capacity_factor: float = 1.25,
            constrain=None) -> MoEOutput:
    """SwiGLU MoE layer.  x: (tokens, embed); router_w: (embed, E);
    w_gate/w_up: (E, embed, mlp); w_down: (E, mlp, embed).

    ``constrain(x, logical_axes)`` optionally applies sharding constraints
    (expert tensors get ('expert', ...), so 'ep' carries the all-to-all).
    """
    t, d = x.shape
    e = router_w.shape[1]
    capacity = max(1, int(capacity_factor * t * num_selected / e))
    logits = x @ router_w.astype(x.dtype)
    disp, comb, aux = route_topk(logits, num_selected, capacity)

    expert_in = jnp.einsum("tec,td->ecd", disp.astype(x.dtype), x)
    if constrain is not None:
        expert_in = constrain(expert_in, ("expert", None, "embed"))
    gate = jnp.einsum("ecd,edm->ecm", expert_in, w_gate.astype(x.dtype))
    up = jnp.einsum("ecd,edm->ecm", expert_in, w_up.astype(x.dtype))
    act = swiglu(gate, up)
    expert_out = jnp.einsum("ecm,emd->ecd", act, w_down.astype(x.dtype))
    if constrain is not None:
        expert_out = constrain(expert_out, ("expert", None, "embed"))
    out = jnp.einsum("tec,ecd->td", comb.astype(x.dtype), expert_out)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return MoEOutput(out, aux, probs)
