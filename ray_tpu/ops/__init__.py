"""ray_tpu.ops — TPU kernels (Pallas) and their XLA reference forms.

The reference framework has no tensor ops of its own (Ray core schedules
CPUs/GPUs and moves bytes; math lives in torch/tf — SURVEY.md §5
"Long-context / sequence parallelism: absent").  In a TPU-native framework
the hot ops are part of the framework: flash attention on the MXU, ring
attention over the ICI 'sp' axis, Ulysses all-to-all attention, MoE routing.
Every op has a pure-XLA reference implementation used for numerics tests and
as the CPU fallback.
"""

from ray_tpu.ops.attention import flash_attention, mha_reference
from ray_tpu.ops.paged_attention import (
    paged_attention, paged_attention_reference)
from ray_tpu.ops.ring_attention import ring_attention
from ray_tpu.ops.ulysses import ulysses_attention
from ray_tpu.ops.layers import rms_norm, rope, apply_rope, swiglu

__all__ = [
    "flash_attention", "mha_reference", "paged_attention",
    "paged_attention_reference", "ring_attention",
    "ulysses_attention", "rms_norm", "rope", "apply_rope", "swiglu",
]
