"""Elementwise/normalization layer primitives (XLA-fused by design).

These stay as plain jnp: XLA fuses them into neighboring matmuls, so a
Pallas version would only add compile surface.  (Pallas is reserved for ops
XLA can't schedule well: attention inner loops, ring collect-compute
overlap — see ops/attention.py, ops/ring_attention.py.)
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-6) -> jax.Array:
    """RMSNorm (Llama-style, no mean subtraction).  Stats in f32."""
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    x = x * jax.lax.rsqrt(var + eps)
    return (x * weight.astype(jnp.float32)).astype(dtype)


def rope(seq_len: int, head_dim: int, theta: float = 10000.0,
         offset=0) -> Tuple[jax.Array, jax.Array]:
    """Rotary position embedding tables (cos, sin): (seq_len, head_dim/2).
    ``offset`` may be traced (e.g. an 'sp' rank offset inside shard_map)."""
    freqs = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32)
                             / head_dim))
    t = jnp.arange(seq_len, dtype=jnp.float32) + offset
    angles = jnp.outer(t, freqs)
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x: (b, s, h, d); cos/sin: (s, d/2).  Rotate-half formulation."""
    d2 = x.shape[-1] // 2
    x1, x2 = x[..., :d2], x[..., d2:]
    cos = cos[None, :, None, :]
    sin = sin[None, :, None, :]
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def swiglu(gate: jax.Array, up: jax.Array) -> jax.Array:
    """SwiGLU activation: silu(gate) * up."""
    return jax.nn.silu(gate) * up


def repeat_kv_heads(q: jax.Array, k: jax.Array, v: jax.Array):
    """Broadcast GQA kv heads up to q's head count (validated)."""
    h, h_kv = q.shape[2], k.shape[2]
    if h == h_kv:
        return k, v
    if h % h_kv:
        raise ValueError(f"q heads {h} not a multiple of kv heads {h_kv}")
    rep = h // h_kv
    return jnp.repeat(k, rep, axis=2), jnp.repeat(v, rep, axis=2)
