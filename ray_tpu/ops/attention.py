"""Flash attention as a Pallas TPU kernel (fwd + bwd), with an XLA reference.

Design (standard memory-efficient attention, mapped to the TPU grid model):

- Layout: kernels run on ``(batch, heads, seq, head_dim)`` so every block's
  minor two dims are ``(block_seq, head_dim)`` — Mosaic requires the minor
  dims of a block to be (8, 128)-tile friendly or equal to the array dims;
  the model-side ``(b, s, h, d)`` tensors are transposed at the call
  boundary (XLA fuses the transpose into neighbouring ops).
- Forward: grid ``(batch, heads, q_blocks, kv_blocks)``.  The last grid
  dimension is sequential on TPU, so softmax running stats ``(m, l)`` and the
  output accumulator live in VMEM scratch that persists across kv iterations;
  the normalized output and the logsumexp are written on the last kv block.
- The logsumexp residual is lane-replicated to ``(b, h, s, LANES)`` — a 1D
  row per q position cannot be expressed as a legal minor block shape, so
  stats ride in full vector registers (the layout jax's own TPU
  flash-attention kernel uses for its ``l``/``m`` outputs).
- Backward: two kernels (the classic split): one accumulates ``dk, dv`` with
  grid ``(b, h, kv_blocks, q_blocks)``, one accumulates ``dq`` with grid
  ``(b, h, q_blocks, kv_blocks)``; both recompute ``p = exp(s - lse)`` from
  the saved per-row logsumexp instead of materializing the S x S matrix.
- Causal blocks that are fully masked are skipped with ``pl.when`` so the
  kernel does ~half the FLOPs at long sequence.
- Accumulation is f32 regardless of input dtype (bf16 inputs hit the MXU).

The reference framework has no counterpart (Ray core has no tensor ops —
SURVEY.md §5); this op is the compute leaf that the SP layer (ring/ulysses)
and the model family build on.  On non-TPU backends the kernels run in
pallas interpret mode, so the same code path is tested on CPU.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30  # finite "minus infinity": keeps exp() NaN-free on masked rows
_LANES = 128     # TPU lane width; stats are lane-replicated
# Softmax runs in the log2 domain: q is pre-scaled by sm_scale*log2(e)
# outside the kernel, so the hot loop uses exp2 directly (the VPU's
# native transcendental; exp(x) lowers to exp2(x*log2e) anyway) and the
# per-element scale multiply disappears from the (bq, bk) tile.
_LOG2E = 1.4426950408889634
_LN2 = 0.6931471805599453

# Tuned on TPU v5e: large blocks amortize grid overhead (the d=64
# contraction underfills the MXU, so throughput comes from big output
# tiles); _fit_block shrinks them for short sequences.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 1024


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _compiler_params(interpret):
    if interpret:
        return None
    # First three grid dims are embarrassingly parallel; the innermost
    # carries the running softmax state and must stay sequential.
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        vmem_limit_bytes=100 * 1024 * 1024)


def mha_reference(q: jax.Array, k: jax.Array, v: jax.Array, *,
                  causal: bool = True, sm_scale: Optional[float] = None,
                  q_offset: int = 0, kv_offset: int = 0) -> jax.Array:
    """Pure-XLA multi-head attention, the numerics oracle for every kernel.

    ``q_offset``/``kv_offset`` are global positions of element 0 of the q/kv
    chunks — used by ring attention where each device holds a seq slice.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if causal:
        qi = q_offset + jnp.arange(q.shape[1])[:, None]
        ki = kv_offset + jnp.arange(k.shape[1])[None, :]
        s = jnp.where(qi >= ki, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


def _causal_mask(s, qi, ki, block_q, block_k):
    rows = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    cols = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(rows >= cols, s, NEG_INF)


# ---------------------------------------------------------------- forward

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, causal, block_q, block_k):
    qi, ki = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Causal: block is live iff its last q row can see its first kv column.
    live = (qi * block_q + block_q - 1 >= ki * block_k) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0]                                      # (bq, d)
        k = k_ref[0, 0]                                      # (bk, d)
        v = v_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        m_prev = m_scr[...]                          # (bq, LANES) replicated
        m_cur = jnp.max(s, axis=-1, keepdims=True)   # (bq, 1)
        m_next = jnp.maximum(m_prev, jnp.broadcast_to(m_cur, m_prev.shape))
        alpha = jnp.exp2(m_prev - m_next)
        p = jnp.exp2(s - m_next[:, :1])
        l_scr[...] = l_scr[...] * alpha + jnp.broadcast_to(
            jnp.sum(p, axis=-1, keepdims=True), m_prev.shape)
        acc_scr[...] = acc_scr[...] * alpha[:, :1] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = m_next

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scr[...]
        o_ref[0, 0] = (acc_scr[...] / l[:, :1]).astype(o_ref.dtype)
        lse_ref[0, 0] = m_scr[...] + jnp.log2(l)   # log2-domain lse


def _fwd_call(qt, kt, vt, causal, block_q, block_k, interpret):
    """qt/kt/vt: (b, h, s, d); qt PRE-SCALED by sm_scale*log2e.  Returns
    (o_t, lse) with o_t (b, h, sq, d) and lse (b, h, sq, LANES)
    lane-replicated f32 in the log2 domain."""
    b, h, sq, d = qt.shape
    sk = kt.shape[2]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(b, h, pl.cdiv(sq, block_q), pl.cdiv(sk, block_k)),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, i, j: (b_, h_, j, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, i, j: (b_, h_, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_q, _LANES),
                         lambda b_, h_, i, j: (b_, h_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(qt.shape, qt.dtype),
            jax.ShapeDtypeStruct((b, h, sq, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(qt, kt, vt)
    return o, lse


# ---------------------------------------------------------------- backward

def _dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                 dk_ref, dv_ref, dk_scr, dv_scr,
                 *, causal, block_q, block_k):
    ki, qi = pl.program_id(2), pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    live = (qi * block_q + block_q - 1 >= ki * block_k) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]                                   # (bq, LANES)
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        p = jnp.exp2(s - lse[:, :1])                          # (bq, bk)
        # Grad matmuls in the INPUT dtype (bf16 on TPU): the MXU runs
        # bf16 natively; the old f32 operands forced multi-pass matmuls.
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # (bk, d)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, :1])).astype(q.dtype)
        dk_scr[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finalize():
        # q arrives pre-scaled by c = sm_scale*log2e; the true gradient
        # is sm_scale * ds^T @ q_unscaled = ln2 * ds^T @ (q*c).
        dk_ref[0, 0] = (dk_scr[...] * _LN2).astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, dq_scr, *, sm_scale, causal, block_q, block_k):
    # sm_scale is applied once at finalize: dL/dq_orig = sm_scale * ds@k.
    qi, ki = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    live = (qi * block_q + block_q - 1 >= ki * block_k) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        p = jnp.exp2(s - lse[:, :1])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, :1])).astype(k.dtype)
        dq_scr[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0, 0] = (dq_scr[...] * sm_scale).astype(dq_ref.dtype)


def _bwd_call(qt, kt, vt, ot, lse, dot, sm_scale, causal, block_q, block_k,
              interpret):
    """All tensors (b, h, s, d); lse (b, h, sq, LANES).  Returns transposed
    grads (dqt, dkt, dvt)."""
    b, h, sq, d = qt.shape
    sk = kt.shape[2]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    delta = jnp.sum(ot.astype(jnp.float32) * dot.astype(jnp.float32),
                    axis=-1, keepdims=True)                  # (b, h, sq, 1)
    delta = jnp.broadcast_to(delta, (b, h, sq, _LANES))

    q_i = pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0))
    q_j = pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, j, 0))
    k_i = pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, i, j: (b_, h_, i, 0))
    k_j = pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, i, j: (b_, h_, j, 0))
    row_i = pl.BlockSpec((1, 1, block_q, _LANES),
                         lambda b_, h_, i, j: (b_, h_, i, 0))
    row_j = pl.BlockSpec((1, 1, block_q, _LANES),
                         lambda b_, h_, i, j: (b_, h_, j, 0))

    dk, dv = pl.pallas_call(
        functools.partial(_dkdv_kernel, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(b, h, pl.cdiv(sk, block_k), pl.cdiv(sq, block_q)),
        in_specs=[q_j, k_i, k_i, q_j, row_j, row_j],
        out_specs=[k_i, k_i],
        out_shape=[jax.ShapeDtypeStruct(kt.shape, kt.dtype),
                   jax.ShapeDtypeStruct(vt.shape, vt.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(b, h, pl.cdiv(sq, block_q), pl.cdiv(sk, block_k)),
        in_specs=[q_i, k_j, k_j, q_i, row_i, row_i],
        out_specs=q_i,
        out_shape=jax.ShapeDtypeStruct(qt.shape, qt.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta)
    return dq, dk, dv


# ----------------------------------------------------------------- public

def _to_bhsd(x):
    return jnp.transpose(x, (0, 2, 1, 3))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    qs = (q * (sm_scale * _LOG2E)).astype(q.dtype)
    o, _ = _fwd_call(_to_bhsd(qs), _to_bhsd(k), _to_bhsd(v), causal,
                     block_q, block_k, interpret)
    return _to_bhsd(o)


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    qs = (q * (sm_scale * _LOG2E)).astype(q.dtype)
    qt, kt, vt = _to_bhsd(qs), _to_bhsd(k), _to_bhsd(v)
    ot, lse = _fwd_call(qt, kt, vt, causal, block_q, block_k, interpret)
    return _to_bhsd(ot), (qt, kt, vt, ot, lse)


def _flash_bwd(sm_scale, causal, block_q, block_k, interpret, res, do):
    qt, kt, vt, ot, lse = res
    dqt, dkt, dvt = _bwd_call(qt, kt, vt, ot, lse, _to_bhsd(do), sm_scale,
                              causal, block_q, block_k, interpret)
    return _to_bhsd(dqt), _to_bhsd(dkt), _to_bhsd(dvt)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True, sm_scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Memory-efficient MHA.  q: (b, sq, h, d); k/v: (b, sk, h, d).

    Supports grouped-query attention: if k/v have fewer heads than q and
    ``h % h_kv == 0``, kv heads are repeated (XLA fuses the broadcast).
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = _interpret_default()
    from ray_tpu.ops.layers import repeat_kv_heads
    k, v = repeat_kv_heads(q, k, v)
    # The kernels have no partial-block masking: blocks must tile the
    # sequence exactly.  Shrink to a fitting power-of-two block; if none
    # >= 8 exists, use the XLA reference (correct, O(S^2) memory).
    block_q = _fit_block(block_q, q.shape[1])
    block_k = _fit_block(block_k, k.shape[1])
    if block_q is None or block_k is None:
        return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale)
    return _flash(q, k, v, sm_scale, causal, block_q, block_k, interpret)


def _fit_block(block: int, seq: int) -> Optional[int]:
    block = min(block, seq)
    while block >= 8:
        if seq % block == 0:
            return block
        block //= 2
    return None
