"""Ring attention: sequence/context parallelism over the 'sp' mesh axis.

Each device holds a contiguous sequence chunk of q/k/v.  K/V chunks rotate
around the ring with ``jax.lax.ppermute`` while every device accumulates
attention of its local q against each visiting chunk using streaming softmax
stats ``(m, l, acc)`` — i.e. flash attention blocked at the *mesh* level, so
max sequence scales linearly with the 'sp' axis size and ICI carries only
K/V chunks (overlappable with compute by XLA's latency-hiding scheduler).

Differentiability comes for free: the loop is ``lax.scan`` and every step is
plain XLA (+``ppermute``, which has a transpose rule), so reverse-mode AD
yields the exact ring backward with no custom VJP to maintain.

Causal masking is exact: device ``i`` at ring step ``t`` holds kv chunk
``(i - t) mod n``; chunks strictly above the diagonal are skipped with
``lax.cond`` (no FLOPs), the diagonal chunk is masked elementwise.

This fills the gap called out in SURVEY.md §5 ("Long-context / sequence
parallelism: absent" in the reference — it delegates to torch.distributed /
Alpa; here it is a framework op).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.ops.attention import NEG_INF
from ray_tpu.ops.layers import repeat_kv_heads
from ray_tpu.parallel.mesh import AXIS_SP


def _chunk_attn(q, k, v, sm_scale, causal, same_chunk):
    """Unnormalized attention of local q against one kv chunk.
    Returns (m, l, acc): rowmax, rowsum(exp), weighted values — all f32."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if causal and same_chunk:
        qi = jnp.arange(q.shape[1])[:, None]
        ki = jnp.arange(k.shape[1])[None, :]
        s = jnp.where(qi >= ki, s, NEG_INF)
    m = jnp.max(s, axis=-1)                               # (b,h,q)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v
                     ).astype(jnp.float32)
    return m, l, acc


def _ring_body(q, sm_scale, causal, axis_name, n, my_idx):
    """Builds the scan over ring steps; returns fn(kv) -> local output."""

    def step(carry, t):
        k, v, m, l, acc = carry
        kv_idx = (my_idx - t) % n

        def live(_):
            same = kv_idx == my_idx
            # ``same`` is traced; split diagonal vs. full-attend branches.
            def diag(_):
                return _chunk_attn(q, k, v, sm_scale, causal, True)

            def full(_):
                return _chunk_attn(q, k, v, sm_scale, False, False)

            return jax.lax.cond(same, diag, full, None) if causal else \
                _chunk_attn(q, k, v, sm_scale, False, False)

        def dead(_):
            bhq = (q.shape[0], q.shape[2], q.shape[1])
            return (jnp.full(bhq, NEG_INF, jnp.float32),
                    jnp.zeros(bhq, jnp.float32),
                    jnp.zeros(q.shape, jnp.float32))

        if causal:
            m_c, l_c, acc_c = jax.lax.cond(kv_idx <= my_idx, live, dead, None)
        else:
            m_c, l_c, acc_c = live(None)

        m_new = jnp.maximum(m, m_c)
        a_prev = jnp.exp(m - m_new)
        a_cur = jnp.exp(m_c - m_new)
        l_new = l * a_prev + l_c * a_cur
        bhq_to_bqh = lambda x: jnp.moveaxis(x, 1, 2)[..., None]  # (b,h,q)->(b,q,h,1)
        acc_new = acc * bhq_to_bqh(a_prev) + acc_c * bhq_to_bqh(a_cur)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        return (k, v, m_new, l_new, acc_new), None

    return step


def _axis_size(axis_name: str) -> int:
    """jax.lax.axis_size, with the 0.4.x fallback (the axis env frame)."""
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    size = jax.core.axis_frame(axis_name)  # 0.4.x: the size itself
    return getattr(size, "size", size)


def _ring_attention_sharded(q, k, v, sm_scale, causal, axis_name):
    n = _axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, sq, h, _ = q.shape
    m0 = jnp.full((b, h, sq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    acc0 = jnp.zeros(q.shape, jnp.float32)
    step = _ring_body(q, sm_scale, causal, axis_name, n, my_idx)
    (k, v, m, l, acc), _ = jax.lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(n))
    l = jnp.moveaxis(l, 1, 2)[..., None]          # (b,q,h,1)
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   causal: bool = True, sm_scale: Optional[float] = None,
                   mesh: Optional[Mesh] = None,
                   axis_name: str = AXIS_SP) -> jax.Array:
    """Sequence-parallel attention.  q/k/v: (b, seq, h, d), seq sharded over
    ``axis_name``.  Call either inside an existing shard_map/pjit context
    (mesh=None) or pass a mesh to get a self-contained shard_map.

    K/V with fewer heads (GQA) are broadcast to q's head count first.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    k, v = repeat_kv_heads(q, k, v)
    if mesh is None:
        return _ring_attention_sharded(q, k, v, sm_scale, causal, axis_name)
    from ray_tpu.parallel.sharding import manual_shard_map
    spec = P(None, axis_name, None, None)
    fn = manual_shard_map(
        lambda q_, k_, v_: _ring_attention_sharded(
            q_, k_, v_, sm_scale, causal, axis_name),
        {axis_name}, in_specs=(spec, spec, spec), out_specs=spec, mesh=mesh)
    return fn(q, k, v)
