"""Ulysses (DeepSpeed-style) sequence parallelism: all-to-all head sharding.

Alternative SP strategy to ring attention: instead of rotating K/V around a
ring, two ``all_to_all``s re-shard the tensors from sequence-sharded to
head-sharded, run *full* (flash) attention per head group, and shard back:

    (b, s/n, h, d)  --all_to_all-->  (b, s, h/n, d)  --attn-->  --back-->

Cost: 2 all-to-alls of activation size vs. ring's (n-1) K/V ppermutes;
Ulysses wins when heads >= axis size and the interconnect does fast
all-to-all (TPU ICI does); ring wins for very long sequence / few heads.
Both are exposed so the Train layer can pick per model shape
(SURVEY.md §5 — absent in the reference, first-class here).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.ops.attention import flash_attention, mha_reference
from ray_tpu.ops.layers import repeat_kv_heads
from ray_tpu.parallel.mesh import AXIS_SP


def _ulysses_sharded(q, k, v, sm_scale, causal, axis_name, use_flash):
    # (b, s_local, h, d) -> (b, s_global, h_local, d)
    def scatter_heads(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def gather_heads(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    q, k, v = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    if use_flash:
        o = flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    else:
        o = mha_reference(q, k, v, causal=causal, sm_scale=sm_scale)
    return gather_heads(o)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                      causal: bool = True, sm_scale: Optional[float] = None,
                      mesh: Optional[Mesh] = None, axis_name: str = AXIS_SP,
                      use_flash: bool = True) -> jax.Array:
    """All-to-all sequence-parallel attention.  q/k/v: (b, seq, h, d) with
    seq sharded over ``axis_name``; h must be divisible by the axis size."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    k, v = repeat_kv_heads(q, k, v)
    if mesh is None:
        return _ulysses_sharded(q, k, v, sm_scale, causal, axis_name,
                                use_flash)
    from ray_tpu.parallel.sharding import manual_shard_map
    spec = P(None, axis_name, None, None)
    fn = manual_shard_map(
        lambda q_, k_, v_: _ulysses_sharded(q_, k_, v_, sm_scale, causal,
                                            axis_name, use_flash),
        {axis_name}, in_specs=(spec, spec, spec), out_specs=spec, mesh=mesh)
    return fn(q, k, v)
