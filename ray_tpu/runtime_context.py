"""Runtime context introspection.

Reference: ``python/ray/runtime_context.py`` (get_runtime_context with
node_id/task_id/actor_id/assigned resources).  TPU addition:
``tpu_chips`` — the chip indices this worker owns (the analog of
``get_gpu_ids``/CUDA_VISIBLE_DEVICES plumbing in the reference).
"""

from __future__ import annotations

from typing import List, Optional

from ray_tpu._private.api_internal import require_runtime


class RuntimeContext:
    def __init__(self, rt):
        self._rt = rt

    @property
    def is_driver(self) -> bool:
        return not self._rt.is_worker()

    @property
    def node_id(self) -> Optional[str]:
        if self._rt.is_worker():
            return self._rt.node_id_hex
        return self._rt.head_node.node_id.hex()

    @property
    def job_id(self) -> str:
        if self._rt.is_worker():
            return self._rt.job_id_hex
        return self._rt.job_id.hex()

    @property
    def task_id(self) -> Optional[str]:
        if self._rt.is_worker() and self._rt.current_task_id is not None:
            return self._rt.current_task_id.hex()
        return None

    @property
    def actor_id(self) -> Optional[str]:
        if self._rt.is_worker() and self._rt.current_actor_id is not None:
            return self._rt.current_actor_id.hex()
        return None

    def get_assigned_resources(self) -> dict:
        if self._rt.is_worker():
            return dict(self._rt.assigned_resources)
        return {}

    @property
    def tpu_chips(self) -> List[str]:
        """Chip ids granted to this worker (empty on the driver)."""
        if self._rt.is_worker():
            return list(self._rt.tpu_chips)
        return []

    def get_tpu_ids(self) -> List[str]:
        return self.tpu_chips


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext(require_runtime())
