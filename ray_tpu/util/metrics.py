"""User-defined metrics: Counter / Gauge / Histogram.

Reference: ``python/ray/util/metrics.py`` (Counter :155, Histogram :220,
Gauge :295) — the same tagged-metric surface.  Transport re-designed for
this runtime: worker-side records ride the existing worker->driver pubsub
(fire-and-forget, batched with the connection's message flow) instead of
the reference's OpenCensus -> per-node metrics agent -> Prometheus chain;
the driver aggregates on demand.  ``snapshot()`` returns the merged view.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_tpu._private import serialization
from ray_tpu._private.api_internal import require_runtime

_TOPIC = "_metrics"

# Driver-side aggregate: {(kind, name, tags): state}
_agg: Dict[tuple, Any] = {}
_agg_lock = threading.Lock()


def _record(kind: str, name: str, tags: Tuple[tuple, ...], value: float,
            boundaries: Optional[Tuple[float, ...]] = None):
    rt = require_runtime()
    rec = (kind, name, tags, float(value), boundaries)
    if rt.is_worker():
        rt.publish_event(_TOPIC, serialization.dumps_inline(rec))
    else:
        _apply(rec)


def _apply(rec):
    kind, name, tags, value, boundaries = rec
    key = (kind, name, tags)
    with _agg_lock:
        if kind == "counter":
            _agg[key] = _agg.get(key, 0.0) + value
        elif kind == "gauge":
            _agg[key] = value
        elif kind == "histogram":
            st = _agg.get(key)
            if st is None:
                st = _agg[key] = {"count": 0, "sum": 0.0,
                                  "boundaries": boundaries or (),
                                  "buckets": [0] * (len(boundaries or ())
                                                    + 1)}
            st["count"] += 1
            st["sum"] += value
            i = 0
            for i, b in enumerate(st["boundaries"]):
                if value <= b:
                    break
            else:
                i = len(st["boundaries"])
            st["buckets"][i] += 1


def _drain_worker_records():
    """Driver: merge any worker-published records into the aggregate."""
    rt = require_runtime()
    if rt.is_worker():
        return
    for payload in rt.poll_events(_TOPIC):
        try:
            _apply(serialization.loads_inline(payload))
        except Exception:
            pass


def snapshot() -> Dict[str, Any]:
    """{name{tags}: value} merged across driver + all workers (driver
    only).  Counters sum, gauges keep last-written, histograms expose
    count/sum/buckets."""
    _drain_worker_records()
    out: Dict[str, Any] = {}
    with _agg_lock:
        for (kind, name, tags), v in _agg.items():
            tag_s = ",".join(f"{k}={val}" for k, val in tags)
            key = f"{name}{{{tag_s}}}" if tag_s else name
            out[key] = dict(v) if isinstance(v, dict) else v
    return out


def reset():
    with _agg_lock:
        _agg.clear()


class _Metric:
    kind = ""

    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        if not name:
            raise ValueError("metric name required")
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _tags(self, tags: Optional[Dict[str, str]]) -> Tuple[tuple, ...]:
        merged = dict(self._default_tags)
        if tags:
            unknown = set(tags) - set(self._tag_keys)
            if unknown:
                raise ValueError(
                    f"tags {sorted(unknown)} not in tag_keys "
                    f"{self._tag_keys}")
            merged.update(tags)
        return tuple(sorted(merged.items()))


class Counter(_Metric):
    """Monotonically increasing (reference: util/metrics.py:155)."""

    kind = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None):
        if value <= 0:
            raise ValueError("Counter.inc requires value > 0")
        _record("counter", self._name, self._tags(tags), value)


class Gauge(_Metric):
    """Last-value-wins (reference: util/metrics.py:295)."""

    kind = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        _record("gauge", self._name, self._tags(tags), value)


class Histogram(_Metric):
    """Bucketed distribution (reference: util/metrics.py:220)."""

    kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Sequence[float] = (),
                 tag_keys: Sequence[str] = ()):
        super().__init__(name, description, tag_keys)
        self._boundaries = tuple(sorted(float(b) for b in boundaries))

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None):
        _record("histogram", self._name, self._tags(tags), value,
                self._boundaries)
