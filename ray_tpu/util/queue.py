"""Distributed FIFO queue (reference: python/ray/util/queue.py) — an actor
holding the buffer; blocking get/put via a threaded actor."""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, List, Optional

import ray_tpu as ray


class Empty(Exception):
    pass


class Full(Exception):
    pass


@ray.remote
class _QueueActor:
    def __init__(self, maxsize: int):
        self._maxsize = maxsize
        self._q: deque = deque()
        self._cond = threading.Condition()

    def put(self, item, timeout: Optional[float] = None) -> bool:
        with self._cond:
            if self._maxsize > 0:
                ok = self._cond.wait_for(
                    lambda: len(self._q) < self._maxsize, timeout=timeout)
                if not ok:
                    return False
            self._q.append(item)
            self._cond.notify_all()
            return True

    def get(self, timeout: Optional[float] = None):
        with self._cond:
            ok = self._cond.wait_for(lambda: len(self._q) > 0,
                                     timeout=timeout)
            if not ok:
                return (False, None)
            item = self._q.popleft()
            self._cond.notify_all()
            return (True, item)

    def qsize(self) -> int:
        return len(self._q)


class Queue:
    def __init__(self, maxsize: int = 0, max_concurrency: int = 32):
        # max_concurrency bounds how many callers may BLOCK inside the actor
        # simultaneously (put/get with block=True hold a pool thread for the
        # full wait); size it to the expected number of concurrent clients
        # or blocked consumers could starve the put that would wake them.
        self._actor = _QueueActor.options(
            max_concurrency=max_concurrency, num_cpus=0).remote(maxsize)

    def put(self, item, block: bool = True,
            timeout: Optional[float] = None):
        ok = ray.get(self._actor.put.remote(
            item, timeout if block else 0.0))
        if not ok:
            raise Full()

    def get(self, block: bool = True, timeout: Optional[float] = None):
        ok, item = ray.get(self._actor.get.remote(
            timeout if block else 0.0))
        if not ok:
            raise Empty()
        return item

    def put_async(self, item):
        return self._actor.put.remote(item, None)

    def get_async(self):
        return self._actor.get.remote(None)

    def qsize(self) -> int:
        return ray.get(self._actor.qsize.remote())

    def empty(self) -> bool:
        return self.qsize() == 0

    def shutdown(self):
        try:
            ray.kill(self._actor)
        except Exception:
            pass
