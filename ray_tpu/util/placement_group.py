"""Placement groups — gang scheduling of resource bundles.

Reference: ``python/ray/util/placement_group.py`` +
``src/ray/gcs/gcs_server/gcs_placement_group_manager.h:223`` (creation FSM,
2-phase bundle reservation) + shadow bundle resources
(``src/ray/raylet/placement_group_resource_manager.cc``).

TPU note: a placement group is the natural unit for a TPU slice — e.g. a
v5p-32 host group is one STRICT_PACK group of per-host bundles, so a Train
job's workers land on the hosts that share ICI.  See
ray_tpu.train for the slice-aware helper that builds these.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ray_tpu._private.api_internal import require_runtime
from ray_tpu._private.ids import PlacementGroupID


class PlacementGroup:
    def __init__(self, state):
        self._state = state

    @property
    def id(self) -> PlacementGroupID:
        return self._state.pg_id

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        return list(self._state.bundles)

    @property
    def bundle_count(self) -> int:
        return len(self._state.bundles)

    def ready(self):
        """ObjectRef-style readiness: returns an ObjectRef that resolves when
        all bundles are reserved (reference: PlacementGroup.ready())."""
        rt = require_runtime()
        fut = self._state.created_future
        if fut.done():
            return rt.put_object(True)

        from ray_tpu._private.ids import ObjectID
        from ray_tpu._private.object_ref import ObjectRef
        from ray_tpu._private import protocol, serialization
        from ray_tpu._private.runtime import ObjectState

        oid = ObjectID.for_put()
        with rt.lock:
            st = rt.objects[oid] = ObjectState()
            # The caller's reference, counted before the completion callback
            # can possibly fire — otherwise a ready() racing the reservation
            # frees the object and the ref resolves never.
            st.local_refs += 1

        descr = (protocol.INLINE, serialization.dumps_inline(True))

        def _complete(_f):
            with rt.lock:
                rt._complete_object_locked(oid, descr, ok=True)

        fut.add_done_callback(_complete)
        return ObjectRef(oid, _register=False)

    def wait(self, timeout_seconds: float = 30) -> bool:
        import concurrent.futures

        try:
            self._state.created_future.result(timeout=timeout_seconds)
            return True
        except concurrent.futures.TimeoutError:
            return False

    def __reduce__(self):
        raise TypeError("PlacementGroup handles are driver-local in v1")


def placement_group(bundles: List[Dict[str, float]], strategy: str = "PACK",
                    name: str = "", lifetime: Optional[str] = None
                    ) -> PlacementGroup:
    if strategy not in ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD"):
        raise ValueError(f"Invalid placement strategy {strategy!r}")
    norm = []
    for b in bundles:
        nb = {k: float(v) for k, v in b.items() if v}
        if not nb:
            raise ValueError("Empty bundle in placement group")
        norm.append(nb)
    rt = require_runtime()
    if rt.is_worker():
        raise NotImplementedError(
            "placement_group creation from workers lands in v2")
    state = rt.create_placement_group(norm, strategy, name)
    return PlacementGroup(state)


def remove_placement_group(pg: PlacementGroup):
    rt = require_runtime()
    rt.remove_placement_group(pg.id.binary())


def placement_group_table(pg: Optional[PlacementGroup] = None) -> dict:
    rt = require_runtime()
    with rt.lock:
        states = ([pg._state] if pg is not None
                  else list(rt.placement_groups.values()))
        out = {}
        for s in states:
            out[s.pg_id.hex()] = {
                "placement_group_id": s.pg_id.hex(),
                "name": s.name,
                "strategy": s.strategy,
                "bundles": {i: b for i, b in enumerate(s.bundles)},
                "state": ("REMOVED" if s.removed else
                          "CREATED" if s.created_future.done()
                          else "PENDING"),
                "bundle_nodes": [
                    n.hex() if n is not None else None for n in s.reserved],
            }
        return out if pg is None else next(iter(out.values()))
