"""State observability API: list/summarize cluster entities.

Reference: ``python/ray/experimental/state/api.py`` (``list_actors`` :738,
``list_tasks`` :961, ``list_objects`` :1005, ``summarize_tasks`` :1278) —
the same query surface over the runtime's authoritative tables instead of
a separate state aggregator service (the tables live driver-side here, so
aggregation is a read under the lock; workers reach them via one control
round trip).
"""

from __future__ import annotations

from collections import Counter as _Counter
from typing import Any, Dict, List, Optional

from ray_tpu._private.api_internal import require_runtime


def _query(kind: str, **kwargs) -> List[Dict[str, Any]]:
    rt = require_runtime()
    if rt.is_worker():
        reply = rt._request(lambda rid: ("state_req", rid, kind, kwargs))
        if isinstance(reply, Exception):
            raise reply
        return reply
    return rt.state_query(kind, **kwargs)


def list_nodes(**kw) -> List[Dict[str, Any]]:
    return _query("nodes", **kw)


def list_actors(**kw) -> List[Dict[str, Any]]:
    return _query("actors", **kw)


def list_tasks(**kw) -> List[Dict[str, Any]]:
    return _query("tasks", **kw)


def list_objects(**kw) -> List[Dict[str, Any]]:
    return _query("objects", **kw)


def list_workers(**kw) -> List[Dict[str, Any]]:
    return _query("workers", **kw)


def list_placement_groups(**kw) -> List[Dict[str, Any]]:
    return _query("placement_groups", **kw)


def get_worker_log(worker_id: str = "", tail: int = 200
                   ) -> List[Dict[str, Any]]:
    """Captured stdout/stderr lines of workers (reference: the log
    files under the session dir + `ray logs`); ``worker_id`` may be a
    hex prefix."""
    return _query("worker_log", worker_id=worker_id, tail=tail)


def summarize_tasks() -> Dict[str, int]:
    """Task-name x state counts (reference: summarize_tasks, api.py:1278)."""
    counts: _Counter = _Counter()
    for t in list_tasks():
        counts[(t["name"], t["state"])] += 1
    return {f"{name}:{state}": n for (name, state), n in counts.items()}
