from ray_tpu.util.placement_group import (
    placement_group,
    remove_placement_group,
    placement_group_table,
    PlacementGroup,
)
from ray_tpu.util.scheduling_strategies import (
    PlacementGroupSchedulingStrategy,
    NodeAffinitySchedulingStrategy,
)
from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util import accelerators, metrics, state

__all__ = [
    "placement_group", "remove_placement_group", "placement_group_table",
    "PlacementGroup", "PlacementGroupSchedulingStrategy",
    "NodeAffinitySchedulingStrategy", "ActorPool", "accelerators",
    "metrics", "state",
]
