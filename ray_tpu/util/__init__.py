from ray_tpu.util.placement_group import (
    placement_group,
    remove_placement_group,
    placement_group_table,
    PlacementGroup,
)
from ray_tpu.util.scheduling_strategies import (
    PlacementGroupSchedulingStrategy,
    NodeAffinitySchedulingStrategy,
)

__all__ = [
    "placement_group", "remove_placement_group", "placement_group_table",
    "PlacementGroup", "PlacementGroupSchedulingStrategy",
    "NodeAffinitySchedulingStrategy",
]
