from ray_tpu.util.placement_group import (
    placement_group,
    remove_placement_group,
    placement_group_table,
    PlacementGroup,
)
from ray_tpu.util.scheduling_strategies import (
    PlacementGroupSchedulingStrategy,
    NodeAffinitySchedulingStrategy,
)
from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util import accelerators, metrics, state


def __getattr__(name):
    # Lazy re-export (reference parity: ray.util.check_serializability)
    # keeps devtools entirely off the normal `import ray_tpu` path — it
    # loads only on use or when RAY_TPU_LOCKCHECK opts in.
    if name == "check_serializability":
        from ray_tpu.devtools.serializability import check_serializability

        return check_serializability
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "placement_group", "remove_placement_group", "placement_group_table",
    "PlacementGroup", "PlacementGroupSchedulingStrategy",
    "NodeAffinitySchedulingStrategy", "ActorPool", "accelerators",
    "metrics", "state", "check_serializability",
]
