"""Scheduling strategies (reference:
``python/ray/util/scheduling_strategies.py`` — PlacementGroupSchedulingStrategy,
NodeAffinitySchedulingStrategy, plus the "SPREAD"/"DEFAULT" strings)."""

from __future__ import annotations

from typing import Optional


class PlacementGroupSchedulingStrategy:
    def __init__(self, placement_group,
                 placement_group_bundle_index: int = -1,
                 placement_group_capture_child_tasks: bool = False):
        self.placement_group = placement_group
        self.placement_group_bundle_index = (
            None if placement_group_bundle_index < 0
            else placement_group_bundle_index)
        self.placement_group_capture_child_tasks = (
            placement_group_capture_child_tasks)


class NodeAffinitySchedulingStrategy:
    def __init__(self, node_id, soft: bool = False):
        # node_id: hex string or bytes
        self.node_id = (bytes.fromhex(node_id)
                        if isinstance(node_id, str) else node_id)
        self.soft = soft


DEFAULT = "DEFAULT"
SPREAD = "SPREAD"
