"""Accelerator type constants.

Reference: ``python/ray/util/accelerators/accelerators.py:1-8`` — NVIDIA
only, no TPU (SURVEY.md §2.3 calls this out).  The TPU build makes TPU
generations first-class scheduling labels: request with
``@remote(accelerator_type=TPU_V5P)`` -> the scheduler matches nodes whose
``accelerator_type`` label agrees (node labels set at add_node time)."""

TPU_V4 = "TPU-V4"
TPU_V5E = "TPU-V5E"
TPU_V5P = "TPU-V5P"
TPU_V6E = "TPU-V6E"

# Kept for reference-code compatibility: CUDA types map onto scheduling
# labels too, so code written against the reference imports cleanly.
NVIDIA_TESLA_V100 = "V100"
NVIDIA_TESLA_T4 = "T4"
NVIDIA_TESLA_A100 = "A100"

ALL_TPU = (TPU_V4, TPU_V5E, TPU_V5P, TPU_V6E)
