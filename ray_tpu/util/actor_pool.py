"""ActorPool (reference: python/ray/util/actor_pool.py) — load-balance a
stream of work over a fixed set of actors.  ``map`` preserves input order
(as the reference does); ``map_unordered`` yields in completion order."""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List

import ray_tpu as ray


class ActorPool:
    def __init__(self, actors: List[Any]):
        if not actors:
            raise ValueError("ActorPool needs at least one actor")
        self._idle = list(actors)
        self._future_to_meta = {}   # future -> (actor, submission index)
        self._pending = []          # queued (fn, value, index)
        self._next_idx = 0
        self._next_return = 0       # next submission index get_next yields
        self._ready = {}            # completed results buffered by index
        self._consumed = set()      # indices taken out-of-order (unordered)

    def submit(self, fn: Callable[[Any, Any], Any], value: Any):
        """fn(actor, value) -> ObjectRef."""
        idx = self._next_idx
        self._next_idx += 1
        if self._idle:
            actor = self._idle.pop()
            self._future_to_meta[fn(actor, value)] = (actor, idx)
        else:
            self._pending.append((fn, value, idx))

    def has_next(self) -> bool:
        return (bool(self._future_to_meta) or bool(self._pending)
                or bool(self._ready))

    def _complete_one(self, timeout=None):
        """-> (idx, ok, result-or-exception).  Task errors are captured, not
        raised: raising after the future is popped but before its index is
        buffered would wedge ordered get_next forever (the index could never
        appear in _ready).  Reference: _next_return_index semantics in
        python/ray/util/actor_pool.py."""
        done, _ = ray.wait(list(self._future_to_meta), num_returns=1,
                           timeout=timeout)
        if not done:
            raise TimeoutError("get_next timed out")
        fut = done[0]
        actor, idx = self._future_to_meta.pop(fut)
        if self._pending:
            fn, value, pidx = self._pending.pop(0)
            self._future_to_meta[fn(actor, value)] = (actor, pidx)
        else:
            self._idle.append(actor)
        try:
            return idx, True, ray.get(fut)
        except Exception as e:  # noqa: BLE001 — surfaced at yield time
            return idx, False, e

    def get_next(self, timeout=None) -> Any:
        """Next result in SUBMISSION order (reference semantics:
        ``_index_to_future``/``_next_return_index`` in
        ``python/ray/util/actor_pool.py``) — interleaved submit()/get_next()
        pairs inputs with outputs."""
        if not self.has_next():
            raise StopIteration("no pending work")
        import time as _time
        deadline = None if timeout is None else _time.monotonic() + timeout
        while self._next_return in self._consumed:
            self._consumed.discard(self._next_return)
            self._next_return += 1
        want = self._next_return
        while want not in self._ready:
            remaining = (None if deadline is None
                         else max(0.0, deadline - _time.monotonic()))
            idx, ok, result = self._complete_one(remaining)
            self._ready[idx] = (ok, result)
        self._next_return += 1
        ok, result = self._ready.pop(want)
        if not ok:
            raise result
        return result

    def get_next_unordered(self, timeout=None) -> Any:
        """Next result in COMPLETION order."""
        if not self.has_next():
            raise StopIteration("no pending work")
        if self._ready:
            # Results already fetched while waiting in-order: drain first.
            idx = next(iter(self._ready))
            self._consumed.add(idx)
            ok, result = self._ready.pop(idx)
        else:
            idx, ok, result = self._complete_one(timeout)
            self._consumed.add(idx)
        if not ok:
            raise result
        return result

    def map(self, fn: Callable[[Any, Any], Any],
            values: Iterable[Any]) -> Iterator[Any]:
        """Results in input order (reference semantics)."""
        base = self._next_idx
        for v in values:
            self.submit(fn, v)
        buffered = {}
        want = base
        while self.has_next() or buffered:
            while want in buffered:
                yield buffered.pop(want)
                want += 1
            if not self.has_next():
                break
            idx, ok, result = self._complete_one()
            self._consumed.add(idx)
            if not ok:
                raise result
            buffered[idx] = result
        while want in buffered:
            yield buffered.pop(want)
            want += 1

    def map_unordered(self, fn, values) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()
