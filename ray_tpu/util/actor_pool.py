"""ActorPool (reference: python/ray/util/actor_pool.py) — load-balance a
stream of work over a fixed set of actors.  ``map`` preserves input order
(as the reference does); ``map_unordered`` yields in completion order."""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List

import ray_tpu as ray


class ActorPool:
    def __init__(self, actors: List[Any]):
        if not actors:
            raise ValueError("ActorPool needs at least one actor")
        self._idle = list(actors)
        self._future_to_meta = {}   # future -> (actor, submission index)
        self._pending = []          # queued (fn, value, index)
        self._next_idx = 0

    def submit(self, fn: Callable[[Any, Any], Any], value: Any):
        """fn(actor, value) -> ObjectRef."""
        idx = self._next_idx
        self._next_idx += 1
        if self._idle:
            actor = self._idle.pop()
            self._future_to_meta[fn(actor, value)] = (actor, idx)
        else:
            self._pending.append((fn, value, idx))

    def has_next(self) -> bool:
        return bool(self._future_to_meta) or bool(self._pending)

    def _complete_one(self, timeout=None):
        done, _ = ray.wait(list(self._future_to_meta), num_returns=1,
                           timeout=timeout)
        if not done:
            raise TimeoutError("get_next timed out")
        fut = done[0]
        actor, idx = self._future_to_meta.pop(fut)
        if self._pending:
            fn, value, pidx = self._pending.pop(0)
            self._future_to_meta[fn(actor, value)] = (actor, pidx)
        else:
            self._idle.append(actor)
        return idx, ray.get(fut)

    def get_next(self, timeout=None) -> Any:
        """Next result in completion order."""
        if not self.has_next():
            raise StopIteration("no pending work")
        return self._complete_one(timeout)[1]

    def get_next_unordered(self, timeout=None) -> Any:
        return self.get_next(timeout)

    def map(self, fn: Callable[[Any, Any], Any],
            values: Iterable[Any]) -> Iterator[Any]:
        """Results in input order (reference semantics)."""
        base = self._next_idx
        for v in values:
            self.submit(fn, v)
        buffered = {}
        want = base
        while self.has_next() or buffered:
            while want in buffered:
                yield buffered.pop(want)
                want += 1
            if not self.has_next():
                break
            idx, result = self._complete_one()
            buffered[idx] = result
        while want in buffered:
            yield buffered.pop(want)
            want += 1

    def map_unordered(self, fn, values) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()
