"""Host-level collectives between actors (ray.util.collective equivalent).

Reference: ``python/ray/util/collective/collective.py`` —
``init_collective_group`` (:120), declarative ``create_collective_group``
(:151), ``allreduce/allgather/reducescatter/broadcast/send/recv``
(:258,423,472,373,531,594) over NCCL/Gloo groups.

TPU split (SURVEY.md §2.3): *device* collectives are XLA (``jax.lax.p*``
under jit over the mesh — see ray_tpu.parallel), so this module only covers
the *host* tier the reference used Gloo for: numpy buffers between actor
processes, rendezvoused through a named coordinator actor (threaded, so
blocking barriers work).  That is the DCN-control-plane analog — checkpoint
shards, rollout aggregation, eval gathers; never the gradient hot path.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu as ray

_GROUP_PREFIX = "collective_group:"
# Process-scoped (NOT thread-local): in an actor with max_concurrency > 1,
# method calls are serviced by different pool threads, so a group inited on
# one thread must be visible to collective ops handled by another.
_groups_lock = threading.Lock()
_GROUPS: Dict[str, "_GroupState"] = {}


@ray.remote
class _Coordinator:
    """Rendezvous + reduction point for one group.  max_concurrency lets all
    ranks block inside contribute() simultaneously."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._rounds: Dict[tuple, Dict[int, Any]] = {}
        self._results: Dict[tuple, Any] = {}

    def _gather(self, key, rank, value):
        """Block until all ranks contributed; the completion flag is
        monotonic (a waiter's predicate can never flip back to false while
        another rank starts consuming the round)."""
        with self._cond:
            slot = self._rounds.setdefault(
                key, {"vals": {}, "done": False, "left": self.world_size})
            slot["vals"][rank] = value
            if len(slot["vals"]) == self.world_size:
                slot["done"] = True
                self._cond.notify_all()
            elif not self._cond.wait_for(lambda: slot["done"], timeout=120):
                raise TimeoutError(
                    f"collective round {key} timed out with "
                    f"{len(slot['vals'])}/{self.world_size} ranks")
            return slot

    def _finish(self, key, slot, compute):
        """First-finisher computes; everyone reads; last rank cleans up."""
        with self._lock:
            if key not in self._results:
                self._results[key] = compute(slot["vals"])
            out = self._results[key]
            slot["left"] -= 1
            if slot["left"] == 0:
                self._rounds.pop(key, None)
                self._results.pop(key, None)
            return out

    def allreduce(self, seq, rank, arr, op):
        key = ("ar", seq)
        slot = self._gather(key, rank, arr)

        def compute(vals):
            vs = [vals[r] for r in sorted(vals)]
            if op == "sum":
                return sum(vs[1:], start=vs[0].copy())
            if op == "max":
                return np.maximum.reduce(vs)
            if op == "min":
                return np.minimum.reduce(vs)
            if op == "mean":
                return sum(vs[1:], start=vs[0].copy()) / len(vs)
            raise ValueError(op)

        return self._finish(key, slot, compute)

    def allgather(self, seq, rank, arr):
        key = ("ag", seq)
        slot = self._gather(key, rank, arr)
        return self._finish(
            key, slot, lambda vals: [vals[r] for r in sorted(vals)])

    def reducescatter(self, seq, rank, arr, op):
        key = ("rs", seq)
        slot = self._gather(key, rank, arr)

        def compute(vals):
            vs = [vals[r] for r in sorted(vals)]
            if op in ("sum", "mean"):
                total = sum(vs[1:], start=vs[0].copy())
                if op == "mean":
                    total = total / len(vs)
            elif op == "max":
                total = np.maximum.reduce(vs)
            elif op == "min":
                total = np.minimum.reduce(vs)
            else:
                raise ValueError(op)
            return np.array_split(total, self.world_size)

        return self._finish(key, slot, compute)[rank]

    def broadcast(self, seq, rank, arr, src):
        key = ("bc", seq)
        slot = self._gather(key, rank, arr if rank == src else None)
        return self._finish(key, slot, lambda vals: vals[src])

    def barrier(self, seq, rank):
        key = ("ba", seq)
        slot = self._gather(key, rank, True)
        return self._finish(key, slot, lambda vals: True)

    def put_p2p(self, seq, dst, arr):
        with self._cond:
            self._rounds[("p2p", seq, dst)] = {0: arr}
            self._cond.notify_all()
        return True

    def get_p2p(self, seq, dst):
        with self._cond:
            self._cond.wait_for(
                lambda: ("p2p", seq, dst) in self._rounds, timeout=120)
            return self._rounds.pop(("p2p", seq, dst))[0]


class _GroupState:
    def __init__(self, name, rank, world_size, coordinator):
        self.name = name
        self.rank = rank
        self.world_size = world_size
        self.coordinator = coordinator
        self.seq = 0
        # p2p counters are per (src, dst) pair: only the two endpoints
        # advance them, so they stay matched without a global barrier.
        self.p2p_seq: Dict[tuple, int] = {}
        self.ring: Optional["_Ring"] = None

    def next_seq(self):
        self.seq += 1
        return self.seq

    def next_p2p_seq(self, src: int, dst: int):
        key = (src, dst)
        self.p2p_seq[key] = self.p2p_seq.get(key, 0) + 1
        return self.p2p_seq[key]


class _Ring:
    """Ring transport over the workers' direct-push listeners: each rank
    holds ONE connection to its right neighbour and receives from its
    left via the process's DirectServer ``dmsg`` channel.

    This replaces the star coordinator for bulk collectives — the star
    shipped world_size FULL arrays through one actor's pickled call path
    (its GIL and NIC serialized every round); a ring moves
    2*(N-1)/N * bytes per rank over direct peer sockets, all links busy
    simultaneously (reference shape: ring allreduce in
    nccl_collective_group.py:821 — re-designed over our own transport;
    TPU-device collectives remain XLA's, ray_tpu.parallel)."""

    def __init__(self, group_name: str, rank: int, world_size: int):
        import queue as _q

        from ray_tpu._private import protocol as _protocol
        from ray_tpu._private.worker_main import get_worker_runtime

        self._protocol = _protocol
        self.rank = rank
        self.world = world_size
        self.channel = f"coll:{group_name}:{rank}"
        self._rt = get_worker_runtime()
        self._inbox: "_q.SimpleQueue" = _q.SimpleQueue()
        # Handler registered BEFORE the address barrier: a fast
        # neighbour's first step may land the instant the barrier
        # releases it, and an unregistered channel drops silently.
        self._rt.register_peer_handler(self.channel, self._inbox.put)
        self._right = None
        self._right_lock = threading.Lock()

    def connect(self, addrs: List[tuple]):
        import os
        from multiprocessing.connection import Client

        right = addrs[(self.rank + 1) % self.world]
        authkey = bytes.fromhex(os.environ.get("RAY_TPU_AUTHKEY", ""))
        self._right = Client(tuple(right), authkey=authkey)

    def send_right(self, step: int, payload: bytes):
        dst_channel = f"{self.channel.rsplit(':', 1)[0]}:" \
                      f"{(self.rank + 1) % self.world}"
        with self._right_lock:
            self._protocol.send(self._right,
                                ("dmsg", dst_channel, (step, payload)))

    def recv_left(self, step: int) -> bytes:
        # Per-step matching: collective calls are issued in the same
        # order on every rank, and the left neighbour sends steps in
        # order, so messages arrive matched (assert guards drift).
        got_step, payload = self._inbox.get(timeout=120)
        assert got_step == step, (got_step, step)
        return payload

    def close(self):
        try:
            self._rt.unregister_peer_handler(self.channel)
        except Exception:
            pass
        try:
            self._right.close()
        except Exception:
            pass


def _groups() -> Dict[str, _GroupState]:
    with _groups_lock:
        return dict(_GROUPS)


def init_collective_group(world_size: int, rank: int,
                          group_name: str = "default"):
    """Called by each participating actor/task (reference:
    collective.py:120)."""
    name = _GROUP_PREFIX + group_name
    with _groups_lock:
        if group_name in _GROUPS:
            raise RuntimeError(
                f"collective group {group_name!r} already initialized in "
                f"this process")
    if rank == 0:
        coord = _Coordinator.options(
            name=name, max_concurrency=max(world_size + 2, 4),
            num_cpus=0).remote(world_size)
    else:
        coord = _wait_for_actor(name)
    g = _GroupState(group_name, rank, world_size, coord)
    with _groups_lock:
        _GROUPS[group_name] = g
    # Ring setup: exchange each rank's direct-listener address (tiny)
    # through the star; bulk collectives then bypass it entirely.  Two
    # agreement rounds: addresses, then per-rank connect success — ALL
    # ranks use the ring or NONE do (a mixed group would deadlock).
    ring = None
    addr = None
    try:
        from ray_tpu._private.worker_main import get_worker_runtime

        rt = get_worker_runtime()
        if rt is not None and rt.direct_addr and world_size > 1:
            ring = _Ring(group_name, rank, world_size)
            addr = tuple(rt.direct_addr)
    except Exception:
        ring = None
    addrs = ray.get(g.coordinator.allgather.remote(
        g.next_seq(), rank, addr))
    ok = ring is not None and all(a is not None for a in addrs)
    if ok:
        try:
            ring.connect(addrs)
        except Exception:
            ok = False
    oks = ray.get(g.coordinator.allgather.remote(g.next_seq(), rank, ok))
    if all(oks) and ring is not None:
        g.ring = ring
    elif ring is not None:
        ring.close()


def _wait_for_actor(name, timeout=30.0):
    import time
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            return ray.get_actor(name)
        except Exception:
            time.sleep(0.05)
    raise TimeoutError(f"collective group actor {name} not found")


def create_collective_group(actors: List[Any], world_size: int,
                            ranks: List[int],
                            group_name: str = "default"):
    """Declarative setup from the driver (reference: collective.py:151)."""
    futs = []
    for actor, rank in zip(actors, ranks):
        futs.append(actor.execute.remote(
            init_collective_group, world_size, rank, group_name))
    ray.get(futs)


def _group(group_name) -> _GroupState:
    g = _groups().get(group_name)
    if g is None:
        raise RuntimeError(
            f"collective group {group_name!r} not initialized in this "
            f"process — call init_collective_group first")
    return g


def _op_apply(op: str, dst: np.ndarray, src: np.ndarray):
    if op in ("sum", "mean"):
        np.add(dst, src, out=dst)
    elif op == "max":
        np.maximum(dst, src, out=dst)
    elif op == "min":
        np.minimum(dst, src, out=dst)
    else:
        raise ValueError(op)


def _ring_reduce_phase(g: _GroupState, seq: int, chunks: List[np.ndarray],
                       op: str):
    """Ring reduce-scatter pass: indices shifted so that after n-1 steps
    rank r fully owns chunk r (matching the star's array_split[rank]
    semantics)."""
    n, ring = g.world_size, g.ring
    rr = (g.rank - 1) % n
    for i in range(n - 1):
        send_idx = (rr - i) % n
        recv_idx = (rr - i - 1) % n
        ring.send_right((seq, "rs", i), chunks[send_idx])
        incoming = ring.recv_left((seq, "rs", i))
        _op_apply(op, chunks[recv_idx], incoming)


def _ring_allgather_phase(g: _GroupState, seq: int,
                          chunks: List[np.ndarray]):
    n, ring = g.world_size, g.ring
    rr = (g.rank - 1) % n
    for i in range(n - 1):
        send_idx = (rr + 1 - i) % n
        recv_idx = (rr - i) % n
        ring.send_right((seq, "ag", i), chunks[send_idx])
        chunks[recv_idx][...] = ring.recv_left((seq, "ag", i))


def allreduce(tensor: np.ndarray, group_name: str = "default",
              op: str = "sum") -> np.ndarray:
    g = _group(group_name)
    arr = np.asarray(tensor)
    if g.ring is not None and arr.size >= 1024:
        seq = g.next_seq()
        out = np.ascontiguousarray(arr).copy()
        flat = out.reshape(-1)
        chunks = np.array_split(flat, g.world_size)  # views into out
        _ring_reduce_phase(g, seq, chunks, op)
        _ring_allgather_phase(g, seq, chunks)
        if op == "mean":
            # True division promotes (int inputs -> float), matching
            # the star path's sum/len.
            return (out / g.world_size).reshape(arr.shape)
        return out
    return ray.get(g.coordinator.allreduce.remote(
        g.next_seq(), g.rank, arr, op))


def allgather(tensor: np.ndarray, group_name: str = "default"
              ) -> List[np.ndarray]:
    g = _group(group_name)
    arr = np.asarray(tensor)
    # No size threshold: per-rank sizes may differ, and a size-dependent
    # branch would let ranks pick different transports and deadlock.
    if g.ring is not None:
        # Pass each rank's whole array around the ring: n-1 steps, every
        # link busy, nothing through the coordinator.
        seq = g.next_seq()
        n, r, ring = g.world_size, g.rank, g.ring
        out: List[Optional[np.ndarray]] = [None] * n
        out[r] = arr.copy()  # snapshot: callers may mutate their input
        cur = arr
        for i in range(n - 1):
            ring.send_right((seq, "ag", i), cur)
            cur = ring.recv_left((seq, "ag", i))
            out[(r - i - 1) % n] = cur
        return [np.asarray(a) for a in out]
    return ray.get(g.coordinator.allgather.remote(
        g.next_seq(), g.rank, arr))


def reducescatter(tensor: np.ndarray, group_name: str = "default",
                  op: str = "sum") -> np.ndarray:
    g = _group(group_name)
    arr = np.asarray(tensor)
    if g.ring is not None and arr.size >= 1024:
        seq = g.next_seq()
        # Split along axis 0 like the star path (array_split on the
        # UNflattened total), so multi-dim tensors partition into the
        # same row blocks on either transport.
        buf = np.ascontiguousarray(arr).copy()
        chunks = np.array_split(buf, g.world_size)
        _ring_reduce_phase(g, seq, chunks, op)
        mine = chunks[g.rank]
        if op == "mean":
            return mine / g.world_size
        return mine.copy()  # drop the world_size-times-larger backing buf
    return ray.get(g.coordinator.reducescatter.remote(
        g.next_seq(), g.rank, arr, op))


def broadcast(tensor: np.ndarray, src_rank: int = 0,
              group_name: str = "default") -> np.ndarray:
    g = _group(group_name)
    return ray.get(g.coordinator.broadcast.remote(
        g.next_seq(), g.rank, np.asarray(tensor), src_rank))


def barrier(group_name: str = "default"):
    g = _group(group_name)
    ray.get(g.coordinator.barrier.remote(g.next_seq(), g.rank))


def send(tensor: np.ndarray, dst_rank: int, group_name: str = "default"):
    g = _group(group_name)
    seq = g.next_p2p_seq(g.rank, dst_rank)
    ray.get(g.coordinator.put_p2p.remote(
        (g.rank, dst_rank, seq), dst_rank, np.asarray(tensor)))


def recv(src_rank: int, group_name: str = "default") -> np.ndarray:
    g = _group(group_name)
    seq = g.next_p2p_seq(src_rank, g.rank)
    return ray.get(g.coordinator.get_p2p.remote(
        (src_rank, g.rank, seq), g.rank))


def destroy_collective_group(group_name: str = "default"):
    # Mutate _GROUPS itself under its lock — _groups() hands out a copy, so
    # popping from that copy would leak the entry and make any later
    # destroy-then-reinit of the same name fail the duplicate check.
    with _groups_lock:
        g = _GROUPS.pop(group_name, None)
    if g is not None and g.ring is not None:
        g.ring.close()
    if g is not None and g.rank == 0:
        try:
            ray.kill(g.coordinator)
        except Exception:
            pass
