"""Task timeline: aggregate execution spans into a Chrome/Perfetto trace.

Reference: ``ray timeline`` (``python/ray/scripts/scripts.py:1840`` — dumps
profiling events as chrome://tracing JSON) + the task-event span pipeline of
``python/ray/util/tracing/tracing_helper.py:164``.  Here every worker
records (task_id, name, start, end) wall-clock spans around execution
(worker_main._execute) and ships them to the head in periodic batches; this
module renders them in the Chrome trace-event format so a 1k-task run opens
directly in Perfetto / chrome://tracing.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from ray_tpu._private.api_internal import require_runtime


def get_task_spans(limit: int = 200_000) -> List[Dict[str, Any]]:
    """Raw execution spans aggregated at the head."""
    rt = require_runtime()
    if rt.is_worker():
        reply = rt._request(
            lambda rid: ("state_req", rid, "spans", {"limit": limit}))
        if isinstance(reply, Exception):
            raise reply
        return reply
    return rt.state_query("spans", limit=limit)


def handler_stats() -> List[Dict[str, Any]]:
    """Per-message-handler latency counters on the head loop
    (reference: src/ray/common/event_stats.h)."""
    rt = require_runtime()
    if rt.is_worker():
        reply = rt._request(
            lambda rid: ("state_req", rid, "handler_stats", {}))
        if isinstance(reply, Exception):
            raise reply
        return reply
    return rt.state_query("handler_stats")


def chrome_trace(spans: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Spans -> Chrome trace-event list ("X" complete events; pid=node,
    tid=worker, so Perfetto lays tasks out per worker lane)."""
    events: List[Dict[str, Any]] = []
    # Stable short lane ids: Perfetto renders pid/tid as numbers-with-
    # names via metadata events; thread names bind per (pid, tid), so
    # lanes are tracked as (node, worker) pairs.
    node_ids: Dict[str, int] = {}
    lane_ids: Dict[tuple, int] = {}
    for s in spans:
        node = s.get("node_id") or "head"
        pid = node_ids.setdefault(node, len(node_ids) + 1)
        tid = lane_ids.setdefault((node, s["worker_id"]),
                                  len(lane_ids) + 1)
        events.append({
            "name": s["name"],
            "cat": s.get("kind", "task"),
            "ph": "X",
            "ts": round(s["start"] * 1e6, 1),
            "dur": round((s["end"] - s["start"]) * 1e6, 1),
            "pid": pid,
            "tid": tid,
            "args": {"task_id": s["task_id"]},
        })
    for nid, pid in node_ids.items():
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": f"node {nid[:12]}"}})
    for (node, wid), tid in lane_ids.items():
        events.append({"name": "thread_name", "ph": "M",
                       "pid": node_ids[node], "tid": tid,
                       "args": {"name": f"worker {wid[:12]}"}})
    return events


def timeline(filename: Optional[str] = None):
    """Dump the cluster's task timeline (reference: ``ray.timeline()`` /
    ``ray timeline``).  With ``filename``, writes Chrome trace JSON and
    returns the path; otherwise returns the event list."""
    events = chrome_trace(get_task_spans())
    if filename is None:
        return events
    with open(filename, "w", encoding="utf-8") as f:
        json.dump(events, f)
    return filename
