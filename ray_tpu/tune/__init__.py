"""ray_tpu.tune — trial orchestration / HPO (Ray Tune equivalent).

Reference: ``python/ray/tune/`` (SURVEY.md §2.3, 43k LoC) — ``tune.run``
(:185), ``Tuner`` (tuner.py:47), trials as Trainable actors, schedulers
(ASHA/PBT/...), searchers, experiment checkpointing.  Condensed here to the
same moving parts: search.py (spaces + variant generation), trainable.py
(class/function API), schedulers.py (FIFO/ASHA/PBT), trial_runner.py (event
loop + experiment checkpoint/resume).
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, Dict, Optional

from ray_tpu.air.result import Result
from ray_tpu.tune.search import (
    BasicVariantGenerator, OptunaSearch, Searcher, TPESearcher, choice,
    grid_search, loguniform, randint, sample_from, uniform,
)
from ray_tpu.tune.schedulers import (
    AsyncHyperBandScheduler, FIFOScheduler, HyperBandScheduler,
    MedianStoppingRule, PopulationBasedTraining, TrialScheduler,
)
from ray_tpu.tune.trainable import Trainable, wrap_function
from ray_tpu.tune.trial_runner import Trial, TrialRunner


class TuneConfig:
    """Reference: python/ray/tune/tune_config.py."""

    def __init__(self, metric: str = None, mode: str = "max",
                 num_samples: int = 1, scheduler=None, search_alg=None,
                 max_concurrent_trials: int = 8, seed=None):
        self.metric = metric
        self.mode = mode
        self.num_samples = num_samples
        self.scheduler = scheduler
        self.search_alg = search_alg
        self.max_concurrent_trials = max_concurrent_trials
        self.seed = seed


class ResultGrid:
    """Reference: python/ray/tune/result_grid.py."""

    def __init__(self, trials, metric=None, mode="max"):
        self.trials = trials
        self._metric = metric
        self._mode = mode

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        sign = 1 if mode == "max" else -1
        best = max(
            (t for t in self.trials if metric in t.last_result),
            key=lambda t: sign * t.last_result[metric])
        from ray_tpu.air.checkpoint import Checkpoint
        ckpt = (Checkpoint.from_bytes(best.latest_checkpoint)
                if best.latest_checkpoint else None)
        return Result(metrics=best.last_result, checkpoint=ckpt,
                      metrics_history=best.results)

    @property
    def num_errors(self):
        return sum(1 for t in self.trials if t.error)

    def __len__(self):
        return len(self.trials)


class Tuner:
    """Reference: python/ray/tune/tuner.py:47."""

    def __init__(self, trainable, *, param_space: Optional[Dict] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config=None,
                 resources_per_trial: Optional[Dict[str, float]] = None):
        self._trainable = trainable
        self._param_space = param_space or {}
        self._tune_config = tune_config or TuneConfig()
        self._run_config = run_config
        self._resources = resources_per_trial

    def fit(self) -> ResultGrid:
        tc = self._tune_config
        trainable = self._trainable
        restore_path = getattr(self, "_restore_path", None)
        if not (inspect.isclass(trainable)
                and issubclass(trainable, Trainable)):
            if hasattr(trainable, "as_trainable"):
                trainable = wrap_function(trainable.as_trainable())
            else:
                trainable = wrap_function(trainable)
        searcher = tc.search_alg or BasicVariantGenerator(
            self._param_space, num_samples=tc.num_samples, seed=tc.seed)
        stop = {}
        ckpt_dir = None
        max_failures = 0
        if self._run_config is not None:
            stop = self._run_config.stop or {}
            ckpt_dir = self._run_config.storage_path
            if self._run_config.failure_config:
                max_failures = self._run_config.failure_config.max_failures
        runner = TrialRunner(
            trainable, searcher=searcher, scheduler=tc.scheduler,
            num_concurrent=tc.max_concurrent_trials,
            resources_per_trial=self._resources,
            max_failures=max_failures, stop=stop,
            checkpoint_dir=restore_path or ckpt_dir, checkpoint_every=10)
        if restore_path:
            # Resume: reload trial states; finished trials stay terminated,
            # unfinished ones restart from their latest checkpoint.
            restored = runner.restore_experiment()
            if restored:
                runner._exhausted = True  # don't re-suggest restored configs
        runner.run()
        return ResultGrid(runner.trials, tc.metric, tc.mode)

    @classmethod
    def restore(cls, path: str, trainable, *,
                tune_config: Optional[TuneConfig] = None,
                run_config=None) -> "Tuner":
        """Resume a checkpointed experiment (reference: Tuner.restore)."""
        t = cls(trainable, tune_config=tune_config, run_config=run_config)
        t._restore_path = path
        return t


def run(trainable, *, config: Optional[Dict[str, Any]] = None,
        num_samples: int = 1, scheduler=None, search_alg=None, stop=None,
        metric: Optional[str] = None, mode: str = "max",
        max_concurrent_trials: int = 8,
        resources_per_trial: Optional[Dict[str, float]] = None,
        storage_path: Optional[str] = None, seed=None) -> ResultGrid:
    """Functional entry point (reference: tune.run, tune.py:185)."""
    from ray_tpu.air.config import RunConfig
    tuner = Tuner(
        trainable, param_space=config,
        tune_config=TuneConfig(metric=metric, mode=mode,
                               num_samples=num_samples, scheduler=scheduler,
                               search_alg=search_alg,
                               max_concurrent_trials=max_concurrent_trials,
                               seed=seed),
        run_config=RunConfig(stop=stop, storage_path=storage_path),
        resources_per_trial=resources_per_trial)
    return tuner.fit()


__all__ = [
    "Tuner", "TuneConfig", "ResultGrid", "run", "Trainable", "Trial",
    "TrialRunner", "choice", "uniform", "loguniform", "randint",
    "grid_search", "sample_from", "BasicVariantGenerator", "Searcher",
    "TPESearcher", "OptunaSearch", "TrialScheduler", "FIFOScheduler",
    "AsyncHyperBandScheduler", "HyperBandScheduler",
    "MedianStoppingRule", "PopulationBasedTraining",
]
