"""Trainable: the unit of Tune execution.

Reference: ``python/ray/tune/trainable/trainable.py:343`` (class API with
``train()`` per iteration + ``save_checkpoint``/``load_checkpoint``) and
``function_trainable.py`` (function API).  Both run as one actor per trial.

The class API is the iterative path every scheduler interacts with (ASHA
stops trials between iterations; PBT exploits/explores between iterations);
the function API wraps a generator or plain function into the same shape.
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint


class Trainable:
    """Subclass: implement setup/step (+ save/load for PBT & resume)."""

    def __init__(self, config: Optional[Dict[str, Any]] = None):
        self.config = config or {}
        self.iteration = 0
        self.setup(self.config)

    # -- overridable -------------------------------------------------------
    def setup(self, config: Dict[str, Any]):
        pass

    def step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def save_checkpoint(self) -> Dict[str, Any]:
        return {}

    def load_checkpoint(self, state: Dict[str, Any]):
        pass

    def reset_config(self, new_config: Dict[str, Any]) -> bool:
        """Reuse the actor for a new config (PBT explore). Return True if
        handled (reference: trainable.py reset_config)."""
        return False

    def cleanup(self):
        pass

    # -- driver-called (actor methods) ------------------------------------
    def train(self) -> Dict[str, Any]:
        result = self.step()
        self.iteration += 1
        result.setdefault("training_iteration", self.iteration)
        return result

    def save(self) -> bytes:
        state = {"iteration": self.iteration,
                 "state": self.save_checkpoint(),
                 "config": self.config}
        return Checkpoint.from_dict(state).to_bytes()

    def restore(self, blob: bytes):
        state = Checkpoint.from_bytes(blob).to_dict()
        self.iteration = state["iteration"]
        self.load_checkpoint(state["state"])
        return True

    def reset(self, new_config: Dict[str, Any]) -> bool:
        ok = self.reset_config(new_config)
        if ok:
            self.config = new_config
            self.iteration = 0
        return ok

    def stop(self):
        self.cleanup()
        return True


def wrap_function(fn: Callable) -> type:
    """Function API -> class API.

    Generator functions yield per-iteration metric dicts (the idiomatic
    iterative form here — the reference's session.report inside a running
    function is its streaming equivalent); plain functions run once and
    their return dict is the single result.
    """

    if inspect.isgeneratorfunction(fn):
        class GenTrainable(Trainable):
            def setup(self, config):
                self._gen = fn(config)

            def step(self):
                try:
                    return dict(next(self._gen))
                except StopIteration:
                    return {"done": True}
        GenTrainable.__name__ = f"Gen({fn.__name__})"
        return GenTrainable

    class FuncTrainable(Trainable):
        def step(self):
            out = fn(self.config) or {}
            out["done"] = True
            return dict(out)
    FuncTrainable.__name__ = f"Func({fn.__name__})"
    return FuncTrainable
