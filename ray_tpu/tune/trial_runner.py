"""Trial + TrialRunner: the Tune event loop.

Reference: ``python/ray/tune/experiment/trial.py`` (Trial state machine) and
``tune/execution/trial_runner.py:1140`` (``step`` :1315 — the loop that
starts trials as actors, collects results, consults the scheduler, handles
failures/retries, and checkpoints the experiment for resume).  The actor
execution path condenses ``RayTrialExecutor``
(``tune/execution/ray_trial_executor.py:185``).
"""

from __future__ import annotations

import json
import os
import pickle
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu as ray
from ray_tpu.tune import schedulers as sched_mod
from ray_tpu.tune.schedulers import CONTINUE, PAUSE, STOP, FIFOScheduler
from ray_tpu.tune.search import BasicVariantGenerator, Searcher

PENDING, RUNNING, PAUSED, TERMINATED, ERRORED = (
    "PENDING", "RUNNING", "PAUSED", "TERMINATED", "ERROR")


class Trial:
    def __init__(self, trial_id: str, config: Dict[str, Any]):
        self.trial_id = trial_id
        self.config = config
        self.status = PENDING
        self.actor = None
        self.last_result: Dict[str, Any] = {}
        self.results: List[Dict[str, Any]] = []
        self.latest_checkpoint: Optional[bytes] = None
        self.error: Optional[str] = None
        self.retries = 0
        self.pending_restore: Optional[tuple] = None  # (blob, new_config)

    def __repr__(self):
        return f"Trial({self.trial_id}, {self.status})"


class TrialRunner:
    def __init__(self, trainable_cls: type, *,
                 searcher: Searcher,
                 scheduler=None,
                 num_concurrent: int = 8,
                 resources_per_trial: Optional[Dict[str, float]] = None,
                 max_failures: int = 0,
                 stop: Optional[Dict[str, Any]] = None,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: int = 0):
        self._cls = trainable_cls
        self._remote_cls = ray.remote(trainable_cls)
        self._searcher = searcher
        self._scheduler = scheduler or FIFOScheduler()
        self._num_concurrent = num_concurrent
        self._resources = resources_per_trial or {"CPU": 1.0}
        self._max_failures = max_failures
        self._stop = stop or {}
        self._ckpt_dir = checkpoint_dir
        self._ckpt_every = checkpoint_every
        self.trials: List[Trial] = []
        self._future_to_trial: Dict[Any, Trial] = {}
        self._restore_futures: Dict[str, Any] = {}
        self._exhausted = False
        self._iterations = 0

    # ------------------------------------------------------------- helpers
    def get_trial(self, trial_id: str) -> Optional[Trial]:
        for t in self.trials:
            if t.trial_id == trial_id:
                return t
        return None

    def transfer_checkpoint(self, donor: Trial, target: Trial,
                            new_config: Dict[str, Any]):
        """PBT exploit/explore: restore donor's checkpoint into target with
        a mutated config at its next boundary."""
        target.pending_restore = (donor.latest_checkpoint, new_config)

    def unpause_trial(self, trial: Trial):
        """Resume a PAUSED trial from its checkpoint (synchronous
        HyperBand promotion; reference: trial PAUSED -> RUNNING via
        choose_trial_to_run)."""
        if trial.status != PAUSED:
            return
        self._start_trial(trial)

    def stop_trial(self, trial: Trial):
        """Scheduler-initiated stop of a trial that is not currently
        reporting (e.g. a paused rung loser)."""
        if trial.status in (TERMINATED, ERRORED):
            return
        self._searcher.on_trial_complete(trial.trial_id,
                                         trial.last_result)
        self._terminate(trial, TERMINATED)

    def _make_actor(self, trial: Trial):
        res = dict(self._resources)
        cpu = res.pop("CPU", 1.0)
        tpu = res.pop("TPU", 0.0)
        opts = {"num_cpus": cpu, "resources": res or None}
        if tpu:
            opts["num_tpus"] = int(tpu)
        return self._remote_cls.options(**opts).remote(trial.config)

    def _start_trial(self, trial: Trial):
        trial.actor = self._make_actor(trial)
        trial.status = RUNNING
        if trial.latest_checkpoint is not None:
            # Async submit: per-actor FIFO guarantees restore runs
            # before train.  A blocking get here would wedge the whole
            # runner loop whenever the new actor waits for a CPU that a
            # still-running trial holds (the trial that would free it is
            # serviced by THIS loop).  The future is kept so a failed
            # restore surfaces as a trial error instead of silently
            # training from scratch.
            self._restore_futures[trial.trial_id] = \
                trial.actor.restore.remote(trial.latest_checkpoint)
        self._future_to_trial[trial.actor.train.remote()] = trial

    def _maybe_add_trials(self):
        while (not self._exhausted
               and sum(1 for t in self.trials
                       if t.status in (PENDING, RUNNING))
               < self._num_concurrent):
            # suggest() is keyed by the SAME id later passed to
            # on_trial_complete — model-based searchers match the two to
            # attach the observation to the suggested config.
            trial_id = f"trial_{len(self.trials):04d}"
            cfg = self._searcher.suggest(trial_id)
            if cfg is None:
                self._exhausted = True
                break
            trial = Trial(trial_id, cfg)
            self.trials.append(trial)
            self._scheduler.on_trial_add(self, trial)
            self._start_trial(trial)

    def _should_stop_trial(self, result: Dict[str, Any]) -> bool:
        if result.get("done"):
            return True
        for key, bound in self._stop.items():
            if key == "training_iteration":
                if result.get(key, 0) >= bound:
                    return True
            elif key in result and result[key] >= bound:
                return True
        return False

    def _terminate(self, trial: Trial, status: str):
        trial.status = status
        if trial.actor is not None:
            try:
                # Graceful-then-force (reference: ray_trial_executor stop
                # sequence): wait briefly for Trainable.cleanup() to run
                # before the kill, or user teardown may never execute.
                stop_fut = trial.actor.stop.remote()
                try:
                    ray.get(stop_fut, timeout=5.0)
                except Exception:
                    pass
                ray.kill(trial.actor)
            except Exception:
                pass
            trial.actor = None

    # ---------------------------------------------------------------- loop
    def step(self):
        """One event-loop turn (reference: trial_runner.py:1315)."""
        self._maybe_add_trials()
        # Synchronous schedulers promote paused rungs here; must run
        # even with no futures in flight (a fully parked bracket would
        # otherwise spin forever).
        self._scheduler.on_step(self)
        if not self._future_to_trial:
            return
        done, _ = ray.wait(list(self._future_to_trial),
                           num_returns=1, timeout=10.0)
        for fut in done:
            trial = self._future_to_trial.pop(fut)
            try:
                result = ray.get(fut)
            except Exception as e:
                self._on_trial_error(trial, e)
                continue
            self._on_trial_result(trial, result)
        self._iterations += 1
        if self._ckpt_dir and self._ckpt_every and \
                self._iterations % self._ckpt_every == 0:
            self.save_experiment()

    def _on_trial_result(self, trial: Trial, result: Dict[str, Any]):
        rf = self._restore_futures.pop(trial.trial_id, None)
        if rf is not None:
            # The result arrived AFTER the restore (per-actor FIFO), so
            # this future is done; surface a failed checkpoint load as a
            # trial error — the result came from an UNRESTORED model.
            try:
                ray.get(rf, timeout=5.0)
            except Exception as e:
                self._on_trial_error(trial, e)
                return
        trial.last_result = result
        trial.results.append(result)
        # Checkpoint after every boundary so ASHA-stops and PBT-exploits
        # always have state to clone (perf: make configurable).
        try:
            trial.latest_checkpoint = ray.get(trial.actor.save.remote())
        except Exception:
            pass
        decision = self._scheduler.on_trial_result(self, trial, result)
        if self._should_stop_trial(result) or decision == STOP:
            self._scheduler.on_trial_complete(self, trial, result)
            self._searcher.on_trial_complete(trial.trial_id, result)
            self._terminate(trial, TERMINATED)
            return
        if decision == PAUSE:
            # Checkpoint already saved above; release the actor — the
            # scheduler promotes (unpause_trial) or stops the trial on a
            # later on_step.
            self._terminate(trial, PAUSED)
            return
        if trial.pending_restore is not None:
            blob, new_config = trial.pending_restore
            trial.pending_restore = None
            trial.config = new_config
            # Reuse actor if reset_config supports it, else replace.
            ok = False
            try:
                ok = ray.get(trial.actor.reset.remote(new_config))
            except Exception:
                ok = False
            if not ok:
                self._terminate(trial, PENDING)
                trial.latest_checkpoint = blob
                self._start_trial(trial)
                return
            ray.get(trial.actor.restore.remote(blob))
        self._future_to_trial[trial.actor.train.remote()] = trial

    def _on_trial_error(self, trial: Trial, err: Exception):
        if trial.retries < self._max_failures:
            trial.retries += 1
            self._terminate(trial, PENDING)
            self._start_trial(trial)  # restores latest_checkpoint
            return
        trial.error = str(err)
        # Synchronous schedulers must learn the trial is gone, or a
        # bracket would wait forever for its rung report.
        self._scheduler.on_trial_complete(self, trial, trial.last_result)
        self._terminate(trial, ERRORED)

    def is_finished(self) -> bool:
        return self._exhausted and not self._future_to_trial and all(
            t.status in (TERMINATED, ERRORED) for t in self.trials)

    def run(self):
        while not self.is_finished():
            self.step()
        if self._ckpt_dir:
            self.save_experiment()

    # ------------------------------------------------------ exp checkpoint
    def save_experiment(self):
        """Experiment-level checkpoint for resume (reference:
        TrialRunner.checkpoint + tune resume)."""
        os.makedirs(self._ckpt_dir, exist_ok=True)
        state = []
        for t in self.trials:
            state.append({
                "trial_id": t.trial_id, "config": t.config,
                "status": t.status, "last_result": t.last_result,
                "results": t.results, "error": t.error,
                "checkpoint": t.latest_checkpoint,
            })
        tmp = os.path.join(self._ckpt_dir, ".experiment_state.tmp")
        with open(tmp, "wb") as f:
            pickle.dump(state, f)
        os.replace(tmp, os.path.join(self._ckpt_dir, "experiment_state.pkl"))
        with open(os.path.join(self._ckpt_dir, "experiment_meta.json"),
                  "w") as f:
            json.dump({"num_trials": len(self.trials),
                       "time": time.time()}, f)

    def restore_experiment(self) -> int:
        """Re-load trial states; unfinished trials restart from their last
        checkpoint.  Returns number of restored trials."""
        path = os.path.join(self._ckpt_dir, "experiment_state.pkl")
        if not os.path.exists(path):
            return 0
        with open(path, "rb") as f:
            state = pickle.load(f)
        for st in state:
            t = Trial(st["trial_id"], st["config"])
            t.last_result = st["last_result"]
            t.results = st["results"]
            t.error = st["error"]
            t.latest_checkpoint = st["checkpoint"]
            t.status = st["status"]
            self.trials.append(t)
            if t.status not in (TERMINATED, ERRORED):
                t.status = PENDING
                self._start_trial(t)
        return len(state)
