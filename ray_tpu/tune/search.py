"""Search spaces and trial generation.

Reference: ``python/ray/tune/search/sample.py`` (Domain/Categorical/Float/
grid_search) and ``search/basic_variant.py`` (BasicVariantGenerator: grid
cross-product x num_samples random draws).  External searcher adapters
(Optuna/HyperOpt/...) plug in via the same Searcher interface
(``search/searcher.py``).
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Callable, Dict, Iterator, List, Optional


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Categorical(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Float(Domain):
    def __init__(self, lower, upper, log=False):
        self.lower, self.upper, self.log = lower, upper, log

    def sample(self, rng):
        import math
        if self.log:
            return math.exp(rng.uniform(math.log(self.lower),
                                        math.log(self.upper)))
        return rng.uniform(self.lower, self.upper)


class Integer(Domain):
    def __init__(self, lower, upper):
        self.lower, self.upper = lower, upper

    def sample(self, rng):
        return rng.randrange(self.lower, self.upper)


class GridSearch:
    def __init__(self, values):
        self.values = list(values)


def choice(categories) -> Categorical:
    return Categorical(categories)


def uniform(lower, upper) -> Float:
    return Float(lower, upper)


def loguniform(lower, upper) -> Float:
    return Float(lower, upper, log=True)


def randint(lower, upper) -> Integer:
    return Integer(lower, upper)


def grid_search(values) -> GridSearch:
    return GridSearch(values)


class sample_from:
    """Explicit marker for config values sampled by calling a function
    (reference: tune.sample_from).  Bare callables in a param space are
    passed through untouched — they are often legitimate values, e.g. an
    env constructor."""

    def __init__(self, fn: Callable[[], Any]):
        self.fn = fn


class Searcher:
    """Pluggable suggestion interface (reference: search/searcher.py)."""

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: Dict[str, Any]):
        pass


class BasicVariantGenerator(Searcher):
    """Grid cross-product x num_samples random draws (reference:
    search/basic_variant.py)."""

    def __init__(self, space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None):
        self._rng = random.Random(seed)
        self._space = space
        grid_keys = [k for k, v in space.items()
                     if isinstance(v, GridSearch)]
        grids = [space[k].values for k in grid_keys]
        self._grid_points = [dict(zip(grid_keys, combo))
                             for combo in itertools.product(*grids)] \
            if grid_keys else [{}]
        self._num_samples = num_samples
        self._iter = self._generate()

    def _generate(self) -> Iterator[Dict[str, Any]]:
        for _ in range(self._num_samples):
            for grid_point in self._grid_points:
                cfg = {}
                for k, v in self._space.items():
                    if isinstance(v, GridSearch):
                        cfg[k] = grid_point[k]
                    elif isinstance(v, Domain):
                        cfg[k] = v.sample(self._rng)
                    elif isinstance(v, sample_from):
                        cfg[k] = v.fn()
                    else:
                        cfg[k] = v
                yield cfg

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        try:
            return next(self._iter)
        except StopIteration:
            return None
