"""Search spaces and trial generation.

Reference: ``python/ray/tune/search/sample.py`` (Domain/Categorical/Float/
grid_search) and ``search/basic_variant.py`` (BasicVariantGenerator: grid
cross-product x num_samples random draws).  External searcher adapters
(Optuna/HyperOpt/...) plug in via the same Searcher interface
(``search/searcher.py``).
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Callable, Dict, Iterator, List, Optional


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Categorical(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Float(Domain):
    def __init__(self, lower, upper, log=False):
        self.lower, self.upper, self.log = lower, upper, log

    def sample(self, rng):
        import math
        if self.log:
            return math.exp(rng.uniform(math.log(self.lower),
                                        math.log(self.upper)))
        return rng.uniform(self.lower, self.upper)


class Integer(Domain):
    def __init__(self, lower, upper):
        self.lower, self.upper = lower, upper

    def sample(self, rng):
        return rng.randrange(self.lower, self.upper)


class GridSearch:
    def __init__(self, values):
        self.values = list(values)


def choice(categories) -> Categorical:
    return Categorical(categories)


def uniform(lower, upper) -> Float:
    return Float(lower, upper)


def loguniform(lower, upper) -> Float:
    return Float(lower, upper, log=True)


def randint(lower, upper) -> Integer:
    return Integer(lower, upper)


def grid_search(values) -> GridSearch:
    return GridSearch(values)


class sample_from:
    """Explicit marker for config values sampled by calling a function
    (reference: tune.sample_from).  Bare callables in a param space are
    passed through untouched — they are often legitimate values, e.g. an
    env constructor."""

    def __init__(self, fn: Callable[[], Any]):
        self.fn = fn


class Searcher:
    """Pluggable suggestion interface (reference: search/searcher.py)."""

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: Dict[str, Any]):
        pass


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator (the algorithm behind HyperOpt /
    Optuna's default sampler; reference surface: tune's external
    searcher adapters, search/hyperopt + search/optuna).  Self-contained
    so model-based search works with no extra dependency.

    After ``n_startup`` random trials, observations split into good/bad
    by the ``gamma`` quantile; numeric dims model each side with a
    Parzen (Gaussian-kernel) density and the suggestion maximizes
    l(x)/g(x) over ``n_candidates`` draws from the good side;
    categorical dims use smoothed per-side frequencies."""

    def __init__(self, space: Dict[str, Any], metric: str = "score",
                 mode: str = "max", num_samples: int = 64,
                 n_startup: int = 10, gamma: float = 0.25,
                 n_candidates: int = 24, seed: Optional[int] = None):
        self._space = space
        self._metric = metric
        self._mode = mode
        self._budget = num_samples
        self._n_startup = n_startup
        self._gamma = gamma
        self._n_candidates = n_candidates
        self._rng = random.Random(seed)
        self._suggested = 0
        self._pending: Dict[str, Dict[str, Any]] = {}
        self._observed: List[tuple] = []  # (config, value)

    # -- densities ---------------------------------------------------------
    def _numeric_dims(self):
        return {k: v for k, v in self._space.items()
                if isinstance(v, (Float, Integer))}

    @staticmethod
    def _to_unit(dom, x: float) -> float:
        import math

        if isinstance(dom, Float) and dom.log:
            lo, hi = math.log(dom.lower), math.log(dom.upper)
            return (math.log(x) - lo) / (hi - lo)
        lo, hi = dom.lower, dom.upper
        return (x - lo) / (hi - lo)

    @staticmethod
    def _from_unit(dom, u: float):
        import math

        u = min(1.0, max(0.0, u))
        if isinstance(dom, Float) and dom.log:
            lo, hi = math.log(dom.lower), math.log(dom.upper)
            return math.exp(lo + u * (hi - lo))
        val = dom.lower + u * (dom.upper - dom.lower)
        if isinstance(dom, Integer):
            return min(dom.upper - 1, max(dom.lower, int(round(val))))
        return val

    def _parzen(self, points: List[float]):
        """Gaussian-mixture density over unit-interval points; bandwidth
        by Silverman's rule with a floor so single points still spread."""
        import math

        n = len(points)
        mean = sum(points) / n
        var = sum((p - mean) ** 2 for p in points) / max(1, n - 1)
        bw = max(0.08, 1.06 * math.sqrt(var + 1e-12) * n ** -0.2)

        def pdf(x: float) -> float:
            return sum(math.exp(-0.5 * ((x - p) / bw) ** 2)
                       for p in points) / (n * bw)

        def sample() -> float:
            p = self._rng.choice(points)
            return p + self._rng.gauss(0.0, bw)

        return pdf, sample

    def _random_config(self) -> Dict[str, Any]:
        cfg = {}
        for k, v in self._space.items():
            if isinstance(v, Domain):
                cfg[k] = v.sample(self._rng)
            elif isinstance(v, sample_from):
                cfg[k] = v.fn()
            elif isinstance(v, GridSearch):
                cfg[k] = self._rng.choice(v.values)
            else:
                cfg[k] = v
        return cfg

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._suggested >= self._budget:
            return None
        self._suggested += 1
        if len(self._observed) < self._n_startup:
            cfg = self._random_config()
            self._pending[trial_id] = cfg
            return cfg
        import math

        ranked = sorted(self._observed, key=lambda cv: cv[1],
                        reverse=True)
        n_good = max(2, int(math.ceil(self._gamma * len(ranked))))
        good = [c for c, _ in ranked[:n_good]]
        bad = [c for c, _ in ranked[n_good:]] or good
        cfg = self._random_config()  # non-numeric dims + fallback
        for k, dom in self._numeric_dims().items():
            g_pts = [self._to_unit(dom, c[k]) for c in good if k in c]
            b_pts = [self._to_unit(dom, c[k]) for c in bad if k in c]
            if not g_pts or not b_pts:
                continue
            l_pdf, l_sample = self._parzen(g_pts)
            g_pdf, _ = self._parzen(b_pts)
            best_u, best_ratio = None, -1.0
            for _ in range(self._n_candidates):
                u = min(1.0, max(0.0, l_sample()))
                ratio = l_pdf(u) / (g_pdf(u) + 1e-12)
                if ratio > best_ratio:
                    best_ratio, best_u = ratio, u
            cfg[k] = self._from_unit(dom, best_u)
        for k, v in self._space.items():
            if isinstance(v, Categorical):
                counts_g = {c: 1.0 for c in v.categories}  # +1 smoothing
                counts_b = {c: 1.0 for c in v.categories}
                for c in good:
                    if k in c:
                        counts_g[c[k]] = counts_g.get(c[k], 1.0) + 1
                for c in bad:
                    if k in c:
                        counts_b[c[k]] = counts_b.get(c[k], 1.0) + 1
                cfg[k] = max(v.categories,
                             key=lambda cat: counts_g[cat]
                             / counts_b[cat])
        self._pending[trial_id] = cfg
        return cfg

    def on_trial_complete(self, trial_id: str, result: Dict[str, Any]):
        cfg = self._pending.pop(trial_id, None)
        v = (result or {}).get(self._metric)
        if cfg is None or v is None:
            return
        v = float(v) if self._mode == "max" else -float(v)
        self._observed.append((cfg, v))


class OptunaSearch(Searcher):
    """Adapter for an external Optuna study (reference:
    search/optuna/optuna_search.py).  Optional dependency: raises at
    construction when optuna is absent."""

    def __init__(self, space: Dict[str, Any], metric: str = "score",
                 mode: str = "max", num_samples: int = 64,
                 seed: Optional[int] = None):
        try:
            import optuna
        except ImportError as e:
            raise ImportError(
                "OptunaSearch needs the 'optuna' package; use "
                "TPESearcher for the built-in equivalent") from e
        self._optuna = optuna
        self._space = space
        self._metric = metric
        self._mode = mode
        self._budget = num_samples
        self._suggested = 0
        sampler = optuna.samplers.TPESampler(seed=seed)
        self._study = optuna.create_study(
            direction="maximize" if mode == "max" else "minimize",
            sampler=sampler)
        self._trials: Dict[str, Any] = {}

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._suggested >= self._budget:
            return None
        self._suggested += 1
        ot = self._study.ask()
        cfg = {}
        for k, v in self._space.items():
            if isinstance(v, Float):
                cfg[k] = ot.suggest_float(k, v.lower, v.upper, log=v.log)
            elif isinstance(v, Integer):
                cfg[k] = ot.suggest_int(k, v.lower, v.upper - 1)
            elif isinstance(v, Categorical):
                cfg[k] = ot.suggest_categorical(k, v.categories)
            elif isinstance(v, sample_from):
                cfg[k] = v.fn()
            else:
                cfg[k] = v
        self._trials[trial_id] = ot
        return cfg

    def on_trial_complete(self, trial_id: str, result: Dict[str, Any]):
        ot = self._trials.pop(trial_id, None)
        v = (result or {}).get(self._metric)
        if ot is None or v is None:
            return
        self._study.tell(ot, float(v))


class BasicVariantGenerator(Searcher):
    """Grid cross-product x num_samples random draws (reference:
    search/basic_variant.py)."""

    def __init__(self, space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None):
        self._rng = random.Random(seed)
        self._space = space
        grid_keys = [k for k, v in space.items()
                     if isinstance(v, GridSearch)]
        grids = [space[k].values for k in grid_keys]
        self._grid_points = [dict(zip(grid_keys, combo))
                             for combo in itertools.product(*grids)] \
            if grid_keys else [{}]
        self._num_samples = num_samples
        self._iter = self._generate()

    def _generate(self) -> Iterator[Dict[str, Any]]:
        for _ in range(self._num_samples):
            for grid_point in self._grid_points:
                cfg = {}
                for k, v in self._space.items():
                    if isinstance(v, GridSearch):
                        cfg[k] = grid_point[k]
                    elif isinstance(v, Domain):
                        cfg[k] = v.sample(self._rng)
                    elif isinstance(v, sample_from):
                        cfg[k] = v.fn()
                    else:
                        cfg[k] = v
                yield cfg

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        try:
            return next(self._iter)
        except StopIteration:
            return None
