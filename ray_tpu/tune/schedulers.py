"""Trial schedulers: early stopping and population-based training.

Reference: ``python/ray/tune/schedulers/`` — FIFO (default), ASHA
(``async_hyperband.py``), PBT (``pbt.py``).  Interface mirrors
``TrialScheduler.on_trial_result -> CONTINUE | STOP`` plus PBT's
exploit/explore via trial checkpoints.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"
PAUSE = "PAUSE"


class TrialScheduler:
    def on_trial_result(self, runner, trial, result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, runner, trial, result: Dict[str, Any]):
        pass

    def on_trial_add(self, runner, trial):
        """Called when the runner starts a new trial (reference:
        TrialScheduler.on_trial_add)."""

    def on_step(self, runner):
        """Called once per runner loop turn — synchronous schedulers
        promote paused trials here (reference: choose_trial_to_run)."""


class FIFOScheduler(TrialScheduler):
    pass


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (reference: schedulers/async_hyperband.py): successive-halving
    rungs; a trial reaching a rung survives only if in the top 1/rf of
    completed results at that rung."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 4.0, brackets: int = 1):
        self._metric = metric
        self._mode = mode
        self._max_t = max_t
        self._grace = grace_period
        self._rf = reduction_factor
        # rung milestones: grace * rf^k below max_t
        self._milestones: List[int] = []
        t = grace_period
        while t < max_t:
            self._milestones.append(int(t))
            t *= reduction_factor
        self._rungs: Dict[int, List[float]] = {m: [] for m in self._milestones}

    def _val(self, result):
        v = result.get(self._metric)
        if v is None:
            return None
        return float(v) if self._mode == "max" else -float(v)

    def on_trial_result(self, runner, trial, result) -> str:
        t = result.get("training_iteration", 0)
        v = self._val(result)
        if v is None:
            return CONTINUE
        if t >= self._max_t:
            return STOP
        for m in self._milestones:
            if t == m:
                rung = self._rungs[m]
                rung.append(v)
                cutoff_idx = max(0, math.ceil(len(rung) / self._rf) - 1)
                cutoff = sorted(rung, reverse=True)[cutoff_idx]
                if v < cutoff:
                    return STOP
        return CONTINUE


class HyperBandScheduler(TrialScheduler):
    """Synchronous HyperBand (reference: schedulers/hyperband.py).

    Trials fill a bracket as they arrive; every bracket member PAUSES at
    the bracket's current milestone, and once the whole bracket is
    parked the top 1/eta are promoted (unpaused with an eta-times larger
    budget) while the rest stop — classic successive halving, but
    SYNCHRONOUS: promotion decisions see the complete rung, unlike
    ASHA's running cutoffs."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 max_t: int = 81, reduction_factor: float = 3.0,
                 bracket_size: int = 9, grace_period: int = 1):
        self._metric = metric
        self._mode = mode
        self._max_t = max_t
        self._eta = reduction_factor
        self._bracket_size = bracket_size
        self._grace = grace_period
        # bracket: {"trials": {tid: score}, "milestone": int,
        #           "paused": set, "done": set}
        self._brackets: List[Dict[str, Any]] = []
        self._trial_bracket: Dict[str, int] = {}

    def _val(self, result):
        v = result.get(self._metric)
        if v is None:
            return None
        return float(v) if self._mode == "max" else -float(v)

    def _bracket_of(self, trial) -> Dict[str, Any]:
        bi = self._trial_bracket.get(trial.trial_id)
        if bi is None:
            if (not self._brackets
                    or len(self._brackets[-1]["trials"])
                    >= self._bracket_size):
                self._brackets.append({
                    "trials": {}, "milestone": self._grace,
                    "paused": set(), "done": set()})
            bi = len(self._brackets) - 1
            self._trial_bracket[trial.trial_id] = bi
            self._brackets[bi]["trials"][trial.trial_id] = None
        return self._brackets[bi]

    def on_trial_add(self, runner, trial):
        # Membership binds at START: a promotion decision must see the
        # whole bracket, not just the trials that happened to report
        # first (a fast trial would otherwise get promoted alone).
        self._bracket_of(trial)

    def on_trial_result(self, runner, trial, result) -> str:
        br = self._bracket_of(trial)
        v = self._val(result)
        if v is not None:
            br["trials"][trial.trial_id] = v
        t = result.get("training_iteration", 0)
        if t >= self._max_t:
            br["done"].add(trial.trial_id)
            return STOP
        if t >= br["milestone"]:
            br["paused"].add(trial.trial_id)
            return PAUSE
        return CONTINUE

    def on_trial_complete(self, runner, trial, result):
        bi = self._trial_bracket.get(trial.trial_id)
        if bi is not None:
            self._brackets[bi]["done"].add(trial.trial_id)

    def on_step(self, runner):
        for br in self._brackets:
            live = set(br["trials"]) - br["done"]
            if not live or not live <= br["paused"]:
                continue  # someone still running (or bracket finished)
            # Whole rung parked: promote the top 1/eta.
            ranked = sorted(
                live,
                key=lambda tid: (br["trials"][tid]
                                 if br["trials"][tid] is not None
                                 else float("-inf")),
                reverse=True)
            keep = ranked[:max(1, math.ceil(len(ranked) / self._eta))]
            br["milestone"] = min(self._max_t,
                                  int(br["milestone"] * self._eta))
            for tid in ranked:
                trial = runner.get_trial(tid)
                if trial is None:
                    br["done"].add(tid)
                    continue
                if tid in keep:
                    br["paused"].discard(tid)
                    runner.unpause_trial(trial)
                else:
                    br["done"].add(tid)
                    br["paused"].discard(tid)
                    runner.stop_trial(trial)


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best result is worse than the median of other
    trials' running averages at the same step (reference:
    schedulers/median_stopping_rule.py)."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 grace_period: int = 4, min_samples_required: int = 3):
        self._metric = metric
        self._mode = mode
        self._grace = grace_period
        self._min_samples = min_samples_required
        # trial_id -> list of values (one per reported iteration)
        self._histories: Dict[str, List[float]] = {}

    def _val(self, result):
        v = result.get(self._metric)
        if v is None:
            return None
        return float(v) if self._mode == "max" else -float(v)

    def on_trial_result(self, runner, trial, result) -> str:
        v = self._val(result)
        if v is None:
            return CONTINUE
        hist = self._histories.setdefault(trial.trial_id, [])
        hist.append(v)
        t = result.get("training_iteration", len(hist))
        if t < self._grace:
            return CONTINUE
        # Other trials may trail this one (async execution): compare
        # against their running means over whatever they have reported,
        # floored at the grace period so one fast trial can still be
        # judged (reference computes the mean at step t; requiring
        # len(h) >= t would exempt the fastest trial forever).
        others = [h for tid, h in self._histories.items()
                  if tid != trial.trial_id and len(h) >= self._grace]
        if len(others) < self._min_samples:
            return CONTINUE
        running_means = sorted(
            sum(h[:t]) / min(t, len(h)) for h in others)
        median = running_means[len(running_means) // 2]
        if max(hist) < median:
            return STOP
        return CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: schedulers/pbt.py): at each perturbation interval,
    bottom-quantile trials clone the checkpoint of a top-quantile trial
    (exploit) and perturb its hyperparameters (explore)."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None):
        self._metric = metric
        self._mode = mode
        self._interval = perturbation_interval
        self._mutations = hyperparam_mutations or {}
        self._quantile = quantile_fraction
        self._resample_prob = resample_probability
        self._rng = random.Random(seed)
        self._last_scores: Dict[str, float] = {}
        self._last_perturb: Dict[str, int] = {}

    def _val(self, result):
        v = float(result[self._metric])
        return v if self._mode == "max" else -v

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        new = dict(config)
        for key, mut in self._mutations.items():
            if self._rng.random() < self._resample_prob or key not in new:
                if callable(mut):
                    new[key] = mut()
                elif isinstance(mut, list):
                    new[key] = self._rng.choice(mut)
            else:
                factor = self._rng.choice([0.8, 1.2])
                if isinstance(mut, list):
                    new[key] = self._rng.choice(mut)
                else:
                    new[key] = new[key] * factor
        return new

    def on_trial_result(self, runner, trial, result) -> str:
        if self._metric not in result:
            return CONTINUE
        t = result.get("training_iteration", 0)
        self._last_scores[trial.trial_id] = self._val(result)
        if t - self._last_perturb.get(trial.trial_id, 0) < self._interval:
            return CONTINUE
        self._last_perturb[trial.trial_id] = t
        scores = self._last_scores
        if len(scores) < 2:
            return CONTINUE
        ranked = sorted(scores, key=scores.get, reverse=True)
        k = max(1, int(len(ranked) * self._quantile))
        top, bottom = ranked[:k], ranked[-k:]
        if trial.trial_id not in bottom or trial.trial_id in top:
            return CONTINUE
        donor_id = self._rng.choice(top)
        donor = runner.get_trial(donor_id)
        if donor is None or donor.latest_checkpoint is None:
            return CONTINUE
        # Exploit + explore: runner clones donor checkpoint into this trial
        # with a mutated config (reference: pbt.py _exploit).
        new_config = self._explore(donor.config)
        runner.transfer_checkpoint(donor, trial, new_config)
        return CONTINUE
