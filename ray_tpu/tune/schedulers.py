"""Trial schedulers: early stopping and population-based training.

Reference: ``python/ray/tune/schedulers/`` — FIFO (default), ASHA
(``async_hyperband.py``), PBT (``pbt.py``).  Interface mirrors
``TrialScheduler.on_trial_result -> CONTINUE | STOP`` plus PBT's
exploit/explore via trial checkpoints.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    def on_trial_result(self, runner, trial, result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, runner, trial, result: Dict[str, Any]):
        pass


class FIFOScheduler(TrialScheduler):
    pass


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (reference: schedulers/async_hyperband.py): successive-halving
    rungs; a trial reaching a rung survives only if in the top 1/rf of
    completed results at that rung."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 4.0, brackets: int = 1):
        self._metric = metric
        self._mode = mode
        self._max_t = max_t
        self._grace = grace_period
        self._rf = reduction_factor
        # rung milestones: grace * rf^k below max_t
        self._milestones: List[int] = []
        t = grace_period
        while t < max_t:
            self._milestones.append(int(t))
            t *= reduction_factor
        self._rungs: Dict[int, List[float]] = {m: [] for m in self._milestones}

    def _val(self, result):
        v = result.get(self._metric)
        if v is None:
            return None
        return float(v) if self._mode == "max" else -float(v)

    def on_trial_result(self, runner, trial, result) -> str:
        t = result.get("training_iteration", 0)
        v = self._val(result)
        if v is None:
            return CONTINUE
        if t >= self._max_t:
            return STOP
        for m in self._milestones:
            if t == m:
                rung = self._rungs[m]
                rung.append(v)
                cutoff_idx = max(0, math.ceil(len(rung) / self._rf) - 1)
                cutoff = sorted(rung, reverse=True)[cutoff_idx]
                if v < cutoff:
                    return STOP
        return CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: schedulers/pbt.py): at each perturbation interval,
    bottom-quantile trials clone the checkpoint of a top-quantile trial
    (exploit) and perturb its hyperparameters (explore)."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None):
        self._metric = metric
        self._mode = mode
        self._interval = perturbation_interval
        self._mutations = hyperparam_mutations or {}
        self._quantile = quantile_fraction
        self._resample_prob = resample_probability
        self._rng = random.Random(seed)
        self._last_scores: Dict[str, float] = {}
        self._last_perturb: Dict[str, int] = {}

    def _val(self, result):
        v = float(result[self._metric])
        return v if self._mode == "max" else -v

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        new = dict(config)
        for key, mut in self._mutations.items():
            if self._rng.random() < self._resample_prob or key not in new:
                if callable(mut):
                    new[key] = mut()
                elif isinstance(mut, list):
                    new[key] = self._rng.choice(mut)
            else:
                factor = self._rng.choice([0.8, 1.2])
                if isinstance(mut, list):
                    new[key] = self._rng.choice(mut)
                else:
                    new[key] = new[key] * factor
        return new

    def on_trial_result(self, runner, trial, result) -> str:
        if self._metric not in result:
            return CONTINUE
        t = result.get("training_iteration", 0)
        self._last_scores[trial.trial_id] = self._val(result)
        if t - self._last_perturb.get(trial.trial_id, 0) < self._interval:
            return CONTINUE
        self._last_perturb[trial.trial_id] = t
        scores = self._last_scores
        if len(scores) < 2:
            return CONTINUE
        ranked = sorted(scores, key=scores.get, reverse=True)
        k = max(1, int(len(ranked) * self._quantile))
        top, bottom = ranked[:k], ranked[-k:]
        if trial.trial_id not in bottom or trial.trial_id in top:
            return CONTINUE
        donor_id = self._rng.choice(top)
        donor = runner.get_trial(donor_id)
        if donor is None or donor.latest_checkpoint is None:
            return CONTINUE
        # Exploit + explore: runner clones donor checkpoint into this trial
        # with a mutated config (reference: pbt.py _exploit).
        new_config = self._explore(donor.config)
        runner.transfer_checkpoint(donor, trial, new_config)
        return CONTINUE
