"""Opt-in runtime lock-order checker (the in-process TSAN-lite).

Enable with ``RAY_TPU_LOCKCHECK=1`` (log violations) or
``RAY_TPU_LOCKCHECK=raise`` (raise :class:`LockOrderError` at the
acquisition that closes a cycle) before importing ``ray_tpu``, or call
:func:`install` directly from a test.

What it does, lockdep-style:

- wraps ``threading.Lock`` / ``threading.RLock`` so every lock created
  after :func:`install` is a recording proxy.  Locks are grouped into
  CLASSES by creation site (``file:line``) — all per-connection locks
  minted on one line form one class, exactly how kernel lockdep groups
  lock instances;
- records, per thread, the set of held lock classes, and adds a directed
  edge A -> B to a global graph whenever B is acquired while A is held;
- on each new edge, checks the graph for a cycle.  A cycle means two code
  paths acquire the same lock classes in opposite orders — a potential
  deadlock even if this run never interleaved badly (that is the whole
  point: the schedule-independent check catches what timing-dependent
  tests miss);
- watches asyncio event loops registered via :func:`watch_loop`
  (worker_main's async-actor loop registers itself when lockcheck is on)
  and records any callback/coroutine step that blocks the loop longer
  than 50 ms — the async-actor analog of holding a lock across I/O.

Zero overhead when not installed: the runtime never imports this module
unless the env flag is set or a test asks for it.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Dict, List, Optional, Set, Tuple

logger = logging.getLogger("ray_tpu.lockcheck")

# Default threshold for the event-loop stall watch (seconds).
LOOP_STALL_THRESHOLD_S = 0.05

_real_Lock = threading.Lock
_real_RLock = threading.RLock


class LockOrderError(RuntimeError):
    """Two code paths acquire the same lock classes in opposite orders."""


class _State:
    """Global checker state; guarded by an UN-instrumented lock."""

    def __init__(self):
        self.mu = _real_Lock()
        self.edges: Dict[str, Set[str]] = {}       # site -> {site}
        self.violations: List[str] = []
        self.stalls: List[str] = []
        self.raise_on_cycle = False
        # thread-id -> [proxies currently held], keyed explicitly (not
        # thread-local) because a plain Lock may legitimately be RELEASED
        # on a different thread than acquired it (handoff patterns) and
        # the releasing thread must be able to clear the acquirer's entry.
        self.held_by: Dict[int, List["_LockProxy"]] = {}
        self.seen_cycles: Set[Tuple[str, ...]] = set()

    def held_snapshot(self, tid: int) -> list:
        with self.mu:
            return list(self.held_by.get(tid, ()))

    def push_held(self, tid: int, proxy: "_LockProxy"):
        with self.mu:
            self.held_by.setdefault(tid, []).append(proxy)

    def pop_held(self, tid: int, proxy: "_LockProxy"):
        with self.mu:
            held = self.held_by.get(tid)
            if held and proxy in held:
                held.remove(proxy)


_state: Optional[_State] = None
_installed = False


def _creation_site() -> str:
    """file:line of the frame that called Lock()/RLock(), skipping
    threading.py internals (Condition/Event allocate locks) and this
    module."""
    import sys

    frame = sys._getframe(2)
    skip = (os.sep + "threading.py", os.path.join("devtools", "lockcheck"))
    while frame is not None:
        filename = frame.f_code.co_filename
        if not any(s in filename for s in skip):
            return f"{filename}:{frame.f_lineno}"
        frame = frame.f_back
    return "<unknown>"


def _find_path(edges: Dict[str, Set[str]], src: str, dst: str
               ) -> Optional[List[str]]:
    """DFS path src -> dst in the acquisition graph, or None."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in edges.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


class _LockProxy:
    """Recording wrapper around a real lock primitive.

    Deliberately NOT exposing ``_release_save`` / ``_acquire_restore`` /
    ``_is_owned`` for plain Locks (Condition falls back to its portable
    implementations, which route through this proxy's acquire/release);
    the RLock proxy forwards them with bookkeeping (below).
    """

    _reentrant = False

    def __init__(self, real, site: str):
        self._real = real
        self._site = site
        # Thread currently holding this (plain) lock; cleared by release,
        # possibly from a DIFFERENT thread (lock-handoff patterns).
        self._held_tid = None

    # -- bookkeeping -------------------------------------------------------
    def _on_acquired(self):
        state = _state
        if state is None:
            return
        tid = threading.get_ident()
        for other in state.held_snapshot(tid):
            if other is not self:
                _record_edge(state, other._site, self._site)
        self._held_tid = tid
        state.push_held(tid, self)

    def _on_released(self):
        state = _state
        owner, self._held_tid = self._held_tid, None
        if state is None or owner is None:
            return
        state.pop_held(owner, self)

    # -- lock protocol -----------------------------------------------------
    def acquire(self, blocking=True, timeout=-1):
        got = self._real.acquire(blocking, timeout)
        if got:
            try:
                self._on_acquired()
            except LockOrderError:
                # raise_on_cycle mode: don't hand the caller a lock it
                # will never release (its `with` body is never entered).
                # _on_acquired raises BEFORE registering the hold, so the
                # real release is the only undo needed.
                self._real.release()
                raise
        return got

    def release(self):
        self._on_released()
        self._real.release()

    __enter__ = acquire

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.release()

    def locked(self):
        return self._real.locked()

    def _at_fork_reinit(self):
        # stdlib modules register this as an os fork handler
        # (concurrent.futures.thread does at import time).
        self._real._at_fork_reinit()
        self._held_tid = None

    def __repr__(self):
        return f"<lockcheck proxy for {self._real!r} @ {self._site}>"


class _RLockProxy(_LockProxy):
    _reentrant = True

    def __init__(self, real, site: str):
        super().__init__(real, site)
        # Per-thread reentry depth (dict ops are GIL-atomic; RLock
        # release is always same-thread, unlike plain Lock handoffs).
        # Edges are recorded only on the outermost acquisition — a
        # re-acquire adds no ordering information.
        self._depths: Dict[int, int] = {}

    def _on_acquired(self):
        tid = threading.get_ident()
        depth = self._depths.get(tid, 0)
        if depth:
            self._depths[tid] = depth + 1
            return
        state = _state
        if state is None:
            self._depths[tid] = 1
            return
        for other in state.held_snapshot(tid):
            if other is not self:
                _record_edge(state, other._site, self._site)
        self._depths[tid] = 1
        state.push_held(tid, self)

    def _on_released(self):
        tid = threading.get_ident()
        depth = self._depths.get(tid, 0)
        if depth == 0:
            return
        if depth > 1:
            self._depths[tid] = depth - 1
            return
        self._depths.pop(tid, None)
        state = _state
        if state is not None:
            state.pop_held(tid, self)

    def _at_fork_reinit(self):
        self._real._at_fork_reinit()
        self._depths = {}

    # threading.Condition probes these on its backing lock; forward with
    # held-set bookkeeping so wait() (full release) and the re-acquire are
    # reflected in the graph.
    def _is_owned(self):
        return self._real._is_owned()

    def _release_save(self):
        saved = self._real._release_save()
        tid = threading.get_ident()
        depth = self._depths.pop(tid, 0)
        if depth and _state is not None:
            _state.pop_held(tid, self)
        return (saved, depth)

    def _acquire_restore(self, saved):
        inner, depth = saved
        self._real._acquire_restore(inner)
        tid = threading.get_ident()
        self._depths[tid] = depth
        if depth and _state is not None:
            _state.push_held(tid, self)


def _record_edge(state: _State, frm: str, to: str):
    with state.mu:
        if to in state.edges.get(frm, ()):
            return  # known edge: any cycle it closes was reported then
        state.edges.setdefault(frm, set()).add(to)
        if frm == to:
            # Two distinct instances of one lock CLASS nested: their
            # relative order is schedule-dependent, so this is a
            # potential ABBA deadlock (lockdep flags the same).
            chain = [frm, to]
        else:
            path = _find_path(state.edges, to, frm)
            if path is None:
                return
            chain = path + [to]
        key = tuple(sorted(set(chain)))
        if key in state.seen_cycles:
            return
        state.seen_cycles.add(key)
        message = (
            "lock-order cycle (potential deadlock): "
            + " -> ".join(chain)
            + f" ; closing edge {frm} -> {to} acquired on thread "
            + threading.current_thread().name)
        state.violations.append(message)
        raise_it = state.raise_on_cycle
    logger.warning("%s", message)
    if raise_it:
        raise LockOrderError(message)


def _make_lock_factory(real_factory, proxy_cls):
    def factory():
        return proxy_cls(real_factory(), _creation_site())

    return factory


def install(raise_on_cycle: bool = False):
    """Start instrumenting newly created locks.  Idempotent; locks created
    before install stay un-instrumented (install early — the env-flag path
    runs at ``import ray_tpu`` time, before the runtime builds its locks).
    """
    global _state, _installed
    if _installed:
        if _state is not None:
            _state.raise_on_cycle = raise_on_cycle
        return
    _state = _State()
    _state.raise_on_cycle = raise_on_cycle
    threading.Lock = _make_lock_factory(_real_Lock, _LockProxy)
    threading.RLock = _make_lock_factory(_real_RLock, _RLockProxy)
    _installed = True


def uninstall():
    """Restore the real lock factories, detach the stall watch, and drop
    recorded state.  Locks already minted as proxies keep working (they
    wrap real locks); loops handed to watch_loop keep their asyncio debug
    flag (the loop may be gone), but stalls are no longer captured."""
    global _state, _installed, _stall_handler
    threading.Lock = _real_Lock
    threading.RLock = _real_RLock
    if _stall_handler is not None:
        logging.getLogger("asyncio").removeHandler(_stall_handler)
        _stall_handler = None
    _state = None
    _installed = False


def install_from_env():
    value = os.environ.get("RAY_TPU_LOCKCHECK", "")
    if value and value != "0":
        install(raise_on_cycle=(value == "raise"))


def enabled() -> bool:
    return _installed


def edges() -> Dict[str, Set[str]]:
    """Copy of the acquisition graph: creation-site -> {creation-site}."""
    if _state is None:
        return {}
    with _state.mu:
        return {k: set(v) for k, v in _state.edges.items()}


def violations() -> List[str]:
    if _state is None:
        return []
    with _state.mu:
        return list(_state.violations)


_leaf_registry_cache: Optional[Dict[str, str]] = None


def leaf_registry(refresh: bool = False) -> Dict[str, str]:
    """``realpath:line -> lock name`` for every ``# lock-order: leaf``
    creation site, straight from the static analyzer (lockgraph is the
    one source of truth; this module keeps no leaf list of its own, so
    the static and dynamic checkers cannot disagree).  Cached: the
    static parse is ~seconds and this is debug tooling."""
    global _leaf_registry_cache
    if _leaf_registry_cache is None or refresh:
        from ray_tpu.devtools import lockgraph

        _leaf_registry_cache = lockgraph.leaf_sites()
    return _leaf_registry_cache


def leaf_violations() -> List[str]:
    """Observed runtime edges that LEAVE an annotated leaf lock — the
    dynamic counterpart of lockgraph RTL602.  Computed on demand (not in
    the acquire path) so recording stays cheap."""
    registry = leaf_registry()
    out = []
    for frm, tos in edges().items():
        name = registry.get(frm)
        if name is None:
            continue
        for to in sorted(tos):
            if to != frm:
                out.append(f"leaf lock '{name}' ({frm}) acquired {to} "
                           "while held — an annotated leaf must nest "
                           "nothing")
    return out


def export_graph() -> dict:
    """JSON-serializable dump of everything a cross-checking test
    needs: the observed acquisition edges, cycle + leaf violations, and
    the (static) leaf registry this checker consumes.  The lockgraph
    superset test asserts every observed edge between statically-known
    creation sites appears in the static graph."""
    return {
        "edges": sorted([frm, to] for frm, tos in edges().items()
                        for to in tos),
        "violations": violations(),
        "leaf_violations": leaf_violations(),
        "leaf_registry": dict(leaf_registry()),
    }


def stalls() -> List[str]:
    if _state is None:
        return []
    with _state.mu:
        return list(_state.stalls)


def clear():
    """Drop recorded edges/violations/stalls (keeps instrumentation)."""
    if _state is None:
        return
    with _state.mu:
        _state.edges.clear()
        _state.violations.clear()
        _state.stalls.clear()
        _state.seen_cycles.clear()


def assert_acyclic():
    """Raise LockOrderError if any cycle was recorded (test helper)."""
    if _state is None:
        return
    with _state.mu:
        if _state.violations:
            raise LockOrderError("; ".join(_state.violations))


class _StallHandler(logging.Handler):
    """Captures asyncio-debug 'Executing ... took N seconds' records."""

    def emit(self, record):
        try:
            message = record.getMessage()
        except Exception:
            return
        if "took" not in message:
            return
        state = _state
        if state is not None:
            with state.mu:
                state.stalls.append(message)
        logger.warning("event-loop stall: %s", message)


_stall_handler: Optional[_StallHandler] = None


def watch_loop(loop, threshold_s: float = LOOP_STALL_THRESHOLD_S):
    """Record callbacks/coroutine steps that block ``loop`` longer than
    ``threshold_s`` (asyncio's debug slow-callback machinery does the
    timing; we capture its report).  Used by worker_main for the async
    actor loop when lockcheck is enabled."""
    global _stall_handler
    loop.set_debug(True)
    loop.slow_callback_duration = threshold_s
    if _stall_handler is None:
        _stall_handler = _StallHandler()
        logging.getLogger("asyncio").addHandler(_stall_handler)
