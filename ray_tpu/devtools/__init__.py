"""Developer tools for the ray_tpu framework itself.

Three correctness tools for the hand-rolled concurrency in the runtime
(a dozen ``threading.Lock``\\ s across ``shm_store`` / ``object_transfer`` /
``worker_main`` / ``node_agent``, plus asyncio actor loops) — the in-repo
analog of the tooling the Ray reference grew for the same class of code
(``ray.util.check_serializability``, TSAN CI jobs):

- :mod:`ray_tpu.devtools.lint` — AST-based framework linter with rules
  specific to this codebase (blocking ``get`` in ``async def``, lock
  acquisition outside ``with``, bare ``except:`` swallowing ``SystemExit``,
  closure-captured ``ObjectRef``/ndarray in ``@remote`` functions, ...).
  Run as ``python -m ray_tpu.devtools.lint ray_tpu/ tests/``.
- :mod:`ray_tpu.devtools.lockcheck` — opt-in runtime lock-order checker
  (``RAY_TPU_LOCKCHECK=1``): wraps ``threading.Lock``/``RLock``, records
  the per-thread acquisition graph, and flags cycles (potential deadlock)
  and event-loop stalls >50 ms in async actor handlers.
- :mod:`ray_tpu.devtools.serializability` —
  ``check_serializability(obj)``: walks closures/attributes/containers and
  pinpoints the exact non-serializable leaf with a path string (also wired
  into the ``@remote`` argument-pickling error path).
"""

from ray_tpu.devtools.serializability import (  # noqa: F401 (public API)
    check_serializability,
    find_unserializable,
)

__all__ = ["check_serializability", "find_unserializable"]
