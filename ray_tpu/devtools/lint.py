"""Framework-aware AST linter for the ray_tpu codebase.

Pattern-matches the concurrency and serialization traps this runtime has
actually been bitten by (flaky tier-1 timeouts, event-loop stalls,
pickling errors surfacing three frames from their cause) and fails fast
in CI instead.  The reference grew the same class of tooling once its
hand-rolled concurrency crossed the size where review alone stops working
(``ray.util.check_serializability``, TSAN jobs).

Usage::

    python -m ray_tpu.devtools.lint ray_tpu/ tests/
    python -m ray_tpu.devtools.lint --list-rules
    python -m ray_tpu.devtools.lint --select=RTL402 ray_tpu/   # one rule
    python -m ray_tpu.devtools.lint --doc                      # rule table

Whole-program rules (RTL5xx — wire-protocol conformance, capability
gating, knob plumbing, lock-order inference) live in the sibling
``ray_tpu.devtools.protocheck``.

Findings print as ``path:line:col: RTLxxx message`` and the process exits
non-zero when any un-suppressed finding remains.

Suppression: append ``# noqa: RTL401`` (comma-separated rule IDs, with an
optional ``-- rationale`` tail) to the flagged line.  A bare ``# noqa``
does NOT suppress framework rules — every suppression names what it
silences and should carry a reason.

Rule catalog
============

RTL101  blocking-get-in-async
    ``ray_tpu.get()`` / ``ray.get()`` / ``.wait()`` / ``ref.get()`` /
    ``get_objects()`` called directly inside an ``async def``.  These block
    the whole event loop, stalling every other coroutine sharing it (all
    other async actor methods, every HTTP request on a proxy).  Await the
    ref, or push the call into an executor
    (``await loop.run_in_executor(None, lambda: ray_tpu.get(ref))``).

RTL102  sleep-in-async
    ``time.sleep()`` inside an ``async def``.  Blocks the event loop; use
    ``await asyncio.sleep()``.

RTL103  sleep-in-handler
    ``time.sleep()`` inside a protocol/message handler (a function named
    ``handle*`` / ``on_*`` / ``*_handler`` / ``serve_connection``).
    Handlers run on shared reader/dispatch threads; sleeping stalls every
    message queued behind this one.

RTL201  remote-closure-capture
    A ``@ray_tpu.remote`` function closure-captures a variable that holds
    an ``ObjectRef`` or a (potentially large) ndarray from an enclosing
    scope.  Captured refs are serialized by value into every submitted
    task and silently pin the object; captured arrays re-ship with every
    call.  Pass them as task arguments instead.

RTL301  bare-except
    ``except:`` with no exception class and no re-raise.  Swallows
    ``SystemExit`` / ``KeyboardInterrupt`` — worker/agent loops rely on
    ``SystemExit`` propagating for clean kills.  Catch ``Exception``.

RTL401  lock-acquire-no-with
    ``.acquire()`` called on a lock outside a ``with`` statement.  An
    exception between ``acquire`` and ``release`` leaks the lock and
    deadlocks the next acquirer.  Use ``with lock:``; non-blocking /
    timeout try-locks (``acquire(False)``, ``acquire(timeout=...)``) are
    exempt because ``with`` cannot express them.

RTL403  raw-recv-outside-deadline-core
    A raw connection/socket receive (``conn.recv_bytes()``,
    ``conn.recv_bytes_into()``, ``sock.recv()``) anywhere outside the
    deadline-aware protocol core.  Raw receives bypass the
    failure-detection plane entirely: no zero-progress deadline can ever
    trip, so a stalled-but-alive peer (gray failure) wedges the calling
    thread forever.  Go through ``protocol.recv`` / ``protocol.
    recv_deadline``, or arm the socket with ``protocol.
    set_conn_deadline`` around the raw loop (the object-transfer range
    loops do this) and suppress with the reason.

RTL402  blocking-io-under-runtime-lock
    A blocking socket operation (``protocol.send/recv``,
    ``*.send_bytes/recv_bytes``, ``conn/agent/worker.send/recv``) or a
    payload (un)pickle (``pickle.dumps/loads``,
    ``serialization.dumps*/loads*``) lexically inside a ``with
    self.lock:`` / ``with self._lock:`` body.  Table locks serialize the
    whole runtime: one slow peer's TCP buffer or one multi-MB pickle
    under the lock stalls EVERY submit/result/free on the head — exactly
    the contention class the decentralized-dispatch refactor removes.
    Buffer through the conflation sender (``_queue_send``) or move the
    work outside the critical section.  Lexical heuristic only: calls
    reached from a locked section through another function are not seen.
"""

from __future__ import annotations

import ast
import os
import re
import symtable
import sys
from typing import Dict, List, Optional, Set, Tuple

RULES: Dict[str, str] = {
    "RTL101": "blocking get/wait inside 'async def' stalls the event loop",
    "RTL102": "time.sleep inside 'async def' stalls the event loop",
    "RTL103": "time.sleep inside a protocol handler stalls the dispatch "
              "thread",
    "RTL201": "@remote function closure-captures an ObjectRef/ndarray",
    "RTL301": "bare 'except:' swallows SystemExit/KeyboardInterrupt",
    "RTL401": "lock .acquire() outside 'with' leaks the lock on error "
              "paths",
    "RTL402": "blocking socket send/recv or payload (un)pickling while "
              "holding a runtime lock stalls every other acquirer",
    "RTL403": "raw conn/sock receive outside the deadline-aware protocol "
              "core can hang forever on a stalled peer",
}

# RTL402: any lock-named with-target is a runtime/table lock the rule
# guards.  Locks that exist to guard a socket write (send_lock and
# friends — holding them across the send is the design) opt out with a
# structured `# lock-order: io-guard` annotation at the creation or
# binding site; lockgraph.py reads the same grammar, so the lexical and
# interprocedural checkers cannot disagree about which locks are exempt.
_RUNTIME_LOCK_RE = re.compile(r"(^|_)lock$")
_IO_GUARD_RE = re.compile(r"#\s*lock-order:\s*io-guard\b")
_LOCK_BIND_RE = re.compile(r"([A-Za-z_]\w*)\s*=")
# Receivers whose .send()/.recv() is a blocking socket call in this
# codebase (connection objects and the head-side peer handles).
_SOCKISH_RE = re.compile(r"conn|sock|agent|worker|lessee|peer|client")

_NOQA_RE = re.compile(r"#\s*noqa:\s*([A-Z0-9, ]+)", re.IGNORECASE)

_HANDLER_NAME_RE = re.compile(r"^_?(handle|on_[a-z])|_handler$")
_LOCKISH_RE = re.compile(r"lock|mutex|cond|(^|_)cv$|(^|_)sem($|_)")
_REFISH_RE = re.compile(r"(^|_)refs?($|_)|object_?ref", re.IGNORECASE)

# Names a module-level `import numpy as np` style alias may take; used to
# classify closure-captured array constructors.
_NDARRAY_ROOTS = {"np", "numpy", "jnp", "jax"}


class Finding:
    __slots__ = ("path", "line", "col", "rule", "message")

    def __init__(self, path: str, line: int, col: int, rule: str,
                 message: str):
        self.path = path
        self.line = line
        self.col = col
        self.rule = rule
        self.message = message

    def __repr__(self):
        return f"{self.path}:{self.line}:{self.col}: {self.rule} " \
               f"{self.message}"

    def __eq__(self, other):
        return (isinstance(other, Finding)
                and repr(self) == repr(other))


def _attr_chain(node: ast.AST) -> Optional[List[str]]:
    """['np', 'random', 'rand'] for np.random.rand, or None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return None


def _classify_value(value: ast.AST) -> Optional[str]:
    """What a closure-captured assignment binds: 'ObjectRef', 'ndarray',
    or None when it is not a capture hazard."""
    if not isinstance(value, ast.Call):
        return None
    chain = _attr_chain(value.func)
    if chain is None:
        return None
    if chain[-1] == "remote":
        return "ObjectRef"
    if chain in (["ray_tpu", "put"], ["ray", "put"]):
        return "ObjectRef"
    if chain[-1] == "ObjectRef":
        return "ObjectRef"
    if chain[0] in _NDARRAY_ROOTS and len(chain) > 1:
        return "ndarray"
    return None


def _is_remote_decorated(node: ast.AST) -> bool:
    for dec in getattr(node, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        chain = _attr_chain(target)
        if chain and chain[-1] in ("remote", "remote_decorator"):
            return True
    return False


class _Frame:
    __slots__ = ("kind", "name", "assigns")

    def __init__(self, kind: str, name: str):
        self.kind = kind  # 'module' | 'class' | 'func' | 'async' | 'lambda'
        self.name = name
        # name -> classification ('ObjectRef'/'ndarray') for closure
        # analysis; only hazardous bindings are recorded.
        self.assigns: Dict[str, Tuple[str, int]] = {}


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, tree: ast.Module,
                 table: Optional[symtable.SymbolTable],
                 io_guard: Optional[Set[str]] = None):
        self.path = path
        # Lock names annotated `# lock-order: io-guard` in this file:
        # exempt from RTL402 (they exist to be held across the write).
        self._io_guard: Set[str] = io_guard or set()
        self.findings: List[Finding] = []
        self.frames: List[_Frame] = [_Frame("module", "<module>")]
        # symtable function blocks keyed by (name, first line) so free
        # variables of @remote functions come from the real symbol table
        # instead of a hand-rolled scope walk.
        self.blocks: Dict[Tuple[str, int], symtable.SymbolTable] = {}
        if table is not None:
            self._index_blocks(table)
        self.time_aliases: Set[str] = {"time"}
        self.sleep_aliases: Set[str] = set()
        # RTL402: lexical nesting depth inside `with <runtime lock>:`
        # bodies (reset inside nested function defs — their bodies run at
        # call time, not under this acquisition).
        self._lock_depth = 0
        self._collect_imports(tree)

    # -- setup -------------------------------------------------------------
    def _index_blocks(self, table: symtable.SymbolTable):
        for child in table.get_children():
            if child.get_type() == "function":
                self.blocks[(child.get_name(), child.get_lineno())] = child
            self._index_blocks(child)

    def _collect_imports(self, tree: ast.Module):
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "time":
                        self.time_aliases.add(alias.asname or "time")
            elif isinstance(node, ast.ImportFrom):
                if node.module == "time":
                    for alias in node.names:
                        if alias.name == "sleep":
                            self.sleep_aliases.add(alias.asname or "sleep")

    # -- helpers -----------------------------------------------------------
    def _emit(self, node: ast.AST, rule: str, message: str):
        self.findings.append(
            Finding(self.path, node.lineno, node.col_offset, rule, message))

    def _nearest_function(self) -> Optional[_Frame]:
        for frame in reversed(self.frames):
            if frame.kind in ("func", "async", "lambda"):
                return frame
        return None

    def _enclosing_binding(self, name: str) -> Optional[Tuple[str, int]]:
        # Called from _check_remote_capture BEFORE the decorated
        # function's own frame is pushed, so the innermost frame on the
        # stack is already an ENCLOSING scope.
        for frame in reversed(self.frames):
            if frame.kind in ("func", "async", "lambda") \
                    and name in frame.assigns:
                return frame.assigns[name]
        return None

    def _is_time_sleep(self, call: ast.Call) -> bool:
        func = call.func
        if isinstance(func, ast.Attribute) and func.attr == "sleep" \
                and isinstance(func.value, ast.Name) \
                and func.value.id in self.time_aliases:
            return True
        return (isinstance(func, ast.Name)
                and func.id in self.sleep_aliases)

    # -- scope handling ----------------------------------------------------
    def _visit_function(self, node, kind: str):
        self._check_remote_capture(node)
        self.frames.append(_Frame(kind, node.name))
        saved_depth, self._lock_depth = self._lock_depth, 0
        try:
            for stmt in node.body:
                self.visit(stmt)
        finally:
            self.frames.pop()
            self._lock_depth = saved_depth

    def visit_FunctionDef(self, node: ast.FunctionDef):
        for dec in node.decorator_list:
            self.visit(dec)
        self._visit_function(node, "func")

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef):
        for dec in node.decorator_list:
            self.visit(dec)
        self._visit_function(node, "async")

    def visit_Lambda(self, node: ast.Lambda):
        self.frames.append(_Frame("lambda", "<lambda>"))
        # Like nested defs, a lambda's body runs at CALL time, not under
        # the enclosing with-lock acquisition (RTL402).
        saved_depth, self._lock_depth = self._lock_depth, 0
        try:
            self.visit(node.body)
        finally:
            self.frames.pop()
            self._lock_depth = saved_depth

    def visit_ClassDef(self, node: ast.ClassDef):
        self.frames.append(_Frame("class", node.name))
        try:
            self.generic_visit(node)
        finally:
            self.frames.pop()

    def visit_Assign(self, node: ast.Assign):
        frame = self.frames[-1]
        if frame.kind in ("func", "async"):
            kind = _classify_value(node.value)
            if kind:
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        frame.assigns[target.id] = (kind, node.lineno)
        self.generic_visit(node)

    # -- rules -------------------------------------------------------------
    def _check_remote_capture(self, node):
        """RTL201 — @remote function capturing refs/arrays by closure."""
        if not _is_remote_decorated(node):
            return
        block = self.blocks.get((node.name, node.lineno))
        if block is None or not isinstance(block, symtable.Function):
            return
        for free in block.get_frees():
            binding = self._enclosing_binding(free)
            if binding is None:
                continue
            kind, bind_line = binding
            self._emit(
                node, "RTL201",
                f"@remote function {node.name!r} closure-captures "
                f"{free!r} ({kind}, bound at line {bind_line}); captured "
                f"values are pickled into every submitted task — pass "
                f"{free!r} as a task argument instead")

    def visit_ExceptHandler(self, node: ast.ExceptHandler):
        if node.type is None:
            reraises = any(
                isinstance(sub, ast.Raise) and sub.exc is None
                for stmt in node.body for sub in ast.walk(stmt))
            if not reraises:
                self._emit(
                    node, "RTL301",
                    "bare 'except:' swallows SystemExit/KeyboardInterrupt "
                    "(worker kill paths rely on them propagating); catch "
                    "Exception instead")
        self.generic_visit(node)

    def _holds_runtime_lock(self, node) -> bool:
        for item in node.items:
            chain = _attr_chain(item.context_expr)
            if chain and _RUNTIME_LOCK_RE.search(chain[-1]) \
                    and chain[-1] not in self._io_guard:
                return True
        return False

    def visit_With(self, node: ast.With):
        held = self._holds_runtime_lock(node)
        if held:
            self._lock_depth += 1
        try:
            self.generic_visit(node)
        finally:
            if held:
                self._lock_depth -= 1

    def visit_AsyncWith(self, node: ast.AsyncWith):
        self.visit_With(node)

    def visit_Call(self, node: ast.Call):
        self._check_async_blocking(node)
        self._check_lock_acquire(node)
        self._check_lock_io(node)
        self._check_raw_recv(node)
        self.generic_visit(node)

    def _check_raw_recv(self, node: ast.Call):
        """RTL403 — raw connection/socket receive outside the
        deadline-aware protocol core.  ``recv_bytes``/``recv_bytes_into``
        on a connection-ish receiver, or ``recv`` on a socket-named one,
        can block forever on a stalled-but-alive peer; the deadline core
        (``protocol.recv``/``recv_deadline``/``set_conn_deadline``) is
        the one place that bounds them.  Deliberately-armed raw loops
        suppress with the arming site as the reason."""
        chain = _attr_chain(node.func)
        if not chain or len(chain) < 2:
            return
        leaf, owner = chain[-1], chain[-2]
        if leaf in ("recv_bytes", "recv_bytes_into") \
                and _SOCKISH_RE.search(owner.lower()):
            what = f"{owner}.{leaf}()"
        elif leaf == "recv" and "sock" in owner.lower():
            what = f"{owner}.{leaf}()"
        else:
            return
        self._emit(
            node, "RTL403",
            f"raw '{what}' bypasses the deadline-aware protocol core — "
            "a stalled (alive-but-hung) peer wedges this thread forever; "
            "use protocol.recv/recv_deadline, or arm "
            "protocol.set_conn_deadline around the loop and suppress "
            "with the arming site as the reason")

    def _check_lock_io(self, node: ast.Call):
        """RTL402 — blocking socket IO / payload pickling while a runtime
        lock is (lexically) held."""
        if self._lock_depth <= 0:
            return
        chain = _attr_chain(node.func)
        if not chain or len(chain) < 2:
            return
        leaf, owner = chain[-1], chain[-2]
        what = None
        if owner == "protocol" and leaf in ("send", "recv", "send_batch"):
            what = f"protocol.{leaf}()"
        elif leaf in ("send_bytes", "recv_bytes"):
            what = f"{owner}.{leaf}()"
        elif leaf in ("send", "recv") and _SOCKISH_RE.search(owner.lower()):
            what = f"{owner}.{leaf}()"
        elif owner == "pickle" and leaf in ("dumps", "loads"):
            what = f"pickle.{leaf}()"
        elif owner == "serialization" and (leaf.startswith("dumps")
                                           or leaf.startswith("loads")):
            what = f"serialization.{leaf}()"
        if what:
            self._emit(
                node, "RTL402",
                f"blocking '{what}' inside a 'with <runtime lock>:' body "
                "stalls every other lock acquirer — buffer via the "
                "conflation sender or move it outside the critical "
                "section")

    def _check_async_blocking(self, node: ast.Call):
        frame = self._nearest_function()
        in_async = frame is not None and frame.kind == "async"
        if self._is_time_sleep(node):
            if in_async:
                self._emit(node, "RTL102",
                           "time.sleep() blocks the event loop; use "
                           "'await asyncio.sleep()'")
            elif frame is not None and (
                    _HANDLER_NAME_RE.search(frame.name)
                    or frame.name == "serve_connection"):
                self._emit(
                    node, "RTL103",
                    f"time.sleep() in protocol handler {frame.name!r} "
                    "stalls every message queued on this dispatch thread")
            return
        if not in_async:
            return
        chain = _attr_chain(node.func)
        if chain is None:
            return
        blocking = None
        if chain[0] in ("ray_tpu", "ray") and len(chain) == 2 \
                and chain[1] in ("get", "wait"):
            blocking = ".".join(chain)
        elif chain[-1] == "get_objects":
            blocking = "get_objects"
        elif chain[-1] in ("get", "wait") and len(chain) >= 2 \
                and _REFISH_RE.search(chain[-2]) and not node.args:
            # Positional args mean a container lookup (`refs.get(key)`),
            # not a blocking ObjectRef get — those take no positionals.
            blocking = ".".join(chain[-2:])
        if blocking:
            self._emit(
                node, "RTL101",
                f"blocking '{blocking}()' inside 'async def' "
                f"{frame.name!r} stalls the event loop for every other "
                "coroutine; await the ref or use "
                "'await loop.run_in_executor(None, ...)'")

    def _check_lock_acquire(self, node: ast.Call):
        func = node.func
        if not (isinstance(func, ast.Attribute) and func.attr == "acquire"):
            return
        chain = _attr_chain(func.value)
        leaf = chain[-1] if chain else None
        if leaf is None or not _LOCKISH_RE.search(leaf.lower()):
            return
        # Try-locks are exempt: `with` cannot express acquire(False) /
        # acquire(timeout=...) / acquire(True, 0.5).
        if len(node.args) >= 2:
            return  # second positional is a timeout
        if node.args:
            first = node.args[0]
            if isinstance(first, ast.Constant) and first.value in (False, 0):
                return
        for kw in node.keywords:
            if kw.arg == "timeout":
                return
            if kw.arg == "blocking" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is False:
                return
        self._emit(
            node, "RTL401",
            f"'{leaf}.acquire()' outside 'with': an exception before the "
            "matching release() leaks the lock and deadlocks the next "
            f"acquirer — use 'with {leaf}:'")


def _io_guard_names(source: str) -> Set[str]:
    """Lock names annotated ``# lock-order: io-guard`` anywhere in the
    file — at the creation or forwarded-binding line, or on an
    annotation-only line directly above it (lockgraph's grammar)."""
    out: Set[str] = set()
    lines = source.splitlines()
    for i, line in enumerate(lines):
        if not _IO_GUARD_RE.search(line):
            continue
        bind = line if "=" in line.split("#", 1)[0] else (
            lines[i + 1] if i + 1 < len(lines) else "")
        for name in _LOCK_BIND_RE.findall(bind.split("#", 1)[0]):
            if _RUNTIME_LOCK_RE.search(name):
                out.add(name)
    return out


def _noqa_rules(line: str) -> Set[str]:
    match = _NOQA_RE.search(line)
    if not match:
        return set()
    # Split on commas AND whitespace: '# noqa: RTL401 lock handoff'
    # (rationale without the documented '--') must still suppress RTL401
    # — stray rationale words become harmless non-rule tokens.
    return {tok for tok in re.split(r"[\s,]+", match.group(1).upper())
            if tok}


def lint_source(source: str, path: str = "<string>") -> List[Finding]:
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as err:
        return [Finding(path, err.lineno or 0, err.offset or 0, "RTL000",
                        f"syntax error: {err.msg}")]
    try:
        table = symtable.symtable(source, path, "exec")
    except SyntaxError:
        table = None
    linter = _Linter(path, tree, table, _io_guard_names(source))
    linter.visit(tree)
    lines = source.splitlines()
    kept = []
    for finding in linter.findings:
        line = lines[finding.line - 1] if finding.line <= len(lines) else ""
        if finding.rule in _noqa_rules(line):
            continue
        kept.append(finding)
    kept.sort(key=lambda f: (f.line, f.col, f.rule))
    return kept


def lint_file(path: str) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as f:
        return lint_source(f.read(), path)


def _iter_py_files(paths) -> List[str]:
    out = []
    for path in paths:
        if os.path.isdir(path):
            for root, dirs, files in os.walk(path):
                # `lint_fixtures` holds this linter's own deliberately-bad
                # test corpus — excluded from directory walks so the
                # documented `lint ray_tpu/ tests/` invocation can go
                # green; naming a fixture file explicitly still lints it.
                dirs[:] = sorted(d for d in dirs
                                 if not d.startswith(".")
                                 and d != "__pycache__"
                                 and d != "lint_fixtures")
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if f.endswith(".py"))
        else:
            # Explicitly named files are linted regardless of extension —
            # silently skipping one would report a clean result for a
            # file that was never parsed.
            out.append(path)
    return out


def lint_paths(paths) -> List[Finding]:
    findings: List[Finding] = []
    for path in _iter_py_files(paths):
        findings.extend(lint_file(path))
    return findings


def rules_doc() -> str:
    """Markdown table of the per-file rule catalog (``--doc``)."""
    lines = ["| rule | what it catches |", "|---|---|"]
    for rule_id in sorted(RULES):
        lines.append(f"| {rule_id} | {RULES[rule_id]} |")
    return "\n".join(lines)


def run_cli(argv, *, rules, usage, runner, doc=None) -> int:
    """Shared CLI driver for the devtools analyzers (this linter and
    ``protocheck``): --list-rules, --doc, validated --select, the
    missing-path guard, and the findings print/exit tail live ONCE here
    so the two tools cannot drift.

    ``runner(paths, select)`` returns the (already select-filtered)
    finding list — or an int to take over the exit code (protocheck's
    ``--dump``)."""
    argv = list(argv)
    if "--list-rules" in argv:
        for rule_id in sorted(rules):
            print(f"{rule_id}  {rules[rule_id]}")
        return 0
    if doc is not None and "--doc" in argv:
        print(doc())
        return 0
    select = None
    for arg in list(argv):
        if arg.startswith("--select="):
            select = {s.strip().upper() for s in
                      arg.split("=", 1)[1].split(",") if s.strip()}
            argv.remove(arg)
    if select:
        # A typo'd selector must not filter every finding and report a
        # green run (prefix match is the contract: RTL4 = the family).
        unknown = sorted(s for s in select
                         if not any(r.startswith(s) for r in rules))
        if unknown:
            print(f"error: --select matches no rule: "
                  f"{', '.join(unknown)} (known: "
                  f"{', '.join(sorted(rules))})", file=sys.stderr)
            return 2
    paths = [a for a in argv if not a.startswith("-")]
    if not paths:
        print(usage, file=sys.stderr)
        return 2
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        # A typo'd path must not report a green "clean tree" it never
        # linted.
        print(f"error: no such path(s): {', '.join(missing)}",
              file=sys.stderr)
        return 2
    findings = runner(paths, select)
    if isinstance(findings, int):
        return findings
    for finding in findings:
        print(repr(finding))
    if findings:
        print(f"{len(findings)} finding(s). Suppress deliberate patterns "
              f"with '# noqa: <RULE-ID> -- reason'.", file=sys.stderr)
        return 1
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)

    def runner(paths, select):
        findings = lint_paths(paths)
        if select:
            # Prefix match so --select=RTL4 runs the whole lock family.
            findings = [f for f in findings
                        if any(f.rule.startswith(s) for s in select)]
        return findings

    return run_cli(
        argv, rules=RULES, doc=rules_doc, runner=runner,
        usage="usage: python -m ray_tpu.devtools.lint [--list-rules] "
              "[--doc] [--select=RTLxxx,...] PATH [PATH ...]")


if __name__ == "__main__":
    sys.exit(main())
