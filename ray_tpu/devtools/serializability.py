"""Pinpoint WHY an object fails to serialize, not just that it did.

Reference analog: ``ray.util.check_serializability`` /
``python/ray/util/serialization_addons.py`` — cloudpickle's error for a
deeply nested unpicklable leaf names the leaf's type but not where it
lives; on a 40-field trainer config captured by a closure that is a
20-minute hunt.  :func:`find_unserializable` walks closures, attributes,
and containers breadth-first and returns the PATH to the failing leaf
(e.g. ``arg[0].fn.__closure__['model']``), and
:func:`check_serializability` raises :class:`SerializationTrapError`
carrying it.  The ``@remote`` submit path calls this automatically when
argument pickling fails (remote_function.serialize_args).
"""

from __future__ import annotations

import inspect
from typing import Any, List, Optional, Tuple

from ray_tpu.exceptions import RayTpuError

# Breadth/depth caps: diagnosis must stay cheap even for pathological
# object graphs — this runs on an error path the user is already staring
# at, not in the hot loop.
_MAX_DEPTH = 20
_MAX_CHILDREN = 256


class SerializationTrapError(RayTpuError, TypeError):
    """An object graph contains an unserializable leaf.

    ``path`` names the exact leaf (e.g. ``arg[0].fn.__closure__['model']``)
    and ``leaf_repr`` its repr.  TypeError subclass for parity with the
    reference's pickling errors (``except TypeError`` keeps working).
    """

    def __init__(self, path: str, leaf_repr: str, cause_repr: str):
        self.path = path
        self.leaf_repr = leaf_repr
        self.cause_repr = cause_repr
        super().__init__(
            f"Cannot serialize {path}: {leaf_repr} ({cause_repr}). "
            f"Pass the value explicitly (task argument / actor state) or "
            f"exclude it from the closure.")

    def __reduce__(self):
        return (SerializationTrapError,
                (self.path, self.leaf_repr, self.cause_repr))


def _dumps_ok(obj: Any) -> Optional[Exception]:
    """None when ``obj`` pickles cleanly, else the error."""
    from ray_tpu._private import serialization

    try:
        serialization.dumps_inline(obj)
        return None
    except Exception as err:  # noqa: BLE001 — any failure is the answer
        return err


def _short(obj: Any) -> str:
    try:
        text = repr(obj)
    except Exception:
        text = f"<unreprable {type(obj).__name__}>"
    return text if len(text) <= 120 else text[:117] + "..."


def _children(obj: Any) -> List[Tuple[str, Any]]:
    """(path-suffix, child) pairs for one level of the object graph."""
    out: List[Tuple[str, Any]] = []
    if inspect.isfunction(obj) or inspect.ismethod(obj):
        fn = obj.__func__ if inspect.ismethod(obj) else obj
        closure = getattr(fn, "__closure__", None) or ()
        freevars = getattr(fn.__code__, "co_freevars", ())
        for name, cell in zip(freevars, closure):
            try:
                out.append((f".__closure__[{name!r}]", cell.cell_contents))
            except ValueError:
                pass  # empty cell
        for i, default in enumerate(getattr(fn, "__defaults__", None) or ()):
            out.append((f".__defaults__[{i}]", default))
        # Globals the function body references (cloudpickle captures these
        # by value for __main__/interactively defined functions).
        fn_globals = getattr(fn, "__globals__", {})
        for name in getattr(fn.__code__, "co_names", ()):
            if name in fn_globals:
                out.append((f".__globals__[{name!r}]", fn_globals[name]))
        return out[:_MAX_CHILDREN]
    if isinstance(obj, dict):
        for key, value in list(obj.items())[:_MAX_CHILDREN]:
            out.append((f"[{key!r}]" if isinstance(key, (str, bytes, int))
                        else f"[<key {_short(key)}>]", value))
            out.append((f"<key {_short(key)}>", key))
        return out
    if isinstance(obj, (list, tuple)):
        return [(f"[{i}]", value)
                for i, value in enumerate(obj[:_MAX_CHILDREN])]
    if isinstance(obj, (set, frozenset)):
        return [(f"<member {_short(value)}>", value)
                for value in list(obj)[:_MAX_CHILDREN]]
    state = getattr(obj, "__dict__", None)
    if isinstance(state, dict):
        out.extend((f".{name}", value)
                   for name, value in list(state.items())[:_MAX_CHILDREN])
    slots = getattr(type(obj), "__slots__", ())
    if isinstance(slots, str):
        slots = (slots,)
    for name in slots:
        try:
            out.append((f".{name}", getattr(obj, name)))
        except AttributeError:
            pass
    return out[:_MAX_CHILDREN]


def find_unserializable(obj: Any, name: str = "obj"
                        ) -> Optional[Tuple[str, Any, Exception]]:
    """Deepest unserializable leaf as ``(path, leaf, error)``, or None
    when ``obj`` serializes cleanly."""
    err = _dumps_ok(obj)
    if err is None:
        return None
    path, node = name, obj
    seen = {id(obj)}
    for _ in range(_MAX_DEPTH):
        for suffix, child in _children(node):
            if id(child) in seen:
                continue
            child_err = _dumps_ok(child)
            if child_err is not None:
                seen.add(id(child))
                path, node, err = path + suffix, child, child_err
                break
        else:
            break  # no failing child: `node` itself is the leaf
    return path, node, err


def diagnose_pickle_error(obj: Any, name: str, err: Exception) -> None:
    """Error-path upgrade for a pickling failure on ``obj``: when the walk
    confirms an unserializable leaf, raise :class:`SerializationTrapError`
    naming it (chained to ``err``); otherwise the failure had some other
    cause (store full, transient) — re-raise ``err`` untouched."""
    found = find_unserializable(obj, name)
    if found is None:
        raise err
    path, leaf, leaf_err = found
    raise SerializationTrapError(path, _short(leaf), repr(leaf_err)) from err


def check_serializability(obj: Any, name: str = "obj") -> None:
    """Raise :class:`SerializationTrapError` naming the exact leaf if
    ``obj`` (or anything reachable from it) cannot be cloudpickled;
    return None when it serializes cleanly.

    Reference parity: ``ray.util.check_serializability``.
    """
    found = find_unserializable(obj, name)
    if found is None:
        return
    path, leaf, err = found
    raise SerializationTrapError(path, _short(leaf), repr(err))
