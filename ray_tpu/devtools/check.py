"""One entry point for every static analyzer in the repo.

    python -m ray_tpu.devtools.check [PATH ...]

Runs, in order, over the same path set:

  1. ``lint``       — per-file pattern rules (RTL0xx-RTL4xx)
  2. ``protocheck`` — whole-program wire-protocol conformance (RTL5xx)
  3. ``lockgraph``  — whole-program static lock-graph rules (RTL6xx)

and exits with the MERGED status: 0 only when all three sweep clean,
1 when any analyzer produced findings, 2 on usage errors.  With no
paths, defaults to ``ray_tpu/`` and ``tests/`` — the exact invocation
the tier-1 clean-tree gates (test_lint_clean.py,
test_lockgraph_clean.py) keep green.

Per-analyzer flags (``--select``, ``--doc``, ``--dump``) live on the
individual CLIs; this runner takes only paths.
"""

import os
import sys
from typing import List, Optional, Tuple

from ray_tpu.devtools import lint, lockgraph, protocheck
from ray_tpu.devtools.lint import Finding

_USAGE = "usage: python -m ray_tpu.devtools.check [PATH ...]"


def _default_paths() -> List[str]:
    import ray_tpu

    pkg = os.path.dirname(os.path.abspath(ray_tpu.__file__))
    tests = os.path.join(os.path.dirname(pkg), "tests")
    return [pkg] + ([tests] if os.path.isdir(tests) else [])


def check_paths(paths) -> List[Tuple[str, Finding]]:
    """(analyzer name, finding) for every un-suppressed finding from
    every analyzer, in analyzer order then location order."""
    out: List[Tuple[str, Finding]] = []
    out.extend(("lint", f) for f in lint.lint_paths(paths))
    out.extend(("protocheck", f) for f in protocheck.check_paths(paths))
    out.extend(("lockgraph", f) for f in lockgraph.check_paths(paths))
    return out


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if any(a.startswith("-") for a in argv):
        print(_USAGE, file=sys.stderr)
        return 2
    paths = argv or _default_paths()
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"error: no such path: {', '.join(missing)}",
              file=sys.stderr)
        return 2
    findings = check_paths(paths)
    for name, f in findings:
        print(f"[{name}] {f!r}")
    if findings:
        print(f"{len(findings)} finding(s) across "
              f"{len({name for name, _ in findings})} analyzer(s). "
              f"Suppress deliberate patterns with "
              f"'# noqa: <RULE-ID> -- reason'.", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
