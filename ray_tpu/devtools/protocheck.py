"""Whole-program wire-protocol & conformance checker for ray_tpu.

The per-file linter (``ray_tpu.devtools.lint``) catches local patterns;
this tool checks the contracts that span modules — exactly the bug
classes every review-hardening round since PR 6 has re-found by hand: a
sent verb whose handler arity drifted, a new verb sent to a peer that
never advertised the capability, a config knob that reached only one of
the two worker spawn paths, a counter incremented but never surfaced,
and lock nesting that contradicts a documented independent-leaf
convention.  The reference makes these impossible by construction (22
proto files under ``src/ray/protobuf/``); our contract is tuple literals
dispatched via ``msg[0] ==`` chains, so this tool recovers the schema
statically and diffs every site against the one catalog
(``ray_tpu._private.protocol.VERBS``).

Usage::

    python -m ray_tpu.devtools.protocheck ray_tpu/ tests/
    python -m ray_tpu.devtools.protocheck --doc          # catalog table
    python -m ray_tpu.devtools.protocheck --dump ray_tpu/  # inventory
    python -m ray_tpu.devtools.protocheck --select=RTL505 ray_tpu/

Findings print as ``path:line:col: RTLxxx message`` and the process
exits non-zero when any un-suppressed finding remains.  Suppression is
the linter's: ``# noqa: RTL501 -- reason`` on the anchored line — and
for the protocheck rule family the reason is MANDATORY (a reasonless
RTL5xx suppression is itself a finding, RTL500).

How sites are found
===================

SEND sites: tuple literals whose first element is a lowercase string
verb, flowing into a send carrier — ``protocol.send``/``send_batch``,
``self._send``/``_send_wire``/``_queue_send``/``head_send``/``.send``,
a conflation-buffer ``append``/``appendleft``, or a message-builder
``lambda``.  The sender's ROLE comes from the defining module (head =
``runtime.py``/``head_main.py``, worker = ``worker_main.py`` +
``direct.py``, client = ``client.py``, agent = ``node_agent.py``,
object server = ``object_transfer.py``/``shm_store.py``); other
ray_tpu modules are role-free senders (checked for verb existence and
arity, exempt from role rules), and test files never keep a handler
alive.  A module can override with a ``# protocheck: role=<role>``
comment in its first lines (fixtures use this).

HANDLE sites: ``msg[0] == "verb"`` / ``tag == "verb"`` chains (``tag``
assigned from ``msg[0]``), including ``assert msg[0] == "verb"``
handshakes.  The guarded block's subscript reach (``msg[i]``), exact
tuple unpacks (``_tag, a, b = msg``) and ``len(msg)`` guards give the
handler's arity requirements.

Rule catalog
============

RTL500  reasonless-suppression
    A ``# noqa: RTL5xx`` without a ``-- reason`` tail.  Protocol-level
    suppressions document a contract exception; the reason is the
    documentation.

RTL501  wire-verb conformance
    A sent verb missing from the catalog (typo or undocumented), a verb
    sent by a role the catalog does not list as a sender, a handler for
    an uncataloged verb or in a role the catalog does not list, a verb
    with in-tree senders but NO handler in any analyzed handler-role
    module, and a dead handler (no in-tree sender, verb not marked
    ``external``).

RTL502  wire-arity conformance
    A sender tuple whose arity falls outside the catalog range; a
    handler whose exact unpack or subscript reach contradicts the
    catalog; a handler that reads an optional element (index beyond the
    shortest legal form) without a ``len(msg)`` guard while some sender
    ships the short form — anchored with BOTH file:line ends.

RTL503  capability gating
    A send of a caps-gated verb (the negotiated ``object_caps`` /
    v1-lease families) from a function that is not capability-gated:
    neither the function nor (transitively, via intra-module callers)
    any path into it tests caps membership.  Pins the PR 3/6/7 "never
    probe an old peer" convention.

RTL504  knob & counter plumbing
    A ``Config`` field (every field has a ``RAY_TPU_*`` env alias) that
    neither rides ``_worker_config_env`` into BOTH spawn paths nor
    carries a ``# protocheck: head-only -- reason`` /
    ``# protocheck: env-alias RAY_TPU_X -- reason`` exemption; a spawn
    path that stopped consuming ``_worker_config_env``; a worker-side
    xfer-stats counter the head's aggregator drops; an aggregated
    counter ``transfer_stats()`` never surfaces.

RTL505  static lock-order inference
    The ``with self.<lock>:`` nesting graph across method bodies (one
    level of call resolution: ``self.m()``, ``self.attr.m()`` with the
    attr's class inferred from its constructor assignment, module
    functions — across all analyzed modules).  Locks created with a
    ``# lock-order: leaf`` annotation are the documented independent
    leaves: nesting INTO a leaf is the convention, any acquisition
    UNDER a leaf is a violation, and an edge into a non-leaf lock is
    undeclared nesting (annotate the target as a leaf, or suppress with
    a reason).  Catches statically what the runtime lockcheck only sees
    if the path executes.  Lexical heuristic: locks reached through
    unresolvable receivers are not seen.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from collections import defaultdict
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.devtools.lint import Finding, _attr_chain, _iter_py_files

RULES: Dict[str, str] = {
    "RTL500": "protocheck suppression without a '-- reason' tail",
    "RTL501": "wire verb unknown to the catalog, sent/handled by the "
              "wrong role, sent with no handler, or handled dead",
    "RTL502": "wire tuple arity contradicts the catalog or another "
              "module's sender/handler",
    "RTL503": "caps-gated verb sent from a function with no capability "
              "gate on any path into it",
    "RTL504": "config knob not plumbed through _worker_config_env (or "
              "exempted), or a stats counter dropped before "
              "transfer_stats()",
    "RTL505": "undeclared lock nesting, or a lock acquired under a "
              "documented independent leaf",
}

# Module basename -> wire role (the ISSUE's attribution table).
MODULE_ROLES: Dict[str, str] = {
    "runtime.py": "head",
    "head_main.py": "head",
    "worker_main.py": "worker",
    "direct.py": "worker",
    "client.py": "client",
    "node_agent.py": "agent",
    "object_transfer.py": "objsrv",
    "shm_store.py": "objsrv",
}

# Object descriptors ride inside messages and share the tuple-with-a-
# string-head shape; they are payload, not verbs.  "head"/"lease" are
# direct.py's outbound-routing wrappers (their PAYLOAD tuples are the
# send sites) and "ref" is the argument-encoding marker inside specs.
DESCRIPTOR_KINDS = {"inline", "shm", "parts", "spilled", "error", "ref"}
ROUTING_TAGS = {"head", "lease"}

_VERB_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_LOCKISH_RE = re.compile(r"lock|cond|(^|_)cv$|(^|_)sem($|_)")
_CAPS_RE = re.compile(r"caps", re.IGNORECASE)
_ROLE_MARK_RE = re.compile(r"#\s*protocheck:\s*role=([a-z_]+)")
_STANDS_FOR_RE = re.compile(r"#\s*protocheck:\s*stands-for=([a-z_.]+)")
_LEAF_MARK_RE = re.compile(r"#\s*lock-order:\s*leaf\b")
_NOQA_RE = re.compile(r"#\s*noqa:\s*([A-Z0-9, ]+)(--\s*(.*))?")
_HEAD_ONLY_RE = re.compile(
    r"#\s*protocheck:\s*head-only(\s*--\s*(?P<reason>.*))?")
_ENV_ALIAS_RE = re.compile(
    r"#\s*protocheck:\s*env-alias\s+(?P<alias>[A-Z0-9_]+)"
    r"(\s*--\s*(?P<reason>.*))?")

# A send carrier is any callee whose name smells like a socket write or
# a message queue (protocol.send/send_batch, _send/_send_wire,
# _queue_send, head_send, worker_send_safe, queue_msg,
# _queue_small_put...); conflation-buffer appends count only inside
# role-attributed protocol modules (role-free library code appends
# plenty of non-wire tuples).
_SEND_CALLEE_RE = re.compile(r"send|queue")
BUFFER_CALLEES = {"append", "appendleft"}

LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                  "BoundedSemaphore"}


def _load_catalog():
    from ray_tpu._private import protocol

    return getattr(protocol, "VERBS", {})


class _SendSite:
    __slots__ = ("path", "line", "col", "verb", "lo", "hi", "role",
                 "fn", "is_test")

    def __init__(self, path, line, col, verb, lo, hi, role, fn, is_test):
        self.path, self.line, self.col = path, line, col
        self.verb, self.lo, self.hi = verb, lo, hi  # hi None = open-ended
        self.role, self.fn, self.is_test = role, fn, is_test


class _HandleSite:
    __slots__ = ("path", "line", "col", "verb", "reach", "exact",
                 "len_guarded", "role", "is_test")

    def __init__(self, path, line, col, verb, reach, exact, len_guarded,
                 role, is_test):
        self.path, self.line, self.col, self.verb = path, line, col, verb
        self.reach = reach            # 1 + max constant subscript index
        self.exact = exact            # arity pinned by a strict unpack
        self.len_guarded = len_guarded
        self.role, self.is_test = role, is_test


class _Fn:
    """One function/method def, for the caps-gating fixpoint."""
    __slots__ = ("module", "name", "node", "mentions_caps", "calls",
                 "parent")

    def __init__(self, module, name, node, parent=None):
        self.module, self.name, self.node = module, name, node
        self.mentions_caps = False
        self.calls: Set[str] = set()
        self.parent = parent  # lexically enclosing _Fn (closures)


class _ClassInfo:
    __slots__ = ("module", "name", "node", "bases", "methods",
                 "lock_attrs", "attr_types")

    def __init__(self, module, name, node, bases):
        self.module, self.name, self.node = module, name, node
        self.bases = bases                  # base-class name strings
        self.methods: Dict[str, ast.AST] = {}
        # lock attr name -> (line, declared-leaf?)
        self.lock_attrs: Dict[str, Tuple[int, bool]] = {}
        # self.<attr> = ClassName(...) -> attr -> ClassName
        self.attr_types: Dict[str, str] = {}


class _Module:
    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        base = os.path.basename(path)
        self.is_test = (base.startswith("test_")
                        or (os.sep + "tests" + os.sep) in path)
        self.role: Optional[str] = MODULE_ROLES.get(base)
        # Fixtures impersonate special modules: `# protocheck: role=X`
        # assigns a wire role, `# protocheck: stands-for=config.py`
        # makes the knob pass treat the file as that module.
        self.stands_for: Optional[str] = None
        for line in self.lines[:10]:
            m = _ROLE_MARK_RE.search(line)
            if m:
                self.role = m.group(1)
                self.is_test = False
            m = _STANDS_FOR_RE.search(line)
            if m:
                self.stands_for = m.group(1)
                self.is_test = False
        self.sends: List[_SendSite] = []
        self.handles: List[_HandleSite] = []
        self.fns: List[_Fn] = []
        self.classes: List[_ClassInfo] = []
        # module-level lock names -> (line, leaf?)
        self.module_locks: Dict[str, Tuple[int, bool]] = {}

    def line_has_leaf_mark(self, lineno: int) -> bool:
        for ln in (lineno, lineno - 1):
            if 1 <= ln <= len(self.lines) \
                    and _LEAF_MARK_RE.search(self.lines[ln - 1]):
                return True
        return False


# ---------------------------------------------------------------- parse --

def _tuple_verb(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Tuple) and node.elts \
            and isinstance(node.elts[0], ast.Constant) \
            and isinstance(node.elts[0].value, str):
        verb = node.elts[0].value
        if _VERB_RE.match(verb) and verb not in DESCRIPTOR_KINDS \
                and verb not in ROUTING_TAGS:
            return verb
    return None


def _tuple_arity(node: ast.Tuple,
                 parent_binop: bool) -> Tuple[int, Optional[int]]:
    n = 0
    open_ended = parent_binop
    for elt in node.elts:
        if isinstance(elt, ast.Starred):
            open_ended = True
        else:
            n += 1
    return n, (None if open_ended else n)


def _is_lock_factory(value: ast.AST) -> bool:
    if not isinstance(value, ast.Call):
        return False
    chain = _attr_chain(value.func)
    return bool(chain) and chain[-1] in LOCK_FACTORIES


class _Extractor(ast.NodeVisitor):
    """One pass per module: send sites, handle sites, function graph,
    class/lock model."""

    def __init__(self, mod: _Module):
        self.mod = mod
        self.fn_stack: List[_Fn] = []
        self.class_stack: List[_ClassInfo] = []
        # verb tuples already claimed by a carrier (avoid double counting
        # the same literal through nested visits)
        self.claimed: Set[int] = set()

    # -- scope ------------------------------------------------------------
    def visit_ClassDef(self, node: ast.ClassDef):
        bases = []
        for b in node.bases:
            chain = _attr_chain(b)
            if chain:
                bases.append(chain[-1])
        info = _ClassInfo(self.mod, node.name, node, tuple(bases))
        self.mod.classes.append(info)
        self.class_stack.append(info)
        try:
            self.generic_visit(node)
        finally:
            self.class_stack.pop()

    def _visit_fn(self, node):
        fn = _Fn(self.mod, node.name, node,
                 parent=self.fn_stack[-1] if self.fn_stack else None)
        self.mod.fns.append(fn)
        if self.class_stack and node in self.class_stack[-1].node.body:
            self.class_stack[-1].methods[node.name] = node
        self.fn_stack.append(fn)
        try:
            self._scan_handler_arms(node)
            self.generic_visit(node)
        finally:
            self.fn_stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    # -- caps tests --------------------------------------------------------
    # A function is capability-gated only when it TESTS caps — a
    # membership check (`"fetch_range" in caps`), a caps attribute in a
    # branch condition (`if not worker.lease_caps`), or a predicate call
    # (`peer_accepts_puts(caps)`) in a test position.  Merely receiving
    # or forwarding a ``caps`` value does not count: that is how the
    # un-gated bug looks.
    @staticmethod
    def _capsish(tree: ast.AST) -> bool:
        for sub in ast.walk(tree):
            if isinstance(sub, ast.Name) and _CAPS_RE.search(sub.id):
                return True
            if isinstance(sub, ast.Attribute) and _CAPS_RE.search(sub.attr):
                return True
            if isinstance(sub, ast.Call):
                chain = _attr_chain(sub.func)
                if chain and re.search(r"caps|accepts", chain[-1]):
                    return True
        return False

    def _note_caps_test(self, test: ast.AST):
        if self.fn_stack and self._capsish(test):
            self.fn_stack[-1].mentions_caps = True

    def visit_If(self, node: ast.If):
        self._note_caps_test(node.test)
        self.generic_visit(node)

    def visit_While(self, node: ast.While):
        self._note_caps_test(node.test)
        self.generic_visit(node)

    def visit_IfExp(self, node: ast.IfExp):
        self._note_caps_test(node.test)
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert):
        self._note_caps_test(node.test)
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare):
        # Membership tests outside an If (e.g. `ok = v in caps`) still
        # gate: the branch may live one expression away.
        if self.fn_stack and any(isinstance(op, (ast.In, ast.NotIn))
                                 for op in node.ops) \
                and any(self._capsish(c) for c in node.comparators):
            self.fn_stack[-1].mentions_caps = True
        self.generic_visit(node)

    # -- assignments: lock creation, attr types --------------------------
    def visit_Assign(self, node: ast.Assign):
        for target in node.targets:
            chain = _attr_chain(target)
            if not chain:
                continue
            if len(chain) == 2 and chain[0] == "self" and self.class_stack:
                cls = self.class_stack[-1]
                if _is_lock_factory(node.value):
                    cls.lock_attrs[chain[1]] = (
                        node.lineno,
                        self.mod.line_has_leaf_mark(node.lineno))
                elif isinstance(node.value, ast.Call):
                    cchain = _attr_chain(node.value.func)
                    if cchain and cchain[-1][:1].isupper():
                        cls.attr_types[chain[1]] = cchain[-1]
            elif len(chain) == 1 and not self.fn_stack \
                    and not self.class_stack \
                    and _is_lock_factory(node.value):
                self.mod.module_locks[chain[0]] = (
                    node.lineno, self.mod.line_has_leaf_mark(node.lineno))
        self.generic_visit(node)

    # -- calls: send carriers + call graph -------------------------------
    def visit_Call(self, node: ast.Call):
        chain = _attr_chain(node.func)
        leaf = chain[-1] if chain else None
        if self.fn_stack and leaf:
            self.fn_stack[-1].calls.add(leaf)
        carrier = leaf is not None and bool(_SEND_CALLEE_RE.search(leaf))
        buffered = (leaf in BUFFER_CALLEES and self.mod.role is not None
                    and not self.mod.is_test)
        if carrier or buffered:
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                self._claim_verb_tuples(arg)
        self.generic_visit(node)

    def visit_Lambda(self, node: ast.Lambda):
        # Message-builder lambdas (("lease_req", rid, ...) factories).
        if not self.mod.is_test:
            self._claim_verb_tuples(node.body)
        self.generic_visit(node)

    def _claim_verb_tuples(self, root: ast.AST):
        """Find verb tuples in an argument subtree: through ternaries,
        concatenation, list literals, and the elements of routing
        wrappers / other claimed tuples (direct.py parks messages as
        ("head", msg) / ("lease", lease, msg, fallback)) — but not
        through nested calls."""
        stack = [(root, False)]
        while stack:
            node, in_binop = stack.pop()
            if isinstance(node, ast.Tuple):
                verb = _tuple_verb(node)
                if verb is not None and id(node) not in self.claimed:
                    self.claimed.add(id(node))
                    lo, hi = _tuple_arity(node, in_binop)
                    self.mod.sends.append(_SendSite(
                        self.mod.path, node.lineno, node.col_offset,
                        verb, lo, hi, self.mod.role,
                        self.fn_stack[-1] if self.fn_stack else None,
                        self.mod.is_test))
                # Nested payload tuples (routing wrappers, batched
                # message lists) are send sites of their own.
                stack += [(e, False) for e in node.elts[1:]]
            elif isinstance(node, ast.IfExp):
                stack += [(node.body, in_binop), (node.orelse, in_binop)]
            elif isinstance(node, ast.BinOp):
                stack += [(node.left, True), (node.right, True)]
            elif isinstance(node, (ast.List, ast.Set)):
                stack += [(e, in_binop) for e in node.elts]

    # -- handler arms -----------------------------------------------------
    def _scan_handler_arms(self, fn_node):
        """Within one function: find tag variables (``tag = msg[0]``),
        then every ``== "verb"`` guard and its block's subscript reach."""
        tagvars: Dict[str, str] = {}   # tag var -> msg var
        for stmt in ast.walk(fn_node):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and stmt is not fn_node:
                continue
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and isinstance(stmt.value, ast.Subscript) \
                    and isinstance(stmt.value.value, ast.Name):
                sl = stmt.value.slice
                if isinstance(sl, ast.Constant) and sl.value == 0:
                    tagvars[stmt.targets[0].id] = stmt.value.value.id

        def compare_verbs(test) -> Tuple[Optional[str], List[str]]:
            """(msg var, verbs) when this test is a tag == "verb" (or
            or-chain / membership) guard."""
            verbs: List[str] = []
            msg_var: Optional[str] = None
            comps = []
            if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.Or):
                comps = test.values
            else:
                comps = [test]
            for comp in comps:
                if not isinstance(comp, ast.Compare) \
                        or len(comp.ops) != 1:
                    return None, []
                left, op, right = comp.left, comp.ops[0], \
                    comp.comparators[0]
                var = None
                if isinstance(left, ast.Name) and left.id in tagvars:
                    var = tagvars[left.id]
                elif isinstance(left, ast.Subscript) \
                        and isinstance(left.value, ast.Name) \
                        and isinstance(left.slice, ast.Constant) \
                        and left.slice.value == 0:
                    var = left.value.id
                if var is None:
                    return None, []
                vs = []
                if isinstance(op, ast.Eq) and isinstance(right, ast.Constant) \
                        and isinstance(right.value, str):
                    vs = [right.value]
                elif isinstance(op, ast.In) \
                        and isinstance(right, (ast.Tuple, ast.List, ast.Set)):
                    for e in right.elts:
                        if isinstance(e, ast.Constant) \
                                and isinstance(e.value, str):
                            vs.append(e.value)
                if not vs:
                    return None, []
                if msg_var is None:
                    msg_var = var
                verbs.extend(vs)
            return msg_var, verbs

        def is_nested_arm(stmt, msg_var: str) -> bool:
            """An inner If that re-dispatches on the same message var
            (multi-verb arms like the job_* family): its subscripts
            belong to ITS verbs, not the outer arm's."""
            if not isinstance(stmt, ast.If):
                return False
            for sub in ast.walk(stmt.test):
                if isinstance(sub, ast.Compare):
                    left = sub.left
                    if isinstance(left, ast.Name) \
                            and tagvars.get(left.id) == msg_var:
                        return True
                    if isinstance(left, ast.Subscript) \
                            and isinstance(left.value, ast.Name) \
                            and left.value.id == msg_var \
                            and isinstance(left.slice, ast.Constant) \
                            and left.slice.value == 0:
                        return True
            return False

        def block_reach(body: List[ast.stmt], msg_var: str,
                        top_level: bool = True):
            reach, exact, guarded = 0, None, False
            stack = list(body)
            while stack:
                sub = stack.pop()
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.Lambda)):
                    continue
                if top_level and is_nested_arm(sub, msg_var):
                    continue  # its subscripts belong to the inner arms
                if isinstance(sub, ast.Subscript) \
                        and isinstance(sub.value, ast.Name) \
                        and sub.value.id == msg_var \
                        and isinstance(sub.slice, ast.Constant) \
                        and isinstance(sub.slice.value, int):
                    reach = max(reach, sub.slice.value + 1)
                elif isinstance(sub, ast.Assign) \
                        and len(sub.targets) == 1 \
                        and isinstance(sub.targets[0], ast.Tuple) \
                        and isinstance(sub.value, ast.Name) \
                        and sub.value.id == msg_var:
                    elts = sub.targets[0].elts
                    if any(isinstance(e, ast.Starred) for e in elts):
                        reach = max(
                            reach,
                            sum(1 for e in elts
                                if not isinstance(e, ast.Starred)))
                    else:
                        exact = len(elts)
                elif isinstance(sub, ast.Call):
                    cchain = _attr_chain(sub.func)
                    if cchain == ["len"] and sub.args \
                            and isinstance(sub.args[0], ast.Name) \
                            and sub.args[0].id == msg_var:
                        guarded = True
                stack.extend(ast.iter_child_nodes(sub))
            return reach, exact, guarded

        def scan_stmts(stmts: List[ast.stmt]):
            for i, stmt in enumerate(stmts):
                if isinstance(stmt, ast.If):
                    msg_var, verbs = compare_verbs(stmt.test)
                    if msg_var and verbs:
                        guard_has_len = any(
                            isinstance(s, ast.Call)
                            and _attr_chain(s.func) == ["len"]
                            for s in ast.walk(stmt.test))
                        reach, exact, guarded = block_reach(
                            stmt.body, msg_var)
                        for verb in verbs:
                            if verb in DESCRIPTOR_KINDS \
                                    or verb in ROUTING_TAGS:
                                continue
                            self.mod.handles.append(_HandleSite(
                                self.mod.path, stmt.lineno,
                                stmt.col_offset, verb, reach, exact,
                                guarded or guard_has_len, self.mod.role,
                                self.mod.is_test))
                    scan_stmts(stmt.body)
                    scan_stmts(stmt.orelse)
                elif isinstance(stmt, ast.Assert):
                    msg_var, verbs = compare_verbs(stmt.test)
                    if msg_var and verbs:
                        reach, exact, guarded = block_reach(
                            stmts[i + 1:], msg_var)
                        for verb in verbs:
                            if verb in DESCRIPTOR_KINDS \
                                    or verb in ROUTING_TAGS:
                                continue
                            self.mod.handles.append(_HandleSite(
                                self.mod.path, stmt.lineno,
                                stmt.col_offset, verb, reach, exact,
                                guarded, self.mod.role,
                                self.mod.is_test))
                elif isinstance(stmt, (ast.For, ast.While, ast.With,
                                       ast.Try)):
                    for attr in ("body", "orelse", "finalbody"):
                        scan_stmts(getattr(stmt, attr, []) or [])
                    for h in getattr(stmt, "handlers", []) or []:
                        scan_stmts(h.body)

        scan_stmts(fn_node.body)


# ------------------------------------------------------------- analysis --

class Analysis:
    def __init__(self, paths, catalog=None):
        self.catalog = _load_catalog() if catalog is None else catalog
        self.modules: List[_Module] = []
        self.findings: List[Finding] = []
        for path in _iter_py_files(paths):
            try:
                with open(path, "r", encoding="utf-8") as f:
                    source = f.read()
                tree = ast.parse(source, filename=path)
            except (OSError, SyntaxError):
                continue  # the lint gate owns syntax errors
            mod = _Module(path, source, tree)
            _Extractor(mod).visit(tree)
            self.modules.append(mod)

    # -- helpers ----------------------------------------------------------
    def _emit(self, path, line, col, rule, message):
        self.findings.append(Finding(path, line, col, rule, message))

    def run(self, select: Optional[Set[str]] = None) -> List[Finding]:
        self.findings = []
        self._check_verbs()
        self._check_caps()
        self._check_knobs()
        self._check_counters()
        self._check_serve_counters()
        self._check_locks()
        # One edge/site can be reached through several call paths or
        # held-lock levels: report it once.
        seen: Set[str] = set()
        unique = []
        for f in self.findings:
            key = repr(f)
            if key not in seen:
                seen.add(key)
                unique.append(f)
        self.findings = unique
        kept = self._apply_suppressions()
        if select:
            kept = [f for f in kept
                    if any(f.rule.startswith(s) for s in select)]
        kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
        return kept

    def _apply_suppressions(self) -> List[Finding]:
        by_path = {m.path: m for m in self.modules}
        kept: List[Finding] = []
        flagged_noqa: Set[Tuple[str, int]] = set()
        for f in self.findings:
            mod = by_path.get(f.path)
            line = (mod.lines[f.line - 1]
                    if mod and f.line <= len(mod.lines) else "")
            m = _NOQA_RE.search(line)
            rules = set()
            if m:
                rules = {tok for tok in
                         re.split(r"[\s,]+", m.group(1).upper()) if tok}
            if m and f.rule in rules:
                reason = (m.group(3) or "").strip()
                if not reason and (f.path, f.line) not in flagged_noqa:
                    flagged_noqa.add((f.path, f.line))
                    kept.append(Finding(
                        f.path, f.line, f.col, "RTL500",
                        f"suppression of {f.rule} carries no '-- reason' "
                        f"tail; protocol exceptions must say why"))
                continue
            kept.append(f)
        return kept

    # -- RTL501/502: verbs ------------------------------------------------
    def _check_verbs(self):
        sends = defaultdict(list)
        handles = defaultdict(list)
        roles_present: Set[str] = set()
        for mod in self.modules:
            if mod.role and not mod.is_test:
                roles_present.add(mod.role)
            for s in mod.sends:
                sends[s.verb].append(s)
            for h in mod.handles:
                handles[h.verb].append(h)

        for verb, sites in sends.items():
            spec = self.catalog.get(verb)
            for s in sites:
                if spec is None:
                    self._emit(
                        s.path, s.line, s.col, "RTL501",
                        f"verb {verb!r} is not in the protocol catalog "
                        f"(protocol.VERBS) — typo, or add it with roles/"
                        f"arity/doc")
                    continue
                if s.is_test:
                    pass  # tests may impersonate any role
                elif s.role and s.role not in spec.senders:
                    self._emit(
                        s.path, s.line, s.col, "RTL501",
                        f"verb {verb!r} sent from role {s.role!r}; the "
                        f"catalog lists senders {spec.senders}")
                # Arity vs catalog.
                if spec.arity is not None:
                    lo, hi = spec.arity
                    if s.hi is not None and not (lo <= s.hi and s.lo <= hi):
                        self._emit(
                            s.path, s.line, s.col, "RTL502",
                            f"{verb!r} sent with arity {s.lo}; the "
                            f"catalog allows {lo}..{hi}")
                    elif s.hi is None and s.lo > hi:
                        self._emit(
                            s.path, s.line, s.col, "RTL502",
                            f"{verb!r} sent with arity >= {s.lo}; the "
                            f"catalog allows {lo}..{hi}")

        for verb, sites in handles.items():
            spec = self.catalog.get(verb)
            live_senders = [s for s in sends.get(verb, ())
                            if not s.is_test]
            for h in sites:
                if spec is None:
                    self._emit(
                        h.path, h.line, h.col, "RTL501",
                        f"handler for verb {verb!r} not in the protocol "
                        f"catalog (protocol.VERBS) — typo, or add it")
                    continue
                if h.is_test:
                    continue
                if h.role and h.role not in spec.handlers:
                    self._emit(
                        h.path, h.line, h.col, "RTL501",
                        f"verb {verb!r} handled in role {h.role!r}; the "
                        f"catalog lists handlers {spec.handlers}")
                if spec.arity is not None:
                    self._check_handler_arity(h, spec, live_senders)

        # Liveness: cross-module existence checks.
        for verb, spec in self.catalog.items():
            live_sends = [s for s in sends.get(verb, ())
                          if not s.is_test]
            live_handles = [h for h in handles.get(verb, ())
                            if not h.is_test]
            if live_sends and not live_handles and not spec.external \
                    and set(spec.handlers) & roles_present:
                s = live_sends[0]
                self._emit(
                    s.path, s.line, s.col, "RTL501",
                    f"verb {verb!r} is sent but NO analyzed module of "
                    f"roles {spec.handlers} handles it "
                    f"({len(live_sends)} send site(s))")
            if live_handles and not live_sends and not spec.external \
                    and set(spec.senders) & roles_present:
                h = live_handles[0]
                self._emit(
                    h.path, h.line, h.col, "RTL501",
                    f"dead handler: no analyzed module sends {verb!r} "
                    f"(catalog senders {spec.senders}); delete the arm "
                    f"or mark the verb external=True in the catalog")

    def _check_handler_arity(self, h: _HandleSite, spec, live_senders):
        lo, hi = spec.arity
        if h.exact is not None:
            if not (lo <= h.exact <= hi):
                self._emit(
                    h.path, h.line, h.col, "RTL502",
                    f"handler unpacks {h.verb!r} into exactly {h.exact} "
                    f"elements; the catalog allows {lo}..{hi}")
            elif h.exact < hi and not h.len_guarded:
                self._emit(
                    h.path, h.line, h.col, "RTL502",
                    f"handler unpacks {h.verb!r} into exactly {h.exact} "
                    f"elements without a len() guard, but the catalog "
                    f"allows up to {hi} — a longer legal message would "
                    f"crash the unpack")
        if h.reach > hi:
            self._emit(
                h.path, h.line, h.col, "RTL502",
                f"handler reads {h.verb}[{h.reach - 1}] but the catalog "
                f"caps arity at {hi}")
        elif h.reach > lo and not h.len_guarded:
            short = [s for s in live_senders
                     if s.hi is not None and s.hi < h.reach]
            if short:
                s = short[0]
                self._emit(
                    h.path, h.line, h.col, "RTL502",
                    f"handler reads optional element "
                    f"{h.verb}[{h.reach - 1}] without a len() guard, but "
                    f"{s.path}:{s.line} sends the {s.hi}-element form")

    # -- RTL503: caps gating ----------------------------------------------
    def _check_caps(self):
        # Fixpoint per module: a function is caps-gated if it mentions
        # caps itself, or every known intra-module caller is gated.
        for mod in self.modules:
            if mod.is_test:
                continue
            by_name = defaultdict(list)
            for fn in mod.fns:
                by_name[fn.name].append(fn)
            callers: Dict[int, Set[int]] = defaultdict(set)
            for fn in mod.fns:
                for callee_name in fn.calls:
                    for callee in by_name.get(callee_name, ()):
                        if callee is not fn:
                            callers[id(callee)].add(id(fn))
                # A nested def runs on behalf of its enclosing function
                # (thread targets, deferred closures): the enclosing
                # gate covers it.
                if fn.parent is not None:
                    callers[id(fn)].add(id(fn.parent))
            gated = {id(fn): fn.mentions_caps for fn in mod.fns}
            changed = True
            while changed:
                changed = False
                for fn in mod.fns:
                    if gated[id(fn)]:
                        continue
                    cs = callers.get(id(fn))
                    if cs and all(gated.get(c, False) for c in cs):
                        gated[id(fn)] = True
                        changed = True
            for s in mod.sends:
                spec = self.catalog.get(s.verb)
                if spec is None or not spec.caps:
                    continue
                if s.fn is None or not gated.get(id(s.fn), False):
                    self._emit(
                        s.path, s.line, s.col, "RTL503",
                        f"caps-gated verb {s.verb!r} ({spec.caps}) sent "
                        f"with no capability test on any path into "
                        f"{s.fn.name if s.fn else '<module>'}() — old "
                        f"peers must never see it (PR 3/6/7 convention)")

    # -- RTL504: knobs + counters ----------------------------------------
    def _find_module(self, basename: str) -> Optional[_Module]:
        for mod in self.modules:
            if not mod.is_test \
                    and (os.path.basename(mod.path) == basename
                         or mod.stands_for == basename):
                return mod
        return None

    def _config_fields(self, cfg: _Module):
        """[(field, line, exemption)] from the Config dataclass;
        exemption is None, "head-only", or an env-alias string."""
        out = []
        for cls in cfg.classes:
            if cls.name != "Config":
                continue
            for stmt in cls.node.body:
                if not isinstance(stmt, ast.AnnAssign) \
                        or not isinstance(stmt.target, ast.Name):
                    continue
                field = stmt.target.id
                exempt = None
                for ln in (stmt.lineno, stmt.lineno - 1):
                    if not (1 <= ln <= len(cfg.lines)):
                        continue
                    text = cfg.lines[ln - 1]
                    m = _HEAD_ONLY_RE.search(text)
                    if m:
                        exempt = ("head-only",
                                  (m.group("reason") or "").strip(), ln)
                        break
                    m = _ENV_ALIAS_RE.search(text)
                    if m:
                        exempt = ("env-alias", m.group("alias"), ln)
                        break
                out.append((field, stmt.lineno, exempt))
        return out

    def _worker_env_keys(self, rt: _Module):
        """String keys of the dict literal(s) inside
        _worker_config_env, with the def's line for anchoring."""
        keys: Set[str] = set()
        line = None
        for fn in rt.fns:
            if fn.name != "_worker_config_env":
                continue
            line = fn.node.lineno
            for sub in ast.walk(fn.node):
                if isinstance(sub, ast.Dict):
                    for k in sub.keys:
                        if isinstance(k, ast.Constant) \
                                and isinstance(k.value, str):
                            keys.add(k.value)
        return keys, line

    def _check_knobs(self):
        cfg = self._find_module("config.py")
        rt = self._find_module("runtime.py")
        if cfg is None or rt is None:
            return
        env_keys, env_line = self._worker_env_keys(rt)
        if env_line is None:
            return
        # Both spawn paths must consume _worker_config_env.
        for spawn in ("_spawn_worker", "_spawn_worker_via_agent"):
            fns = [fn for fn in rt.fns if fn.name == spawn]
            for fn in fns:
                if "_worker_config_env" not in fn.calls:
                    self._emit(
                        rt.path, fn.node.lineno, fn.node.col_offset,
                        "RTL504",
                        f"spawn path {spawn}() does not consume "
                        f"_worker_config_env() — knobs will reach only "
                        f"the other spawn path")
        for field, line, exempt in self._config_fields(cfg):
            canonical = "RAY_TPU_" + field.upper()
            if canonical in env_keys:
                continue
            if exempt is not None:
                kind, value, mline = exempt
                if kind == "head-only":
                    if not value:
                        self._emit(cfg.path, mline, 0, "RTL500",
                                   f"head-only exemption for {field!r} "
                                   f"carries no '-- reason' tail")
                    continue
                if kind == "env-alias":
                    if value in env_keys:
                        continue
                    self._emit(
                        cfg.path, line, 0, "RTL504",
                        f"config field {field!r} declares env-alias "
                        f"{value} but _worker_config_env "
                        f"(runtime.py:{env_line}) does not ship it")
                    continue
            self._emit(
                cfg.path, line, 0, "RTL504",
                f"config field {field!r} (env RAY_TPU_{field.upper()}) "
                f"does not ride _worker_config_env "
                f"(runtime.py:{env_line}) into the worker spawn paths — "
                f"plumb it, or mark it '# protocheck: head-only -- "
                f"reason' / '# protocheck: env-alias RAY_TPU_X'")

    def _check_counters(self):
        rt = self._find_module("runtime.py")
        if rt is None:
            return
        # A: keys the head's xfer_stats handler aggregates (d.get("k")),
        # located via the handler arm protocheck already extracted.
        agg: Dict[str, int] = {}
        agg_line = None
        for h in rt.handles:
            if h.verb == "xfer_stats":
                agg_line = h.line
        if agg_line is None:
            return
        # Collect d.get("key") string constants near the handler line.
        for fn in rt.fns:
            node = fn.node
            if not (node.lineno <= agg_line <= (node.end_lineno or 0)):
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr == "get" and sub.args \
                        and isinstance(sub.args[0], ast.Constant) \
                        and isinstance(sub.args[0].value, str) \
                        and sub.lineno >= agg_line \
                        and sub.lineno <= agg_line + 40:
                    agg[sub.args[0].value] = sub.lineno
        if not agg:
            return
        # T: keys surfaced by transfer_stats().
        surfaced: Set[str] = set()
        for fn in rt.fns:
            if fn.name != "transfer_stats":
                continue
            for sub in ast.walk(fn.node):
                if isinstance(sub, ast.Dict):
                    for k in sub.keys:
                        if isinstance(k, ast.Constant) \
                                and isinstance(k.value, str):
                            surfaced.add(k.value)
        for key, line in agg.items():
            if key not in surfaced:
                self._emit(
                    rt.path, line, 0, "RTL504",
                    f"xfer_stats aggregates counter {key!r} but "
                    f"transfer_stats() never surfaces it")
        # W: worker-side stats() dicts that feed the xfer stream — any
        # stats() whose keys overlap the aggregated set must be fully
        # aggregated (a counter added to one is silently dropped
        # otherwise).
        for mod in self.modules:
            if mod.is_test or mod.role not in ("worker", "objsrv"):
                continue
            for fn in mod.fns:
                if fn.name != "stats":
                    continue
                keys = {}
                for sub in ast.walk(fn.node):
                    if isinstance(sub, ast.Dict):
                        for k in sub.keys:
                            if isinstance(k, ast.Constant) \
                                    and isinstance(k.value, str):
                                keys[k.value] = sub.lineno
                if not keys or not (set(keys) & set(agg)):
                    continue
                for key, line in keys.items():
                    if key not in agg:
                        self._emit(
                            mod.path, line, 0, "RTL504",
                            f"worker counter {key!r} rides the "
                            f"xfer_stats delta but the head's "
                            f"aggregator (runtime.py:{agg_line}) drops "
                            f"it — every shipped counter must reach "
                            f"transfer_stats()")

    def _check_serve_counters(self):
        """Serve-plane twin of _check_counters: every key a serve
        batcher's ``stats()`` ships (serve/batching.py,
        serve/continuous.py, and the kv engine's ``stats_locked()``,
        whose dict is merged into the batcher's) must SURVIVE the
        controller rollup — appear in ``serving_stats`` in
        serve/api.py, either read off a replica row (``b[...]`` /
        ``b.get(...)``) or recomputed into the aggregate dict.  A
        counter added to a batcher but dropped by the rollup is
        invisible at ``serve.serving_stats()`` — exactly the bug class
        the xfer-stats rule pins for the head."""
        sep = os.sep
        api = None
        for mod in self.modules:
            if not mod.is_test and mod.path.endswith(
                    f"serve{sep}api.py"):
                api = mod
                break
        if api is None:
            return
        # Keys surviving the rollup: string constants subscripted /
        # .get()'d / assigned anywhere inside serving_stats defs, plus
        # dict-literal keys (the aggregate's shape).
        survived: Set[str] = set()
        roll_line = None
        for fn in api.fns:
            if fn.name != "serving_stats":
                continue
            roll_line = roll_line or fn.node.lineno
            for sub in ast.walk(fn.node):
                if isinstance(sub, ast.Subscript) \
                        and isinstance(sub.slice, ast.Constant) \
                        and isinstance(sub.slice.value, str):
                    survived.add(sub.slice.value)
                elif isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr == "get" and sub.args \
                        and isinstance(sub.args[0], ast.Constant) \
                        and isinstance(sub.args[0].value, str):
                    survived.add(sub.args[0].value)
                elif isinstance(sub, (ast.Dict, ast.Tuple)):
                    for k in (sub.keys if isinstance(sub, ast.Dict)
                              else sub.elts):
                        if isinstance(k, ast.Constant) \
                                and isinstance(k.value, str):
                            survived.add(k.value)
        if roll_line is None:
            return
        for mod in self.modules:
            if mod.is_test or f"{sep}serve{sep}" not in mod.path \
                    or mod.path.endswith(f"serve{sep}api.py"):
                continue
            for fn in mod.fns:
                if fn.name not in ("stats", "stats_locked"):
                    continue
                for sub in ast.walk(fn.node):
                    if not isinstance(sub, ast.Dict):
                        continue
                    for k in sub.keys:
                        if isinstance(k, ast.Constant) \
                                and isinstance(k.value, str) \
                                and k.value not in survived:
                            self._emit(
                                mod.path, sub.lineno, 0, "RTL504",
                                f"serve batcher counter {k.value!r} is "
                                f"dropped by the controller rollup "
                                f"(serve/api.py:{roll_line} "
                                f"serving_stats) — every shipped "
                                f"counter must survive head "
                                f"aggregation")

    # -- RTL505: lock order -----------------------------------------------
    def _check_locks(self):
        # Global class registry (unique names only — ambiguous names are
        # skipped rather than guessed).
        registry: Dict[str, _ClassInfo] = {}
        ambiguous: Set[str] = set()
        for mod in self.modules:
            if mod.is_test:
                continue
            for cls in mod.classes:
                if cls.name in registry:
                    ambiguous.add(cls.name)
                registry[cls.name] = cls
        for name in ambiguous:
            registry.pop(name, None)

        def resolve_cls(cls: _ClassInfo) -> List[_ClassInfo]:
            """cls + base classes (by unique name)."""
            out, seen = [cls], {cls.name}
            queue = list(cls.bases)
            while queue:
                b = queue.pop()
                if b in seen:
                    continue
                seen.add(b)
                info = registry.get(b)
                if info is not None:
                    out.append(info)
                    queue += list(info.bases)
            return out

        def lock_id(cls: Optional[_ClassInfo], mod: _Module, attr: str):
            if cls is not None:
                for c in resolve_cls(cls):
                    if attr in c.lock_attrs:
                        line, leaf = c.lock_attrs[attr]
                        return (c.module.path, c.name, attr), leaf
                return (mod.path, cls.name, attr), False
            if attr in mod.module_locks:
                line, leaf = mod.module_locks[attr]
                return (mod.path, None, attr), leaf
            return None, False

        def entry_locks(cls: Optional[_ClassInfo], mod: _Module,
                        fn_node) -> List[Tuple[tuple, bool]]:
            """Locks a callee acquires lexically (not inside nested
            defs) — the one-level resolution target set."""
            out = []
            stack = list(fn_node.body)
            while stack:
                stmt = stack.pop()
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    for item in stmt.items:
                        lid = self._with_lock_id(
                            item.context_expr, cls, mod, lock_id)
                        if lid is not None:
                            out.append((lid[0], lid[1], stmt.lineno))
                for child in ast.iter_child_nodes(stmt):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.Lambda)):
                        continue
                    stack.append(child)
            return out

        for mod in self.modules:
            if mod.is_test:
                continue
            method_nodes = set()
            for cls in mod.classes:
                for mname, mnode in cls.methods.items():
                    method_nodes.add(id(mnode))
                    self._walk_regions(mod, cls, mnode, [], registry,
                                       resolve_cls, lock_id, entry_locks)
            # Module-level (and nested) functions are region roots too —
            # the one module-level leaf in the tree (shm_store's
            # _copy_pool_lock) is only ever acquired in module
            # functions, so skipping them would make its leaf
            # declaration unenforceable.  Without a class context only
            # module-lock / module-function resolution applies.
            for fn in mod.fns:
                if id(fn.node) not in method_nodes \
                        and not isinstance(fn.node, ast.Lambda):
                    self._walk_regions(mod, None, fn.node, [], registry,
                                       resolve_cls, lock_id, entry_locks)

    def _with_lock_id(self, expr, cls, mod, lock_id):
        chain = _attr_chain(expr)
        if not chain:
            return None
        out = None
        if len(chain) == 2 and chain[0] == "self" \
                and _LOCKISH_RE.search(chain[1].lower()):
            out = lock_id(cls, mod, chain[1])
        elif len(chain) == 1 and chain[0] in mod.module_locks:
            out = lock_id(None, mod, chain[0])
        return out if out is not None and out[0] is not None else None

    def _walk_regions(self, mod, cls, node, held, registry, resolve_cls,
                      lock_id, entry_locks):
        """held: [(lock_id, leaf?)] currently-held with-locks."""
        for stmt in ast.iter_child_nodes(node):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue  # runs at call time, not under this region
            acquired = None
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    lid = self._with_lock_id(item.context_expr, cls,
                                             mod, lock_id)
                    if lid is not None:
                        acquired = lid
                        self._note_edges(mod, held, lid, stmt.lineno)
            # Resolve calls appearing anywhere in this statement while
            # locks are held (one level).
            if held:
                for sub in ast.walk(stmt):
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef, ast.Lambda)):
                        continue
                    if not isinstance(sub, ast.Call):
                        continue
                    target = self._resolve_call(sub, cls, mod, registry,
                                                resolve_cls)
                    if target is None:
                        continue
                    tcls, tmod, tnode = target
                    for lid, leaf, _ln in entry_locks(tcls, tmod, tnode):
                        self._note_edges(mod, held, (lid, leaf),
                                         sub.lineno)
            if acquired is not None:
                held.append(acquired)
                self._walk_regions(mod, cls, stmt, held, registry,
                                   resolve_cls, lock_id, entry_locks)
                held.pop()
            else:
                self._walk_regions(mod, cls, stmt, held, registry,
                                   resolve_cls, lock_id, entry_locks)

    def _resolve_call(self, call: ast.Call, cls, mod, registry,
                      resolve_cls):
        chain = _attr_chain(call.func)
        if not chain:
            return None
        if len(chain) == 2 and chain[0] == "self" and cls is not None:
            for c in resolve_cls(cls):
                if chain[1] in c.methods:
                    return c, c.module, c.methods[chain[1]]
            return None
        if len(chain) == 3 and chain[0] == "self" and cls is not None:
            attr, meth = chain[1], chain[2]
            for c in resolve_cls(cls):
                tname = c.attr_types.get(attr)
                if tname and tname in registry:
                    target = registry[tname]
                    if meth in target.methods:
                        return (target, target.module,
                                target.methods[meth])
            return None
        if len(chain) == 1:
            for fn in mod.fns:
                if fn.name == chain[0] \
                        and isinstance(fn.node, (ast.FunctionDef,
                                                 ast.AsyncFunctionDef)):
                    # Module-level functions only (methods resolved via
                    # self above).
                    return None, mod, fn.node
        return None

    def _note_edges(self, mod: _Module, held, target, lineno: int):
        tid, tleaf = target
        for hid, hleaf in held:
            if hid == tid:
                continue  # re-entrant / same-lock
            if hleaf:
                self._emit(
                    mod.path, lineno, 0, "RTL505",
                    f"lock {_fmt_lock(tid)} acquired while holding "
                    f"{_fmt_lock(hid)}, which is declared an "
                    f"independent leaf ('# lock-order: leaf') — leaves "
                    f"must acquire nothing")
            elif not tleaf:
                self._emit(
                    mod.path, lineno, 0, "RTL505",
                    f"undeclared lock nesting: {_fmt_lock(tid)} "
                    f"acquired while holding {_fmt_lock(hid)} — declare "
                    f"the inner lock '# lock-order: leaf' at its "
                    f"creation site, or suppress here with a reason")

    # -- inventory dump ---------------------------------------------------
    def dump(self) -> str:
        out = []
        sends = defaultdict(list)
        handles = defaultdict(list)
        for mod in self.modules:
            for s in mod.sends:
                sends[s.verb].append(s)
            for h in mod.handles:
                handles[h.verb].append(h)
        for verb in sorted(set(sends) | set(handles)):
            out.append(f"== {verb}")
            for s in sends.get(verb, ()):
                hi = "open" if s.hi is None else s.hi
                out.append(f"  send   {s.role or '-':7} "
                           f"arity={s.lo}..{hi}  "
                           f"{s.path}:{s.line}"
                           f"{'  [test]' if s.is_test else ''}")
            for h in handles.get(verb, ()):
                out.append(
                    f"  handle {h.role or '-':7} reach={h.reach} "
                    f"exact={h.exact} lenguard={h.len_guarded}  "
                    f"{h.path}:{h.line}"
                    f"{'  [test]' if h.is_test else ''}")
        return "\n".join(out)


def _fmt_lock(lid: tuple) -> str:
    path, cls, attr = lid
    base = os.path.splitext(os.path.basename(path))[0]
    return f"{base}.{cls + '.' if cls else ''}{attr}"


# ------------------------------------------------------------------ doc --

def catalog_doc() -> str:
    """Markdown table of the wire-verb catalog (the README's generated
    wire-protocol section: `python -m ray_tpu.devtools.protocheck
    --doc`)."""
    catalog = _load_catalog()
    lines = [
        "| verb | senders | handlers | arity | caps | description |",
        "|---|---|---|---|---|---|",
    ]
    for verb in sorted(catalog):
        spec = catalog[verb]
        if spec.arity is None:
            arity = "var"
        elif spec.arity[0] == spec.arity[1]:
            arity = str(spec.arity[0])
        else:
            arity = f"{spec.arity[0]}..{spec.arity[1]}"
        lines.append(
            f"| `{verb}` | {', '.join(spec.senders)} "
            f"| {', '.join(spec.handlers)} | {arity} "
            f"| {spec.caps or ''} "
            f"| {spec.doc}{' *(external)*' if spec.external else ''} |")
    return "\n".join(lines)


def check_paths(paths, select: Optional[Set[str]] = None,
                catalog=None) -> List[Finding]:
    return Analysis(paths, catalog=catalog).run(select=select)


def main(argv=None) -> int:
    from ray_tpu.devtools.lint import run_cli

    argv = list(sys.argv[1:] if argv is None else argv)
    dump = "--dump" in argv
    if dump:
        argv.remove("--dump")

    def runner(paths, select):
        analysis = Analysis(paths)
        if dump:
            print(analysis.dump())
            return 0
        return analysis.run(select=select)

    return run_cli(
        argv, rules=RULES, doc=catalog_doc, runner=runner,
        usage="usage: python -m ray_tpu.devtools.protocheck "
              "[--doc|--dump|--list-rules] [--select=RTL5xx,...] "
              "PATH [PATH ...]")


if __name__ == "__main__":
    sys.exit(main())
