"""Whole-program static concurrency analyzer for ray_tpu (RTL6xx).

``protocheck`` recovers the wire protocol; this tool recovers the LOCK
GRAPH: every lock creation site, every ``with <lock>:`` region, an
interprocedural call graph (self-method resolution, attribute-typed
receivers, module functions across import aliases, constructor calls,
``Thread(target=...)`` / executor-submit / callback spawn edges), and
the static lock-nesting graph — lock A's with-body transitively
reaching an acquisition of lock B is an edge A -> B, whether or not any
test schedule ever executes the path.  Kernel lockdep's trick, done at
review time: the runtime lockcheck (``ray_tpu.devtools.lockcheck``)
only certifies schedules the suite actually executes; this tool
certifies every path the source contains.

Usage::

    python -m ray_tpu.devtools.lockgraph ray_tpu/
    python -m ray_tpu.devtools.lockgraph --doc          # LOCK ORDER table
    python -m ray_tpu.devtools.lockgraph --dump ray_tpu/  # inventory
    python -m ray_tpu.devtools.lockgraph --select=RTL601 ray_tpu/

Annotation grammar (ONE mechanism shared by lint.py RTL402, protocheck
RTL505, this tool, and the runtime lockcheck's leaf registry) — on, or
one line above, a lock CREATION/BINDING site::

    # lock-order: leaf [-- note]
    # lock-order: io-guard [-- note]

``leaf``: the lock is a documented independent leaf — its holder
acquires nothing and signals nothing; anyone may nest INTO it.
``io-guard``: the lock exists to serialize a blocking channel (a socket
write, a snapshot file) and holding it across that IO is the design —
lint's RTL402 and this tool's RTL604 skip io-guard bodies (the guarded
IO is still flagged when reached while some OTHER lock is held).

Spawned/deferred callees (``Thread(target=...)``, ``executor.submit``,
``call_soon*``, ``add_done_callback``) run on another thread or at a
later time: they appear in the call graph for ``--dump`` but do NOT
propagate held locks — each spawned function is analyzed as its own
region root.

Rule catalog
============

RTL600  reasonless-suppression
    A ``# noqa: RTL6xx`` without a ``-- reason`` tail.  Lock-graph
    suppressions document a concurrency-contract exception; the reason
    is the documentation.

RTL601  static-lock-cycle
    A cycle in the static lock-nesting graph: two (or more) code paths
    acquire the same lock classes in opposite orders.  A potential
    deadlock even if no test schedule has ever interleaved them — the
    whole point of checking statically.

RTL602  leaf-grew-an-edge
    A lock annotated ``# lock-order: leaf`` whose with-body reaches
    (lexically or through calls) the acquisition of another lock.
    Leaves must acquire nothing; that contract is what makes nesting
    INTO them safe from every caller.

RTL603  signal-under-leaf
    ``Event.set()`` / ``Condition.notify()`` / ``notify_all()``
    lexically-or-transitively inside an annotated leaf body.  Waking a
    waiter while holding the leaf hands it a lock it may immediately
    contend on (and Event.set itself takes the event's internal lock —
    an edge out of the leaf).  Fire signals after releasing the leaf —
    the convention every PR has pinned by hand until now.

RTL604  blocking-io-reachable-under-lock
    Interprocedural RTL402: blocking socket IO (``protocol.send/recv``,
    ``*.send_bytes/recv_bytes``, sockish ``.send/.recv``) or a payload
    (un)pickle (``pickle.dumps/loads``, ``serialization.dumps*/
    loads*``) reachable THROUGH CALLS from a ``with <lock>:`` body —
    not just lexically inside it (that is RTL402's job and stays in
    lint.py).  io-guard locks are exempt: serializing that IO is what
    they are for.

Resolution is a lexical heuristic: receivers reached through
function-valued variables, dict dispatch, or untyped parameters are not
seen.  The runtime lockcheck covers the residue for executed schedules
— and the static edge set is asserted (in tests) to be a superset of
every edge the runtime checker observes across the suite.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from collections import defaultdict
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.devtools.lint import Finding, _attr_chain, _iter_py_files

RULES: Dict[str, str] = {
    "RTL600": "lockgraph suppression without a '-- reason' tail",
    "RTL601": "cycle in the static lock-nesting graph (potential "
              "deadlock on a never-executed path)",
    "RTL602": "a '# lock-order: leaf' lock's body reaches another "
              "acquisition — leaves must acquire nothing",
    "RTL603": "Event.set/Condition.notify reached while holding a "
              "declared leaf lock",
    "RTL604": "blocking socket IO or payload (un)pickling reachable "
              "through calls from a lock body (interprocedural RTL402)",
}

LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                  "BoundedSemaphore"}
EVENT_FACTORIES = {"Event", "Condition"}
_LOCKISH_RE = re.compile(r"lock|cond|(^|_)cv$|(^|_)sem($|_)")
_SOCKISH_RE = re.compile(r"conn|sock|agent|worker|lessee|peer|client")
_ANNOT_RE = re.compile(
    r"#\s*lock-order:\s*(?P<kind>leaf|io-guard)\b"
    r"(?:\s*--\s*(?P<note>.*))?")
_NOQA_RE = re.compile(r"#\s*noqa:\s*([A-Z0-9, ]+)(--\s*(.*))?")
_SIGNAL_METHODS = {"set", "notify", "notify_all"}
_SPAWN_CALLEES = {"submit", "call_soon", "call_soon_threadsafe",
                  "add_done_callback", "run_in_executor"}

# Lock identity: (module path, class name or None, attr/name).  The
# creation line rides along so static locks map onto the runtime
# lockcheck's ``file:line`` lock classes.
LockKey = Tuple[str, Optional[str], str]


class _LockDef:
    __slots__ = ("key", "line", "kind", "note", "factory", "forwarded")

    def __init__(self, key: LockKey, line: int, kind: Optional[str],
                 note: str, factory: str, forwarded: bool = False):
        self.key = key
        self.line = line          # creation/binding line in key[0]
        self.kind = kind          # None | 'leaf' | 'io-guard'
        self.note = note
        self.factory = factory    # 'Lock' | 'RLock' | ... | 'param'
        # True when bound from a constructor parameter (`self.x = x`):
        # the real creation site is the caller's — excluded from the
        # runtime site mapping but still a graph node.
        self.forwarded = forwarded


class _Cls:
    __slots__ = ("module", "name", "node", "bases", "methods", "locks",
                 "events", "attr_types", "cond_alias")

    def __init__(self, module: "_Module", name: str, node: ast.ClassDef,
                 bases: Tuple[str, ...]):
        self.module = module
        self.name = name
        self.node = node
        self.bases = bases
        self.methods: Dict[str, ast.AST] = {}
        self.locks: Dict[str, _LockDef] = {}
        self.events: Dict[str, str] = {}     # attr -> 'Event'|'Condition'
        self.attr_types: Dict[str, str] = {}  # self.x = ClassName(...)
        # self.cv = threading.Condition(self.lock): cv IS self.lock.
        self.cond_alias: Dict[str, str] = {}


class _Fn:
    __slots__ = ("module", "cls", "name", "node", "parent", "children")

    def __init__(self, module: "_Module", cls: Optional[_Cls], name: str,
                 node: ast.AST, parent: Optional["_Fn"]):
        self.module = module
        self.cls = cls
        self.name = name
        self.node = node
        self.parent = parent
        self.children: Dict[str, "_Fn"] = {}

    @property
    def qual(self) -> str:
        base = os.path.splitext(os.path.basename(self.module.path))[0]
        mid = f"{self.cls.name}." if self.cls is not None else ""
        return f"{base}.{mid}{self.name}"


class _Module:
    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        base = os.path.basename(path)
        self.is_test = (base.startswith("test_")
                        or (os.sep + "tests" + os.sep) in path)
        self.classes: List[_Cls] = []
        self.fns: List[_Fn] = []
        self.module_locks: Dict[str, _LockDef] = {}
        self.module_events: Dict[str, str] = {}
        self.import_aliases: Dict[str, str] = {}  # alias -> basename.py

    def annotation(self, lineno: int) -> Tuple[Optional[str], str]:
        """('leaf'|'io-guard'|None, note) on the line or the one above."""
        for ln in (lineno, lineno - 1):
            if 1 <= ln <= len(self.lines):
                m = _ANNOT_RE.search(self.lines[ln - 1])
                if m:
                    return m.group("kind"), (m.group("note") or "").strip()
        return None, ""


def _is_factory(value: ast.AST, names: Set[str]) -> Optional[str]:
    if not isinstance(value, ast.Call):
        return None
    chain = _attr_chain(value.func)
    if chain and chain[-1] in names:
        return chain[-1]
    return None


# ---------------------------------------------------------------- parse --

class _Extractor(ast.NodeVisitor):
    """Pass 1, per module: classes, methods, lock/event attrs, attr
    types, import aliases.  No cross-module resolution yet."""

    def __init__(self, mod: _Module):
        self.mod = mod
        self.cls_stack: List[_Cls] = []
        self.fn_stack: List[_Fn] = []

    def visit_Import(self, node: ast.Import):
        for alias in node.names:
            local = alias.asname or alias.name.split(".")[0]
            self.mod.import_aliases[local] = \
                alias.name.split(".")[-1] + ".py"

    def visit_ImportFrom(self, node: ast.ImportFrom):
        if node.module and "ray_tpu" in node.module:
            for alias in node.names:
                self.mod.import_aliases[alias.asname or alias.name] = \
                    alias.name + ".py"

    def visit_ClassDef(self, node: ast.ClassDef):
        bases = tuple(c[-1] for c in
                      (_attr_chain(b) for b in node.bases) if c)
        info = _Cls(self.mod, node.name, node, bases)
        self.mod.classes.append(info)
        self.cls_stack.append(info)
        try:
            self.generic_visit(node)
        finally:
            self.cls_stack.pop()

    def _visit_fn(self, node):
        cls = None
        if self.cls_stack and node in self.cls_stack[-1].node.body:
            cls = self.cls_stack[-1]
        parent = self.fn_stack[-1] if self.fn_stack else None
        fn = _Fn(self.mod, cls, node.name, node, parent)
        self.mod.fns.append(fn)
        if cls is not None:
            cls.methods[node.name] = node
        if parent is not None:
            parent.children[node.name] = fn
        self.fn_stack.append(fn)
        try:
            self.generic_visit(node)
        finally:
            self.fn_stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def _ctor_call(self, value: ast.AST) -> Optional[ast.Call]:
        """The Call node a binding ultimately takes its type from —
        through a conditional (`X(...) if flag else None`)."""
        if isinstance(value, ast.Call):
            return value
        if isinstance(value, ast.IfExp):
            return self._ctor_call(value.body) or \
                self._ctor_call(value.orelse)
        return None

    def visit_Assign(self, node: ast.Assign):
        for target in node.targets:
            chain = _attr_chain(target)
            if not chain:
                continue
            if len(chain) == 2 and chain[0] == "self" and self.cls_stack:
                self._self_assign(self.cls_stack[-1], chain[1], node)
            elif len(chain) == 1 and not self.fn_stack \
                    and not self.cls_stack:
                self._module_assign(chain[0], node)
        self.generic_visit(node)

    def _self_assign(self, cls: _Cls, attr: str, node: ast.Assign):
        kind, note = self.mod.annotation(node.lineno)
        factory = _is_factory(node.value, LOCK_FACTORIES)
        if factory == "Condition" and isinstance(node.value, ast.Call) \
                and node.value.args:
            inner = _attr_chain(node.value.args[0])
            if inner and len(inner) == 2 and inner[0] == "self":
                # Condition(self.X): acquiring the condition IS
                # acquiring X — alias, not a new lock.
                cls.cond_alias[attr] = inner[1]
                cls.events[attr] = "Condition"
                return
        if factory:
            cls.locks[attr] = _LockDef(
                (self.mod.path, cls.name, attr), node.lineno, kind,
                note, factory)
            if factory == "Condition":
                cls.events[attr] = "Condition"
            return
        efactory = _is_factory(node.value, EVENT_FACTORIES)
        if efactory:
            cls.events[attr] = efactory
            return
        # `self.x = x` from a lockish constructor parameter: a forwarded
        # lock (created by the caller).  The annotation still binds here
        # so per-file tools (lint RTL402) see it.
        if isinstance(node.value, ast.Name) \
                and _LOCKISH_RE.search(attr.lower()) \
                and attr not in cls.locks:
            cls.locks[attr] = _LockDef(
                (self.mod.path, cls.name, attr), node.lineno, kind,
                note, "param", forwarded=True)
            return
        call = self._ctor_call(node.value)
        if call is not None:
            cchain = _attr_chain(call.func)
            if cchain and cchain[-1][:1].isupper():
                cls.attr_types[attr] = cchain[-1]

    def _module_assign(self, name: str, node: ast.Assign):
        kind, note = self.mod.annotation(node.lineno)
        factory = _is_factory(node.value, LOCK_FACTORIES)
        if factory:
            self.mod.module_locks[name] = _LockDef(
                (self.mod.path, None, name), node.lineno, kind, note,
                factory)
            if factory == "Condition":
                self.mod.module_events[name] = "Condition"
            return
        efactory = _is_factory(node.value, EVENT_FACTORIES)
        if efactory:
            self.mod.module_events[name] = efactory


# ------------------------------------------------------------- analysis --

class _Facts:
    """Direct (intra-function) effects of one function, nested defs
    excluded — they run at call time."""
    __slots__ = ("acquires", "signals", "blocking", "calls", "spawns")

    def __init__(self):
        # [(LockKey, line)] — with-entries and .acquire() sites.
        self.acquires: List[Tuple[LockKey, int]] = []
        # [(receiver LockKey or None, descr, line)]
        self.signals: List[Tuple[Optional[LockKey], str, int]] = []
        # [(descr, line)]
        self.blocking: List[Tuple[str, int]] = []
        # [(callee _Fn, line)] — synchronous edges (propagate locks).
        self.calls: List[Tuple[_Fn, int]] = []
        # [(descr, callee _Fn or None, line)] — deferred, dump-only.
        self.spawns: List[Tuple[str, Optional[_Fn], int]] = []


class Analysis:
    def __init__(self, paths):
        self.modules: List[_Module] = []
        self.findings: List[Finding] = []
        for path in _iter_py_files(paths):
            try:
                with open(path, "r", encoding="utf-8") as f:
                    source = f.read()
                tree = ast.parse(source, filename=path)
            except (OSError, SyntaxError):
                continue  # the lint gate owns syntax errors
            mod = _Module(path, source, tree)
            _Extractor(mod).visit(tree)
            self.modules.append(mod)
        self._build_registries()
        self._facts: Dict[int, _Facts] = {}
        self._fn_by_id: Dict[int, _Fn] = {}
        for mod in self.modules:
            for fn in mod.fns:
                self._fn_by_id[id(fn)] = fn
                self._facts[id(fn)] = self._extract_facts(fn)
        self._summaries = self._fixpoint_summaries()
        # (frm, to) -> (witness module path, line, path descr, to line)
        self.edges: Dict[Tuple[LockKey, LockKey],
                         Tuple[str, int, str, int]] = {}
        self._region_findings: List[Tuple] = []
        self._seen: Set[Tuple] = set()
        for mod in self.modules:
            for fn in mod.fns:
                self._analyze_regions(fn)

    # -- registries --------------------------------------------------------
    def _build_registries(self):
        self.cls_registry: Dict[str, _Cls] = {}
        ambiguous: Set[str] = set()
        for mod in self.modules:
            for cls in mod.classes:
                if cls.name in self.cls_registry:
                    ambiguous.add(cls.name)
                self.cls_registry[cls.name] = cls
        for name in ambiguous:
            self.cls_registry.pop(name, None)
        self.mod_by_base: Dict[str, _Module] = {}
        amb_mod: Set[str] = set()
        for mod in self.modules:
            base = os.path.basename(mod.path)
            if base in self.mod_by_base:
                amb_mod.add(base)
            self.mod_by_base[base] = mod
        for base in amb_mod:
            self.mod_by_base.pop(base, None)
        # Global event-attr name set (weak fallback for receivers whose
        # owner type is unresolvable).
        self.event_names: Set[str] = set()
        for mod in self.modules:
            self.event_names |= set(mod.module_events)
            for cls in mod.classes:
                self.event_names |= set(cls.events)
        self.locks: Dict[LockKey, _LockDef] = {}
        for mod in self.modules:
            for ld in mod.module_locks.values():
                self.locks[ld.key] = ld
            for cls in mod.classes:
                for ld in cls.locks.values():
                    self.locks[ld.key] = ld

    def _mro(self, cls: _Cls) -> List[_Cls]:
        out, seen = [cls], {cls.name}
        queue = list(cls.bases)
        while queue:
            b = queue.pop()
            if b in seen:
                continue
            seen.add(b)
            info = self.cls_registry.get(b)
            if info is not None:
                out.append(info)
                queue += list(info.bases)
        return out

    def _cls_lock(self, cls: _Cls, attr: str,
                  depth: int = 0) -> Optional[_LockDef]:
        if depth > 4:
            return None
        for c in self._mro(cls):
            if attr in c.cond_alias:
                return self._cls_lock(c, c.cond_alias[attr], depth + 1)
            if attr in c.locks:
                return c.locks[attr]
        return None

    # -- resolution --------------------------------------------------------
    def _resolve_lock(self, expr: ast.AST, fn: _Fn,
                      local_types: Dict[str, str]) -> Optional[_LockDef]:
        chain = _attr_chain(expr)
        if not chain:
            return None
        mod = fn.module
        if len(chain) == 1:
            return mod.module_locks.get(chain[0])
        if chain[0] == "self" and fn.cls is not None:
            if len(chain) == 2:
                return self._cls_lock(fn.cls, chain[1])
            if len(chain) == 3:
                tname = None
                for c in self._mro(fn.cls):
                    tname = c.attr_types.get(chain[1])
                    if tname:
                        break
                target = self.cls_registry.get(tname) if tname else None
                if target is not None:
                    return self._cls_lock(target, chain[2])
            return None
        if len(chain) == 2:
            # module-alias lock (protocol._NET_STATS_LOCK) or a typed
            # local (`lease.send_lock` with `lease = _Lease(...)`).
            target_mod = self.mod_by_base.get(
                mod.import_aliases.get(chain[0], ""))
            if target_mod is not None:
                return target_mod.module_locks.get(chain[1])
            tname = local_types.get(chain[0])
            target = self.cls_registry.get(tname) if tname else None
            if target is not None:
                return self._cls_lock(target, chain[1])
        return None

    def _resolve_event(self, chain: List[str], fn: _Fn,
                       local_types: Dict[str, str]
                       ) -> Optional[Tuple[Optional[LockKey], str]]:
        """(lock identity if the receiver is ALSO a lock/condition,
        descr) for a known Event/Condition receiver, else None."""
        mod = fn.module
        owner_cls: Optional[_Cls] = None
        attr = chain[-1]
        if len(chain) == 1:
            if attr in mod.module_events:
                ld = mod.module_locks.get(attr)
                return (ld.key if ld else None, attr)
            return None
        if chain[0] == "self" and fn.cls is not None:
            if len(chain) == 2:
                owner_cls = fn.cls
            elif len(chain) == 3:
                for c in self._mro(fn.cls):
                    tname = c.attr_types.get(chain[1])
                    if tname and tname in self.cls_registry:
                        owner_cls = self.cls_registry[tname]
                        break
        elif len(chain) == 2:
            tname = local_types.get(chain[0])
            if tname:
                owner_cls = self.cls_registry.get(tname)
        if owner_cls is not None:
            for c in self._mro(owner_cls):
                if attr in c.events:
                    ld = self._cls_lock(owner_cls, attr)
                    return (ld.key if ld else None,
                            f"{owner_cls.name}.{attr}")
            return None
        # Weak fallback: untyped receiver whose final attr is a known
        # event name somewhere in the tree (no lock identity).
        if attr in self.event_names:
            return (None, f"{chain[-2]}.{attr}")
        return None

    def _resolve_call(self, call: ast.Call, fn: _Fn,
                      local_types: Dict[str, str]) -> Optional[_Fn]:
        return self._resolve_ref(call.func, fn, local_types)

    def _resolve_ref(self, func: ast.AST, fn: _Fn,
                     local_types: Dict[str, str]) -> Optional[_Fn]:
        chain = _attr_chain(func)
        if not chain:
            return None
        mod = fn.module
        if len(chain) == 1:
            name = chain[0]
            # Nested def visible in the lexical scope chain.
            scope = fn
            while scope is not None:
                if name in scope.children:
                    return scope.children[name]
                scope = scope.parent
            hit = self._module_fn(mod, name)
            if hit is not None:
                return hit
            return self._ctor_init(self.cls_registry.get(name))
        if chain[0] == "self" and fn.cls is not None:
            if len(chain) == 2:
                return self._method(fn.cls, chain[1])
            if len(chain) == 3:
                for c in self._mro(fn.cls):
                    tname = c.attr_types.get(chain[1])
                    if tname and tname in self.cls_registry:
                        return self._method(
                            self.cls_registry[tname], chain[2])
            return None
        if len(chain) == 2:
            target_mod = self.mod_by_base.get(
                mod.import_aliases.get(chain[0], ""))
            if target_mod is not None:
                hit = self._module_fn(target_mod, chain[1])
                if hit is not None:
                    return hit
                for cls in target_mod.classes:
                    if cls.name == chain[1]:
                        return self._ctor_init(cls)
                return None
            tname = local_types.get(chain[0])
            if tname and tname in self.cls_registry:
                return self._method(self.cls_registry[tname], chain[1])
        return None

    def _module_fn(self, mod: _Module, name: str) -> Optional[_Fn]:
        for f in mod.fns:
            if f.name == name and f.cls is None and f.parent is None:
                return f
        return None

    def _method(self, cls: _Cls, name: str) -> Optional[_Fn]:
        for c in self._mro(cls):
            node = c.methods.get(name)
            if node is not None:
                for f in c.module.fns:
                    if f.node is node:
                        return f
        return None

    def _ctor_init(self, cls: Optional[_Cls]) -> Optional[_Fn]:
        return self._method(cls, "__init__") if cls is not None else None

    # -- pass 2: per-function facts ---------------------------------------
    def _extract_facts(self, fn: _Fn) -> _Facts:
        facts = _Facts()
        local_types: Dict[str, str] = {}

        def type_of_value(value: ast.AST) -> Optional[str]:
            if isinstance(value, ast.Call):
                chain = _attr_chain(value.func)
                if chain and chain[-1][:1].isupper():
                    return chain[-1]
            elif isinstance(value, ast.Attribute):
                chain = _attr_chain(value)
                if chain and len(chain) == 2 and chain[0] == "self" \
                        and fn.cls is not None:
                    for c in self._mro(fn.cls):
                        if chain[1] in c.attr_types:
                            return c.attr_types[chain[1]]
            elif isinstance(value, ast.IfExp):
                return type_of_value(value.body) \
                    or type_of_value(value.orelse)
            return None

        # Single pre-pass for local variable types (order-insensitive:
        # locks are usually taken after the assignment anyway).
        for stmt in ast.walk(fn.node):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                t = type_of_value(stmt.value)
                if t:
                    local_types[stmt.targets[0].id] = t

        def visit(node: ast.AST):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue  # runs at call time
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    for item in child.items:
                        ld = self._resolve_lock(item.context_expr, fn,
                                                local_types)
                        if ld is not None:
                            facts.acquires.append((ld.key, child.lineno))
                elif isinstance(child, ast.Call):
                    self._fact_call(child, fn, local_types, facts)
                visit(child)

        visit(fn.node)
        return facts

    def _fact_call(self, call: ast.Call, fn: _Fn,
                   local_types: Dict[str, str], facts: _Facts):
        chain = _attr_chain(call.func)
        leaf = chain[-1] if chain else None
        line = call.lineno
        if leaf == "acquire" and chain and len(chain) >= 2:
            ld = self._resolve_lock(call.func.value, fn, local_types)
            if ld is not None:
                facts.acquires.append((ld.key, line))
            return
        if leaf in _SIGNAL_METHODS and chain and len(chain) >= 2:
            hit = self._resolve_event(chain[:-1], fn, local_types)
            if hit is not None:
                facts.signals.append((hit[0], f"{hit[1]}.{leaf}()", line))
                return
        blocking = self._blocking_descr(chain)
        if blocking is not None:
            facts.blocking.append((blocking, line))
            return
        # Spawn edges: deferred callees (never propagate held locks).
        spawned = False
        if leaf == "Thread":
            for kw in call.keywords:
                if kw.arg == "target":
                    facts.spawns.append((
                        "Thread(target=...)",
                        self._resolve_ref(kw.value, fn, local_types),
                        line))
                    spawned = True
        elif leaf in _SPAWN_CALLEES:
            args = call.args
            ref = None
            if leaf == "run_in_executor" and len(args) >= 2:
                ref = args[1]
            elif args:
                ref = args[0]
            if ref is not None:
                facts.spawns.append((
                    f".{leaf}(...)",
                    self._resolve_ref(ref, fn, local_types), line))
            spawned = True
        if not spawned:
            target = self._resolve_call(call, fn, local_types)
            if target is not None and target is not fn:
                facts.calls.append((target, line))

    @staticmethod
    def _blocking_descr(chain: Optional[List[str]]) -> Optional[str]:
        """lint RTL402's blocking-call set, verbatim."""
        if not chain or len(chain) < 2:
            return None
        leaf, owner = chain[-1], chain[-2]
        if owner == "protocol" and leaf in ("send", "recv", "send_batch"):
            return f"protocol.{leaf}()"
        if leaf in ("send_bytes", "recv_bytes"):
            return f"{owner}.{leaf}()"
        if leaf in ("send", "recv") and _SOCKISH_RE.search(owner.lower()):
            return f"{owner}.{leaf}()"
        if owner == "pickle" and leaf in ("dumps", "loads"):
            return f"pickle.{leaf}()"
        if owner == "serialization" and (leaf.startswith("dumps")
                                         or leaf.startswith("loads")):
            return f"serialization.{leaf}()"
        return None

    # -- interprocedural summaries ----------------------------------------
    def _fixpoint_summaries(self) -> Dict[int, Dict]:
        """For every function: the effects reachable from calling it,
        as fact-key -> (origin, next-hop).  origin = (kind, payload,
        fn qual, module path, line); next-hop = (callee id, call line)
        or None when the fact is the function's own.  Computed as a
        worklist fixpoint so recursion converges."""
        summaries: Dict[int, Dict] = {}
        for fid, facts in self._facts.items():
            fn = self._fn_by_id[fid]
            direct = {}
            for key, line in facts.acquires:
                direct[("acquire", key)] = (
                    ("acquire", key, fn.qual, fn.module.path, line), None)
            for rid, descr, line in facts.signals:
                direct[("signal", rid, descr)] = (
                    ("signal", rid, fn.qual, fn.module.path, line,
                     descr), None)
            for descr, line in facts.blocking:
                direct[("blocking", fn.module.path, line, descr)] = (
                    ("blocking", descr, fn.qual, fn.module.path, line),
                    None)
            summaries[fid] = direct
        changed = True
        while changed:
            changed = False
            for fid, facts in self._facts.items():
                summary = summaries[fid]
                for callee, line in facts.calls:
                    for key, (origin, _hop) in \
                            summaries[id(callee)].items():
                        if key not in summary:
                            summary[key] = (origin, (id(callee), line))
                            changed = True
        return summaries

    def _chain_descr(self, fid: int, key, max_hops: int = 12) -> str:
        """'f (a.py:10) -> g (b.py:22)' call chain from fid to the
        function owning the fact."""
        steps = []
        seen = set()
        while max_hops > 0:
            max_hops -= 1
            entry = self._summaries.get(fid, {}).get(key)
            if entry is None:
                break
            origin, hop = entry
            if hop is None:
                break
            callee_id, line = hop
            if (fid, callee_id) in seen:
                break
            seen.add((fid, callee_id))
            callee = self._fn_by_id[callee_id]
            steps.append(f"{callee.qual} "
                         f"({_rel(callee.module.path)}:"
                         f"{callee.node.lineno})")
            fid = callee_id
        return " -> ".join(steps)

    # -- regions: edges + findings ----------------------------------------
    def _analyze_regions(self, fn: _Fn):
        facts = self._facts[id(fn)]
        local_types: Dict[str, str] = {}
        mod = fn.module

        def handle_effects(held: List[Tuple[_LockDef, int]],
                           target_fid: int, line: int):
            """Everything reachable through a call made at `line` while
            `held` locks are held."""
            for key, (origin, _hop) in \
                    self._summaries[target_fid].items():
                kind = origin[0]
                chain_descr = self._chain_descr(target_fid, key)
                via = self._fn_by_id[target_fid].qual
                path_descr = via if not chain_descr \
                    else f"{via} -> {chain_descr}"
                for ld, wline in held:
                    if kind == "acquire":  # noqa: RTL501 -- summary fact tag, not a wire verb
                        self._note_edge(ld, origin[1], mod, line,
                                        path_descr, origin[4])
                    elif kind == "signal" and ld.kind == "leaf":
                        self._note_signal(ld, origin, mod, line,
                                          path_descr)
                    elif kind == "blocking" and ld.kind != "io-guard":
                        # Anchor at the IO SITE, deduped per (lock,
                        # site): one region-side anchor per reaching
                        # path would repeat the same root cause dozens
                        # of times, and the fix (or the noqa) lives
                        # where the IO is.
                        dedup = ("RTL604", ld.key, origin[3], origin[4])
                        if dedup in self._seen:
                            continue
                        self._seen.add(dedup)
                        self._region_findings.append((
                            "RTL604", origin[3], origin[4],
                            f"blocking '{origin[1]}' is reachable "
                            f"through calls from a 'with "
                            f"{_fmt_lock(ld.key)}:' body (e.g. "
                            f"{_rel(mod.path)}:{line} via {path_descr})"
                            f" — holding the lock across IO stalls "
                            f"every other acquirer; move the IO "
                            f"outside the critical section, or "
                            f"suppress with a reason"))

        def visit(node: ast.AST, held: List[Tuple[_LockDef, int]]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                acquired: List[_LockDef] = []
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    for item in child.items:
                        ld = self._resolve_lock(item.context_expr, fn,
                                                local_types)
                        if ld is not None:
                            for h, hline in held:
                                self._note_edge(
                                    h, ld.key, mod, child.lineno, "",
                                    child.lineno)
                            acquired.append(ld)
                elif isinstance(child, ast.Call):
                    self._region_call(child, fn, local_types, held, mod,
                                      handle_effects)
                visit(child, held + [(ld, child.lineno)
                                     for ld in acquired])

        # Rebuild local types (cheap) — shared resolver needs them.
        for stmt in ast.walk(fn.node):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and isinstance(stmt.value, ast.Call):
                chain = _attr_chain(stmt.value.func)
                if chain and chain[-1][:1].isupper():
                    local_types[stmt.targets[0].id] = chain[-1]
        visit(fn.node, [])
        # Unused-variable guard for linters: facts is used above.
        del facts

    def _region_call(self, call: ast.Call, fn: _Fn, local_types, held,
                     mod, handle_effects):
        if not held:
            return
        chain = _attr_chain(call.func)
        leaf = chain[-1] if chain else None
        if leaf == "acquire" and chain and len(chain) >= 2:
            ld = self._resolve_lock(call.func.value, fn, local_types)
            if ld is not None:
                for h, _hl in held:
                    self._note_edge(h, ld.key, mod, call.lineno, "",
                                    call.lineno)
            return
        if leaf in _SIGNAL_METHODS and chain and len(chain) >= 2:
            hit = self._resolve_event(chain[:-1], fn, local_types)
            if hit is not None:
                rid, descr = hit
                for h, _hl in held:
                    if h.kind == "leaf" and rid != h.key:
                        self._region_findings.append((
                            "RTL603", mod.path, call.lineno,
                            f"'{descr}.{leaf}()' while holding "
                            f"{_fmt_lock(h.key)}, a declared leaf "
                            f"('# lock-order: leaf' at "
                            f"{_rel(h.key[0])}:{h.line}) — waking a "
                            f"waiter under the leaf hands it a "
                            f"contended lock; signal after releasing"))
                return
        if self._blocking_descr(chain) is not None:
            return  # lexical blocking-under-lock is lint RTL402's job
        if leaf == "Thread" or leaf in _SPAWN_CALLEES:
            return  # deferred: runs without these locks held
        target = self._resolve_call(call, fn, local_types)
        if target is not None and target is not fn:
            handle_effects(held, id(target), call.lineno)

    def _note_edge(self, held: _LockDef, to: LockKey, mod: _Module,
                   line: int, path_descr: str, to_line: int):
        if held.key == to:
            return  # re-entrant same-lock (RLock) / self-alias
        if (held.key, to) not in self.edges:
            self.edges[(held.key, to)] = (mod.path, line, path_descr,
                                          to_line)
        if held.kind == "leaf":
            via = f" via {path_descr}" if path_descr else ""
            self._region_findings.append((
                "RTL602", mod.path, line,
                f"{_fmt_lock(to)} is acquired while holding "
                f"{_fmt_lock(held.key)}, a declared leaf "
                f"('# lock-order: leaf' at {_rel(held.key[0])}:"
                f"{held.line}){via} — leaves must acquire nothing"))

    def _note_signal(self, held: _LockDef, origin, mod: _Module,
                     line: int, path_descr: str):
        rid = origin[1]
        if rid == held.key:
            return  # notifying the held condition itself
        self._region_findings.append((
            "RTL603", mod.path, line,
            f"'{origin[5]}' ({_rel(origin[3])}:{origin[4]}) is reached "
            f"while holding {_fmt_lock(held.key)}, a declared leaf "
            f"('# lock-order: leaf' at {_rel(held.key[0])}:{held.line})"
            f" via {path_descr} — signal after releasing the leaf"))

    # -- rules -------------------------------------------------------------
    def run(self, select: Optional[Set[str]] = None) -> List[Finding]:
        self.findings = []
        for rule, path, line, message in self._region_findings:
            self._emit(path, line, rule, message)
        self._check_cycles()
        seen: Set[str] = set()
        unique = []
        for f in self.findings:
            if repr(f) not in seen:
                seen.add(repr(f))
                unique.append(f)
        self.findings = unique
        kept = self._apply_suppressions()
        if select:
            kept = [f for f in kept
                    if any(f.rule.startswith(s) for s in select)]
        kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
        return kept

    def _emit(self, path: str, line: int, rule: str, message: str):
        self.findings.append(Finding(path, line, 0, rule, message))

    def _check_cycles(self):
        adj: Dict[LockKey, Set[LockKey]] = defaultdict(set)
        for (frm, to) in self.edges:
            adj[frm].add(to)
        for scc in _sccs(adj):
            if len(scc) < 2:
                continue
            in_scc = set(scc)
            cyc_edges = sorted(
                (e for e in self.edges
                 if e[0] in in_scc and e[1] in in_scc),
                key=lambda e: (self.edges[e][0], self.edges[e][1]))
            chain = " -> ".join(_fmt_lock(k) for k in
                                sorted(in_scc)) + " -> (cycle)"
            detail = "; ".join(
                f"{_fmt_lock(frm)} -> {_fmt_lock(to)} at "
                f"{_rel(self.edges[(frm, to)][0])}:"
                f"{self.edges[(frm, to)][1]}"
                + (f" via {self.edges[(frm, to)][2]}"
                   if self.edges[(frm, to)][2] else "")
                for frm, to in cyc_edges)
            path, line = self.edges[cyc_edges[0]][:2]
            self._emit(
                path, line, "RTL601",
                f"static lock-order cycle (potential deadlock): "
                f"{chain}; {detail} — pick one global order, or break "
                f"an edge by moving the inner acquisition outside")

    def _apply_suppressions(self) -> List[Finding]:
        by_path = {m.path: m for m in self.modules}
        kept: List[Finding] = []
        flagged: Set[Tuple[str, int]] = set()
        for f in self.findings:
            mod = by_path.get(f.path)
            line = (mod.lines[f.line - 1]
                    if mod and f.line <= len(mod.lines) else "")
            m = _NOQA_RE.search(line)
            rules = set()
            if m:
                rules = {tok for tok in
                         re.split(r"[\s,]+", m.group(1).upper()) if tok}
            if m and f.rule in rules:
                reason = (m.group(3) or "").strip()
                if not reason and (f.path, f.line) not in flagged:
                    flagged.add((f.path, f.line))
                    kept.append(Finding(
                        f.path, f.line, f.col, "RTL600",
                        f"suppression of {f.rule} carries no "
                        f"'-- reason' tail; concurrency-contract "
                        f"exceptions must say why"))
                continue
            kept.append(f)
        return kept

    # -- exports -----------------------------------------------------------
    def leaf_sites(self) -> Dict[str, str]:
        """Runtime-lockcheck site ('realpath:line') -> lock name, for
        every '# lock-order: leaf' creation site (forwarded bindings
        excluded: their creation line is the caller's)."""
        out = {}
        for ld in self.locks.values():
            if ld.kind == "leaf" and not ld.forwarded:
                out[f"{os.path.realpath(ld.key[0])}:{ld.line}"] = \
                    _fmt_lock(ld.key)
        return out

    def known_sites(self) -> Dict[str, LockKey]:
        """Every non-forwarded lock creation site, runtime-site keyed."""
        out = {}
        for ld in self.locks.values():
            if not ld.forwarded:
                out[f"{os.path.realpath(ld.key[0])}:{ld.line}"] = ld.key
        return out

    def site_edges(self) -> Set[Tuple[str, str]]:
        """Static edges as (creation-site, creation-site) pairs — the
        runtime lockcheck's vocabulary, for the superset cross-check."""
        site_of = {key: site for site, key in self.known_sites().items()}
        out = set()
        for (frm, to) in self.edges:
            sf, st = site_of.get(frm), site_of.get(to)
            if sf and st:
                out.add((sf, st))
        return out

    # -- inventory / doc ---------------------------------------------------
    def dump(self) -> str:
        out = ["== locks"]
        for ld in sorted(self.locks.values(),
                         key=lambda d: (d.key[0], d.line)):
            mark = f"  [{ld.kind}]" if ld.kind else ""
            fwd = "  (forwarded)" if ld.forwarded else ""
            out.append(f"  {_fmt_lock(ld.key):44} {ld.factory:10} "
                       f"{_rel(ld.key[0])}:{ld.line}{mark}{fwd}")
        out.append("== edges")
        for (frm, to), (path, line, descr, _tl) in sorted(
                self.edges.items(),
                key=lambda kv: (kv[1][0], kv[1][1])):
            via = f"  via {descr}" if descr else ""
            out.append(f"  {_fmt_lock(frm)} -> {_fmt_lock(to)}  "
                       f"[{_rel(path)}:{line}]{via}")
        out.append("== spawn edges (deferred; do not propagate locks)")
        for fid, facts in sorted(self._facts.items(),
                                 key=lambda kv: self._fn_by_id[
                                     kv[0]].qual):
            fn = self._fn_by_id[fid]
            for descr, target, line in facts.spawns:
                tgt = target.qual if target else "<unresolved>"
                out.append(f"  {fn.qual} --{descr}--> {tgt}  "
                           f"[{_rel(fn.module.path)}:{line}]")
        return "\n".join(out)

    def lock_order_doc(self) -> str:
        """The LOCK ORDER table (``--doc``): one row per known lock,
        its contract kind, creation site, and static outgoing edges —
        the single source the README embeds and tests pin."""
        lines = [
            "| lock | kind | created at | nests (static edges out) "
            "| note |",
            "|---|---|---|---|---|",
        ]
        out_edges: Dict[LockKey, List[LockKey]] = defaultdict(list)
        for (frm, to) in self.edges:
            out_edges[frm].append(to)
        for ld in sorted(self.locks.values(),
                         key=lambda d: (_rel(d.key[0]), d.line)):
            if ld.forwarded and ld.kind is None:
                continue  # alias rows without a contract add noise
            nests = ", ".join(
                f"`{_fmt_lock(t)}`"
                for t in sorted(out_edges.get(ld.key, []))) or "—"
            lines.append(
                f"| `{_fmt_lock(ld.key)}` | {ld.kind or ''} "
                f"| {_rel(ld.key[0])}:{ld.line} | {nests} "
                f"| {ld.note} |")
        return "\n".join(lines)


# ------------------------------------------------------------- helpers --

def _rel(path: str) -> str:
    """Path relative to the ray_tpu package root (stable in docs)."""
    norm = path.replace(os.sep, "/")
    marker = "ray_tpu/"
    idx = norm.rfind(marker)
    if idx >= 0:
        return norm[idx + len(marker):]
    return os.path.basename(path)


def _fmt_lock(key: LockKey) -> str:
    path, cls, attr = key
    base = os.path.splitext(os.path.basename(path))[0]
    return f"{base}.{cls + '.' if cls else ''}{attr}"


def _sccs(adj: Dict[LockKey, Set[LockKey]]) -> List[List[LockKey]]:
    """Iterative Tarjan strongly-connected components."""
    index: Dict[LockKey, int] = {}
    low: Dict[LockKey, int] = {}
    on_stack: Set[LockKey] = set()
    stack: List[LockKey] = []
    out: List[List[LockKey]] = []
    counter = [0]
    nodes = set(adj)
    for tos in adj.values():
        nodes |= tos

    for root in sorted(nodes):
        if root in index:
            continue
        work = [(root, iter(sorted(adj.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(adj.get(nxt, ())))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                out.append(comp)
    return out


# ------------------------------------------------------------------ api --

def _package_dir() -> str:
    import ray_tpu
    return os.path.dirname(os.path.abspath(ray_tpu.__file__))


def check_paths(paths, select: Optional[Set[str]] = None
                ) -> List[Finding]:
    return Analysis(paths).run(select=select)


def leaf_sites(paths=None) -> Dict[str, str]:
    """site ('realpath:line') -> name for every statically-annotated
    leaf — the registry the runtime lockcheck consumes, so the static
    and dynamic checkers cannot disagree about which locks are leaves."""
    return Analysis(paths or [_package_dir()]).leaf_sites()


def known_sites(paths=None) -> Dict[str, "LockKey"]:
    """Every non-forwarded lock creation site, runtime-site keyed —
    the vocabulary filter for the static-superset cross-check."""
    return Analysis(paths or [_package_dir()]).known_sites()


def site_edges(paths=None) -> Set[Tuple[str, str]]:
    """Static lock-nesting edges in creation-site terms."""
    return Analysis(paths or [_package_dir()]).site_edges()


def lock_order_doc(paths=None) -> str:
    return Analysis(paths or [_package_dir()]).lock_order_doc()


def main(argv=None) -> int:
    from ray_tpu.devtools.lint import run_cli

    argv = list(sys.argv[1:] if argv is None else argv)
    dump = "--dump" in argv
    if dump:
        argv.remove("--dump")

    def runner(paths, select):
        analysis = Analysis(paths)
        if dump:
            print(analysis.dump())
            return 0
        return analysis.run(select=select)

    return run_cli(
        argv, rules=RULES, doc=lock_order_doc, runner=runner,
        usage="usage: python -m ray_tpu.devtools.lockgraph "
              "[--doc|--dump|--list-rules] [--select=RTL6xx,...] "
              "PATH [PATH ...]")


if __name__ == "__main__":
    sys.exit(main())
