"""Command line interface: ``python -m ray_tpu.scripts <command>``.

Reference: ``python/ray/scripts/scripts.py`` (``ray start`` :529,
``status`` :1955, ``submit``, job CLI in ``dashboard/modules/job/cli.py``).
Condensed to the commands that matter for this runtime's topology:

  agent    join a running cluster as a node (the ``ray start`` analog for
           worker nodes: spawns a node_agent against the head address)
  status   cluster resources + nodes, over a client connection
  submit   submit a job (entrypoint command) to the cluster
  jobs     list jobs;  logs/stop act on one job
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _client(args):
    from ray_tpu._private.client import client_connect

    key = args.authkey or os.environ.get("RAY_TPU_CLIENT_AUTHKEY")
    if not key:
        sys.exit("need --authkey or RAY_TPU_CLIENT_AUTHKEY")
    return client_connect(args.address, bytes.fromhex(key))


def _cmd_agent(args):
    os.environ["RAY_TPU_HEAD_ADDRESS"] = args.address
    key = (args.authkey or os.environ.get("RAY_TPU_CLIENT_AUTHKEY")
           or os.environ.get("RAY_TPU_AUTHKEY"))
    if not key:
        sys.exit("need --authkey or RAY_TPU_CLIENT_AUTHKEY")
    os.environ["RAY_TPU_AUTHKEY"] = key
    resources = {"CPU": float(args.num_cpus)}
    if args.num_tpus:
        resources["TPU"] = float(args.num_tpus)
    if args.resources:
        resources.update(json.loads(args.resources))
    os.environ["RAY_TPU_AGENT_RESOURCES"] = json.dumps(resources)
    if args.shm_dir:
        os.environ["RAY_TPU_AGENT_SHM_DIR"] = args.shm_dir
    from ray_tpu._private.node_agent import main as agent_main

    agent_main()


def _cmd_status(args):
    rt = _client(args)
    info = rt.request(lambda rid: ("cluster_info", rid))
    print(f"session: {info['session_id']}")
    print(f"resources: {info['resources']}")
    print(f"available: {info['available']}")
    print(f"nodes ({len(info['nodes'])}):")
    for n in info["nodes"]:
        state = "ALIVE" if n["alive"] else "DEAD"
        print(f"  {n['node_id'][:12]}  {state:5}  {n['resources']}")
    rt.disconnect()


def _cmd_submit(args):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(args.address, _authkey=args.authkey)
    runtime_env = json.loads(args.runtime_env) if args.runtime_env else None
    import shlex

    entry = args.entrypoint
    if entry and entry[0] == "--":  # argparse.REMAINDER keeps the separator
        entry = entry[1:]
    # Re-quote: the manager shlex-splits the entrypoint string, so argv
    # tokens with spaces must survive the round trip.
    job_id = client.submit_job(
        entrypoint=" ".join(shlex.quote(t) for t in entry),
        runtime_env=runtime_env)
    print(f"submitted: {job_id}")
    if args.follow:
        for chunk in client.tail_job_logs(job_id, timeout=args.timeout):
            sys.stdout.write(chunk)
            sys.stdout.flush()
        print(f"status: {client.get_job_status(job_id)}")


def _cmd_jobs(args):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(args.address, _authkey=args.authkey)
    for j in client.list_jobs():
        print(f"{j['job_id']}  {j['status']:9}  {j['entrypoint']}")


def _cmd_logs(args):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(args.address, _authkey=args.authkey)
    sys.stdout.write(client.get_job_logs(args.job_id))


def _cmd_stop(args):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(args.address, _authkey=args.authkey)
    print(client.stop_job(args.job_id))


def _cmd_head(args):
    """Run a head process until SIGTERM (the launcher's `ray start
    --head` analog: fixed port + authkey so agents and clients can
    dial)."""
    import signal as _signal
    import time as _time

    import ray_tpu as ray

    rt = ray.init(num_cpus=float(args.num_cpus),
                  _system_config={"authkey_hex": args.authkey,
                                  "listen_port": int(args.port),
                                  "listen_host": args.host})
    print(f"head up at {rt.tcp_address}", flush=True)
    stop = {"flag": False}
    _signal.signal(_signal.SIGTERM,
                   lambda *_: stop.__setitem__("flag", True))
    try:
        while not stop["flag"]:
            _time.sleep(0.5)
    finally:
        ray.shutdown()


def _cmd_up(args):
    from ray_tpu.autoscaler.launcher import up

    up(args.config)


def _cmd_down(args):
    from ray_tpu.autoscaler.launcher import down

    down(args.config)


def _cmd_exec(args):
    import shlex

    from ray_tpu.autoscaler.launcher import exec_cmd

    entry = args.cmd
    if entry and entry[0] == "--":
        entry = entry[1:]
    # shlex re-quoting: argv tokens with spaces/metachars must survive
    # the shell=True round trip intact.
    sys.exit(exec_cmd(args.config,
                      " ".join(shlex.quote(t) for t in entry)))


def _cmd_attach(args):
    from ray_tpu.autoscaler.launcher import attach

    sys.exit(attach(args.config))


def _cmd_timeline(args):
    """``ray timeline`` analog (reference: scripts.py:1840): dump the
    cluster's task spans as chrome://tracing / Perfetto JSON."""
    rt = _client(args)
    try:
        spans = rt.request(
            lambda rid: ("state_req", rid, "spans", {"limit": 200000}))
        if isinstance(spans, Exception):
            raise spans
        from ray_tpu.util.tracing import chrome_trace

        events = chrome_trace(spans)
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(events, f)
        print(f"wrote {len(events)} events to {args.out}")
    finally:
        rt.disconnect()


def _cmd_handler_stats(args):
    rt = _client(args)
    try:
        stats = rt.request(
            lambda rid: ("state_req", rid, "handler_stats", {}))
        if isinstance(stats, Exception):
            raise stats
        for s in stats:
            print(f"{s['handler']:>18}  n={s['count']:<8} "
                  f"mean={s['mean_us']:>8.1f}us  max={s['max_ms']:>7.2f}ms "
                  f" total={s['total_ms']:.1f}ms")
    finally:
        rt.disconnect()


def main(argv=None):
    p = argparse.ArgumentParser(prog="ray_tpu",
                                description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="command", required=True)

    def common(sp):
        sp.add_argument("--address", required=True,
                        help="head address, tcp://host:port")
        sp.add_argument("--authkey", default=None,
                        help="cluster authkey hex (or env "
                             "RAY_TPU_CLIENT_AUTHKEY)")

    ag = sub.add_parser("agent", help="join the cluster as a node")
    common(ag)
    ag.add_argument("--num-cpus", type=float, default=1.0)
    ag.add_argument("--num-tpus", type=float, default=0.0)
    ag.add_argument("--resources", default=None, help="extra resources JSON")
    ag.add_argument("--shm-dir", default=None)
    ag.set_defaults(fn=_cmd_agent)

    st = sub.add_parser("status", help="cluster resources + nodes")
    common(st)
    st.set_defaults(fn=_cmd_status)

    sb = sub.add_parser("submit", help="submit a job")
    common(sb)
    sb.add_argument("--runtime-env", default=None, help="JSON runtime env")
    sb.add_argument("--follow", action="store_true")
    sb.add_argument("--timeout", type=float, default=600.0)
    sb.add_argument("entrypoint", nargs=argparse.REMAINDER)
    sb.set_defaults(fn=_cmd_submit)

    jb = sub.add_parser("jobs", help="list jobs")
    common(jb)
    jb.set_defaults(fn=_cmd_jobs)

    lg = sub.add_parser("logs", help="print a job's logs")
    common(lg)
    lg.add_argument("job_id")
    lg.set_defaults(fn=_cmd_logs)

    sp = sub.add_parser("stop", help="stop a running job")
    common(sp)
    sp.add_argument("job_id")
    sp.set_defaults(fn=_cmd_stop)

    hd = sub.add_parser(
        "head", help="run a head process (fixed port + authkey)")
    hd.add_argument("--num-cpus", type=float, default=4.0)
    hd.add_argument("--port", type=int, required=True)
    hd.add_argument("--authkey", required=True)
    hd.add_argument("--host", default="127.0.0.1")
    hd.set_defaults(fn=_cmd_head)

    for cname, fn, extra in (("up", _cmd_up, None),
                             ("down", _cmd_down, None),
                             ("attach", _cmd_attach, None)):
        cp = sub.add_parser(
            cname, help=f"{cname} a cluster from a YAML config "
                        f"(launcher; reference: ray {cname})")
        cp.add_argument("config")
        cp.set_defaults(fn=fn)

    ex = sub.add_parser(
        "exec", help="run a shell command wired to a launched cluster")
    ex.add_argument("config")
    ex.add_argument("cmd", nargs=argparse.REMAINDER)
    ex.set_defaults(fn=_cmd_exec)

    tl = sub.add_parser(
        "timeline", help="dump task timeline as Chrome trace JSON")
    common(tl)
    tl.add_argument("--out", default="ray_tpu_timeline.json")
    tl.set_defaults(fn=_cmd_timeline)

    hs = sub.add_parser(
        "handler-stats", help="head per-message-handler latency stats")
    common(hs)
    hs.set_defaults(fn=_cmd_handler_stats)

    args = p.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
