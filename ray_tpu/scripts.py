"""Command line interface: ``python -m ray_tpu.scripts <command>``.

Reference: ``python/ray/scripts/scripts.py`` (``ray start`` :529,
``status`` :1955, ``submit``, job CLI in ``dashboard/modules/job/cli.py``).
Condensed to the commands that matter for this runtime's topology:

  agent    join a running cluster as a node (the ``ray start`` analog for
           worker nodes: spawns a node_agent against the head address)
  status   cluster resources + nodes, over a client connection
  submit   submit a job (entrypoint command) to the cluster
  jobs     list jobs;  logs/stop act on one job
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _client(args):
    from ray_tpu._private.client import client_connect

    key = args.authkey or os.environ.get("RAY_TPU_CLIENT_AUTHKEY")
    if not key:
        sys.exit("need --authkey or RAY_TPU_CLIENT_AUTHKEY")
    return client_connect(args.address, bytes.fromhex(key))


def _cmd_agent(args):
    os.environ["RAY_TPU_HEAD_ADDRESS"] = args.address
    key = (args.authkey or os.environ.get("RAY_TPU_CLIENT_AUTHKEY")
           or os.environ.get("RAY_TPU_AUTHKEY"))
    if not key:
        sys.exit("need --authkey or RAY_TPU_CLIENT_AUTHKEY")
    os.environ["RAY_TPU_AUTHKEY"] = key
    resources = {"CPU": float(args.num_cpus)}
    if args.num_tpus:
        resources["TPU"] = float(args.num_tpus)
    if args.resources:
        resources.update(json.loads(args.resources))
    os.environ["RAY_TPU_AGENT_RESOURCES"] = json.dumps(resources)
    if args.shm_dir:
        os.environ["RAY_TPU_AGENT_SHM_DIR"] = args.shm_dir
    from ray_tpu._private.node_agent import main as agent_main

    agent_main()


def _cmd_status(args):
    rt = _client(args)
    info = rt.request(lambda rid: ("cluster_info", rid))
    print(f"session: {info['session_id']}")
    print(f"resources: {info['resources']}")
    print(f"available: {info['available']}")
    print(f"nodes ({len(info['nodes'])}):")
    for n in info["nodes"]:
        state = "ALIVE" if n["alive"] else "DEAD"
        print(f"  {n['node_id'][:12]}  {state:5}  {n['resources']}")
    rt.disconnect()


def _cmd_submit(args):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(args.address, _authkey=args.authkey)
    runtime_env = json.loads(args.runtime_env) if args.runtime_env else None
    import shlex

    entry = args.entrypoint
    if entry and entry[0] == "--":  # argparse.REMAINDER keeps the separator
        entry = entry[1:]
    # Re-quote: the manager shlex-splits the entrypoint string, so argv
    # tokens with spaces must survive the round trip.
    job_id = client.submit_job(
        entrypoint=" ".join(shlex.quote(t) for t in entry),
        runtime_env=runtime_env)
    print(f"submitted: {job_id}")
    if args.follow:
        for chunk in client.tail_job_logs(job_id, timeout=args.timeout):
            sys.stdout.write(chunk)
            sys.stdout.flush()
        print(f"status: {client.get_job_status(job_id)}")


def _cmd_jobs(args):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(args.address, _authkey=args.authkey)
    for j in client.list_jobs():
        print(f"{j['job_id']}  {j['status']:9}  {j['entrypoint']}")


def _cmd_logs(args):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(args.address, _authkey=args.authkey)
    sys.stdout.write(client.get_job_logs(args.job_id))


def _cmd_stop(args):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(args.address, _authkey=args.authkey)
    print(client.stop_job(args.job_id))


def main(argv=None):
    p = argparse.ArgumentParser(prog="ray_tpu",
                                description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="command", required=True)

    def common(sp):
        sp.add_argument("--address", required=True,
                        help="head address, tcp://host:port")
        sp.add_argument("--authkey", default=None,
                        help="cluster authkey hex (or env "
                             "RAY_TPU_CLIENT_AUTHKEY)")

    ag = sub.add_parser("agent", help="join the cluster as a node")
    common(ag)
    ag.add_argument("--num-cpus", type=float, default=1.0)
    ag.add_argument("--num-tpus", type=float, default=0.0)
    ag.add_argument("--resources", default=None, help="extra resources JSON")
    ag.add_argument("--shm-dir", default=None)
    ag.set_defaults(fn=_cmd_agent)

    st = sub.add_parser("status", help="cluster resources + nodes")
    common(st)
    st.set_defaults(fn=_cmd_status)

    sb = sub.add_parser("submit", help="submit a job")
    common(sb)
    sb.add_argument("--runtime-env", default=None, help="JSON runtime env")
    sb.add_argument("--follow", action="store_true")
    sb.add_argument("--timeout", type=float, default=600.0)
    sb.add_argument("entrypoint", nargs=argparse.REMAINDER)
    sb.set_defaults(fn=_cmd_submit)

    jb = sub.add_parser("jobs", help="list jobs")
    common(jb)
    jb.set_defaults(fn=_cmd_jobs)

    lg = sub.add_parser("logs", help="print a job's logs")
    common(lg)
    lg.add_argument("job_id")
    lg.set_defaults(fn=_cmd_logs)

    sp = sub.add_parser("stop", help="stop a running job")
    common(sp)
    sp.add_argument("job_id")
    sp.set_defaults(fn=_cmd_stop)

    args = p.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
