"""User-facing exception hierarchy.

Parity with the reference's ``python/ray/exceptions.py`` (RayError,
RayTaskError, RayActorError, ObjectLostError, GetTimeoutError, ...).  The
semantics mirror the ownership model: a task failure is delivered to whoever
``get``s any of its return objects (reference:
``src/ray/core_worker/task_manager.h:90`` stores errors as objects).
"""

from __future__ import annotations

import traceback


class RayTpuError(Exception):
    """Base class for all framework errors."""


# Alias matching the reference spelling for drop-in familiarity.
RayError = RayTpuError


class TaskError(RayTpuError):
    """A remote task raised; re-raised at the caller's ``get``
    (reference: python/ray/exceptions.py RayTaskError)."""

    def __init__(self, function_name: str, cause_repr: str, tb_str: str,
                 cause: BaseException | None = None):
        self.function_name = function_name
        self.cause_repr = cause_repr
        self.tb_str = tb_str
        self.cause = cause
        super().__init__(self._format())

    def _format(self) -> str:
        return (
            f"Task {self.function_name} failed.\n"
            f"{self.tb_str}"
        )

    def __reduce__(self):
        return (TaskError, (self.function_name, self.cause_repr,
                            self.tb_str, self.cause))

    @classmethod
    def from_exception(cls, function_name: str, exc: BaseException) -> "TaskError":
        tb = "".join(traceback.format_exception(type(exc), exc, exc.__traceback__))
        # Keep the original exception when it pickles cleanly so callers can
        # except on its type; fall back to the repr otherwise.
        return cls(function_name, repr(exc), tb, cause=exc)


RayTaskError = TaskError


class ActorError(RayTpuError):
    """The actor died before or during this call
    (reference: python/ray/exceptions.py RayActorError)."""


RayActorError = ActorError


class ActorDiedError(ActorError):
    pass


class ActorUnavailableError(ActorError):
    pass


class WorkerCrashedError(RayTpuError):
    """Worker process died while executing a task
    (reference: WORKER_DIED error type in common.proto)."""


class OutOfMemoryError(WorkerCrashedError):
    """The memory monitor killed the task's worker under node memory
    pressure and its retries are exhausted (reference:
    src/ray/common/memory_monitor.h + OUT_OF_MEMORY error type —
    kill retriable tasks before the kernel OOM-killer takes the node)."""


class ObjectLostError(RayTpuError):
    """Object's value is unrecoverable (owner gone, store evicted and no
    lineage).

    One constructor for every raise site, carrying structured fields the
    recovery subsystem keys off (reference: the typed error-object
    payloads of ``common.proto`` — OBJECT_UNRECONSTRUCTABLE and friends
    carry the object/owner identity, not prose):

    - ``object_id``: hex of the lost object (when known),
    - ``owner``: who held its metadata ("driver", a worker id hex, ...),
    - ``home``: last-known home store id of the segment,
    - ``phase``: where the loss was observed ("get", "pull", "dispatch",
      "relay", "recover", ...).

    ``reconstructable`` is the class-level recovery gate: lineage MAY
    rebuild plain lost objects; subclasses for freed objects and dead
    owners opt out — recovery refuses those by type, not by message
    text."""

    reconstructable = True

    def __init__(self, message: str | None = None, *,
                 object_id: str | None = None, owner: str | None = None,
                 home: str | None = None, phase: str | None = None):
        self.object_id = object_id
        self.owner = owner
        self.home = home
        self.phase = phase
        super().__init__(message if message is not None else self._format())

    def _format(self) -> str:
        parts = [f"Object {self.object_id or '<unknown>'} is lost"]
        detail = [f"{k}={v}" for k, v in (("phase", self.phase),
                                          ("home", self.home),
                                          ("owner", self.owner)) if v]
        if detail:
            parts.append(f" ({', '.join(detail)})")
        parts.append("" if type(self) is not ObjectLostError
                     else "; no lineage survives to reconstruct it")
        return "".join(parts)

    def __reduce__(self):
        return (_rebuild_object_lost,
                (type(self), self.args[0] if self.args else None,
                 self.object_id, self.owner, self.home, self.phase))


def _rebuild_object_lost(cls, message, object_id, owner, home, phase):
    return cls(message, object_id=object_id, owner=owner, home=home,
               phase=phase)


class ObjectFreedError(ObjectLostError):
    """The object was explicitly freed / its last reference dropped —
    never reconstructable (reference: OBJECT_FREED error type)."""

    reconstructable = False


class OwnerDiedError(ObjectLostError):
    """The object's owner process died; its metadata (and lineage) died
    with it — never reconstructable (reference: OWNER_DIED)."""

    reconstructable = False


class GetTimeoutError(RayTpuError, TimeoutError):
    """``ray.get(timeout=...)`` expired."""


class TaskCancelledError(RayTpuError):
    """Task was cancelled with ``ray.cancel``."""


class RuntimeEnvSetupError(RayTpuError):
    pass


class PendingCallsLimitExceeded(RayTpuError):
    pass


class NodeDiedError(RayTpuError):
    pass
