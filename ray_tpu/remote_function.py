"""@remote functions.

Reference: ``python/ray/remote_function.py:35`` (RemoteFunction, ``_remote``
:241) — a decorated function becomes a handle whose ``.remote(*args)``
serializes arguments, registers the function once (content-addressed, like
the reference's function table exported via GCS KV,
``python/ray/_private/function_manager.py``), and submits a task spec to the
runtime.  ``.options(**overrides)`` returns a shallow clone, same as the
reference's options protocol (``python/ray/_private/ray_option_utils.py``).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu._private import serialization
from ray_tpu._private.api_internal import require_runtime
from ray_tpu._private.ids import new_task_id
from ray_tpu._private.object_ref import ObjectRef

_VALID_OPTIONS = {
    "num_cpus", "num_tpus", "num_gpus", "resources", "num_returns",
    "max_retries", "name", "runtime_env", "scheduling_strategy",
    "memory", "retry_exceptions", "_metadata",
}


def _normalize_resources(opts: Dict[str, Any]) -> Dict[str, float]:
    req: Dict[str, float] = {}
    num_cpus = opts.get("num_cpus")
    req["CPU"] = float(1 if num_cpus is None else num_cpus)
    if opts.get("num_tpus"):
        req["TPU"] = float(opts["num_tpus"])
    if opts.get("num_gpus"):
        # GPU requests map onto the TPU resource pool so reference code
        # written against num_gpus schedules unchanged on a TPU node.
        req["TPU"] = float(opts["num_gpus"])
    if opts.get("memory"):
        req["memory"] = float(opts["memory"])
    for k, v in (opts.get("resources") or {}).items():
        req[k] = float(v)
    req = {k: v for k, v in req.items() if v != 0}
    return req or {"CPU": 0.0}


def _strategy_tuple(strategy):
    if strategy is None:
        return None
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
        PlacementGroupSchedulingStrategy,
    )

    if isinstance(strategy, PlacementGroupSchedulingStrategy):
        return ("placement_group",
                strategy.placement_group.id.binary(),
                strategy.placement_group_bundle_index or 0)
    if isinstance(strategy, NodeAffinitySchedulingStrategy):
        return ("node_affinity", strategy.node_id, strategy.soft)
    if strategy == "SPREAD":
        return ("spread",)
    if strategy == "DEFAULT":
        return None
    raise ValueError(f"Unknown scheduling strategy: {strategy!r}")


def serialize_args(rt, args, kwargs, spec):
    """Top-level args: refs stay refs (dependencies); values become
    descriptors (reference: inline vs plasma promotion at submit,
    ``src/ray/core_worker/core_worker.cc`` SubmitTask arg handling)."""
    tmp_segments = []

    def one(a, where):
        if isinstance(a, ObjectRef):
            return ("ref", a.id().binary())
        from ray_tpu._private.ids import ObjectID

        oid = ObjectID.for_put()
        try:
            descr = rt.serialize_value(a, oid)
        except Exception as err:  # noqa: BLE001 — diagnosed and re-raised
            # A raw "cannot pickle _thread.lock" from three frames deep is
            # useless for a 40-field config; walk the argument and name
            # the exact leaf (e.g. arg[0].fn.__closure__['model']).
            from ray_tpu.devtools.serializability import diagnose_pickle_error

            diagnose_pickle_error(a, where, err)
        if descr[0] in ("shm", "spilled"):
            # Ephemeral arg storage (segment name, or spill-file path when
            # the store was full) — freed when the task / its lineage ends.
            tmp_segments.append((descr[1], descr[2]))
        return descr

    # Refs nested inside argument containers are collected during pickling
    # and pinned by the runtime until the task completes (simplified borrow
    # protocol; reference: reference_count.cc borrowed refs).
    rt.begin_ref_collection()
    try:
        try:
            spec["args"] = [one(a, f"arg[{i}]") for i, a in enumerate(args)]
            spec["kwargs"] = {k: one(v, f"kwargs[{k!r}]")
                              for k, v in (kwargs or {}).items()}
        except BaseException:
            # The spec is never submitted, so the runtime's task-end path
            # will never free segments already written for EARLIER args;
            # a retried failing call would otherwise leak one per attempt.
            import os as _os

            shm = getattr(rt, "shm", None)
            for name, size in tmp_segments:
                try:
                    if _os.path.isabs(name):
                        # Spill file (store-full fallback): plain unlink —
                        # routing it through ShmStore.unlink would debit
                        # shm accounting for bytes never charged to it
                        # (mirrors runtime._release_spec_resources).
                        _os.unlink(name)
                    elif shm is not None:
                        shm.unlink(name, size)
                except Exception:
                    pass
            raise
    finally:
        spec["nested_refs"] = rt.end_ref_collection()
    spec["tmp_segments"] = tmp_segments


class RemoteFunction:
    def __init__(self, fn, options: Optional[Dict[str, Any]] = None):
        for k in options or {}:
            if k not in _VALID_OPTIONS:
                raise ValueError(f"Invalid @remote option {k!r}")
        self._fn = fn
        self._options = dict(options or {})
        self._payload: Optional[bytes] = None
        self._func_id: Optional[str] = None
        self._registered_with: Optional[str] = None
        # Options never change after construction (.options() clones), so
        # the normalized resource dict and strategy tuple are computed
        # once — the per-call work on the fan-out hot path is then dict
        # copies only.
        self._req_cache: Optional[Dict[str, float]] = None
        self._strategy_cache = None
        self.__name__ = getattr(fn, "__name__", "remote_fn")
        self.__doc__ = getattr(fn, "__doc__", None)

    def __call__(self, *a, **kw):
        raise TypeError(
            f"Remote function {self.__name__} cannot be called directly; "
            f"use {self.__name__}.remote().")

    def options(self, **overrides) -> "RemoteFunction":
        merged = dict(self._options)
        merged.update(overrides)
        clone = RemoteFunction(self._fn, merged)
        clone._payload = self._payload
        clone._func_id = self._func_id
        return clone

    def _ensure_registered(self, rt):
        if self._payload is None:
            try:
                self._payload = serialization.dumps_inline(self._fn)
            except Exception as err:  # noqa: BLE001 — diagnosed, re-raised
                from ray_tpu.devtools.serializability import (
                    diagnose_pickle_error,
                )

                diagnose_pickle_error(self._fn, self.__name__, err)
        if rt.is_worker():
            import hashlib

            if self._func_id is None:
                self._func_id = hashlib.sha1(self._payload).hexdigest()[:24]
            return self._func_id, self._payload
        # Register once per runtime SESSION (re-registering after
        # shutdown/init matters; re-hashing on every .remote() does not).
        # Keyed by session_id, not id(rt): a new Runtime can reuse the
        # freed old one's memory address.
        session = getattr(rt, "session_id", None)
        if self._func_id is None or self._registered_with != session:
            self._func_id = rt.register_function(self._payload)
            self._registered_with = session
        return self._func_id, None

    def bind(self, *args, **kwargs):
        """Lazy DAG node instead of immediate submission (reference:
        python/ray/dag — fn.bind builds a FunctionNode)."""
        from ray_tpu.dag.node import FunctionNode

        return FunctionNode(self, args, kwargs)

    def _build_spec(self, rt, args, kwargs):
        """Spec for one call (shared by .remote and _bulk_submit)."""
        func_id, payload = self._ensure_registered(rt)
        opts = self._options
        if self._req_cache is None:
            self._req_cache = _normalize_resources(opts)
            self._strategy_cache = _strategy_tuple(
                opts.get("scheduling_strategy"))
        num_returns = opts.get("num_returns", 1)
        spec = {
            "task_id": new_task_id().binary(),
            "func_id": func_id,
            "num_returns": num_returns,
            "name": opts.get("name") or self.__name__,
            "resources": dict(self._req_cache),
            "max_retries": opts.get("max_retries", 3),
            "runtime_env": opts.get("runtime_env"),
            "scheduling_strategy": self._strategy_cache,
        }
        # max_retries budgets SYSTEM failures (worker/node death) only;
        # application exceptions retry solely under this opt-in (True =
        # any app error, or exception type(s) matched against the task
        # error's cause) — reference: retry_exceptions on @ray.remote.
        # Carried only when set so default specs stay lean; a bare
        # class (the natural shorthand) normalizes to a one-element
        # list, and anything else non-boolean must be iterable —
        # silently ignoring a malformed opt-in would fail the user's
        # task permanently with no hint the option never applied.
        rexc = opts.get("retry_exceptions")
        if rexc is not None:
            if isinstance(rexc, type) and issubclass(rexc, BaseException):
                rexc = [rexc]
            elif isinstance(rexc, (list, tuple)):
                bad = [t for t in rexc
                       if not (isinstance(t, type)
                               and issubclass(t, BaseException))]
                if bad:
                    raise TypeError(
                        "retry_exceptions entries must be exception "
                        f"types; got {bad!r}")
            elif not isinstance(rexc, bool):
                raise TypeError(
                    "retry_exceptions must be True/False, an exception "
                    f"type, or a list of exception types; got {rexc!r}")
            spec["retry_exceptions"] = rexc
        serialize_args(rt, args, kwargs, spec)
        if payload is not None and rt.is_worker():
            spec["func_payload"] = payload
        return spec, num_returns

    def remote(self, *args, **kwargs):
        rt = require_runtime()
        spec, num_returns = self._build_spec(rt, args, kwargs)
        refs = rt.submit_task(spec)
        if num_returns == 0:
            return None
        if num_returns == 1:
            return refs[0]
        return refs


def _bulk_submit(calls):
    """Internal fan-out helper: ``calls`` is a sequence of
    (handle, args, kwargs) triples where ``handle`` is a RemoteFunction
    or an ActorMethod.  Builds every spec up front, then submits the
    whole list through the runtime's bulk path — ONE lock acquisition
    and one dispatch pass instead of n (reference: the batched gRPC
    submissions of direct_task_transport.cc).  Returns exactly what the
    n individual ``handle.remote(*args, **kwargs)`` calls would have."""
    rt = require_runtime()
    specs = []
    counts = []
    for handle, args, kwargs in calls:
        spec, num_returns = handle._build_spec(rt, args, kwargs or {})
        specs.append(spec)
        counts.append(num_returns)
    out = []
    for num_returns, refs in zip(counts, rt.submit_tasks(specs)):
        if num_returns == 0:
            out.append(None)
        elif num_returns == 1:
            out.append(refs[0])
        else:
            out.append(refs)
    return out


def remote_decorator(options: Optional[Dict[str, Any]] = None):
    def wrap(fn_or_cls):
        import inspect

        if inspect.isclass(fn_or_cls):
            from ray_tpu.actor import ActorClass

            return ActorClass(fn_or_cls, options)
        return RemoteFunction(fn_or_cls, options)

    return wrap
