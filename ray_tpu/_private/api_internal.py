"""Runtime access shared by the public API, ObjectRef, and handles.

One accessor that answers "which runtime am I in?" — the driver's Runtime or
a worker's _WorkerRuntime (reference: the global_worker singleton,
``python/ray/_private/worker.py``)."""

from __future__ import annotations

from typing import Optional

from ray_tpu._private import object_ref as _object_ref_mod

_global_runtime = None


def get_runtime():
    from ray_tpu._private.worker_main import get_worker_runtime

    wr = get_worker_runtime()
    if wr is not None:
        return wr
    return _global_runtime


def set_global_runtime(rt):
    global _global_runtime
    _global_runtime = rt


def require_runtime():
    rt = get_runtime()
    if rt is None:
        raise RuntimeError(
            "ray_tpu is not initialized; call ray_tpu.init() first.")
    return rt


_object_ref_mod._set_runtime_accessor(get_runtime)
