"""Caller-side ownership + direct worker↔worker task push.

This is the TPU-era re-design of the reference's ownership architecture
(``src/ray/core_worker/transport/direct_task_transport.cc:568`` — callers
lease workers from the scheduler and push tasks to them directly, and
``src/ray/core_worker/reference_count.h:61`` — the caller *owns* its tasks'
returns and is the metadata authority for them).  The head grants worker
leases (resource accounting only); task specs, results, object descriptors
and reference counts for worker-submitted work never touch the head.  This
is what makes N concurrent clients scale: in the v1 design every submit,
result, put and decref funneled through the head's single mailbox, which
collapsed multi-client throughput (the reference's microbenchmarks run 4
independent drivers for exactly this reason).

Two halves:

- ``DirectServer``: runs inside every worker.  A TCP listener (cluster
  authkey) accepting connections from peer workers; each connection can
  push ``dexec`` tasks that flow into the worker's normal execution queue,
  with replies routed back on the originating connection.
- ``DirectCaller``: runs inside every worker (and, via the same interface,
  the driver).  Keeps the *owned object table* (our ownership analog of
  ``reference_count.h``), per-scheduling-class lease pools, caller-side
  dependency resolution, pipelined pushes, and executor-death resubmits.

Fallbacks: anything the direct path does not cover (placement groups,
runtime_env, TPU resources, non-owned ref args, lease starvation) routes
through the existing head path, with owned return refs *delegated* to the
head so both paths share one lifetime story.

Data plane: the direct path never moves payload bytes itself.  Results
and big args travel as SHM *location* descriptors (name, size, store);
a consumer on another node resolves the store's object-server address
through the head once (``store_addr`` — address + verb caps) and pulls
the segment over pooled, striped connections straight into local shm
(object_transfer.py).  The head-relayed ``getparts`` path stays as the
fallback for consumers without direct reachability.

Wire contract: every verb this module sends or handles (``dexec``/
``dexec_batch``/``dfunc``/``dfree``/``dmsg``/``dresult``/
``dresult_batch``/``dspill`` on the direct plane, plus the lease and
ownership-delegation verbs to the head) is declared in
``protocol.VERBS`` and machine-checked against these sites by
``python -m ray_tpu.devtools.protocheck`` (roles, arity, caps gating).
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu._private import protocol, recovery, serialization
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.ids import ObjectID, TaskID
from ray_tpu import exceptions as exc

# Owned-object status values.
PENDING = 0
READY = 1
ERRORED = 2
DELEGATED = 3  # handed to the head (exported or rerouted); head is authority

PIPELINE_DEPTH = 8       # default unacked pushes per leased worker (a v1
#                          lease grant overrides this with its slot count)
MAX_LEASES_PER_REQ = 8
LEASE_LINGER_S = 0.2     # idle time before a lease is returned to the head
REROUTE_CHUNK = 32       # specs sent via the head per failed lease round
ACTOR_PIPELINE = 64      # max unacked direct pushes per actor channel
SPILL_MAX = 3            # spillbacks before an entry reroutes to the head
SATURATED_S = 0.1        # how long a spilled-off lease is deprioritized


class OwnedState:
    """Caller-side record of one owned object (reference_count.h:61 — the
    owner holds status, descriptor, refcounts and waiters)."""

    __slots__ = (
        "status", "descr", "local_refs", "pins", "task_id_bin",
        "nested_local", "nested_head", "attached", "shipped", "creator",
    )

    def __init__(self, task_id_bin: Optional[bytes] = None):
        self.status = PENDING
        self.descr = None
        self.local_refs = 0
        self.pins = 0              # inflight-spec / nested-container pins
        self.task_id_bin = task_id_bin  # producing task (resubmit lineage)
        self.nested_local = []     # owned oid_bins pinned inside this value
        self.nested_head = []      # head-owned oid_bins this entry holds +1 on
        self.attached = False      # we mmap'd the segment (no pool reuse)
        self.shipped = False       # descriptor left this process
        self.creator = None        # _Lease whose worker created the segment


class _Lease:
    """One leased executor worker + its direct connection."""

    __slots__ = ("worker_id", "addr", "conn", "send_lock", "inflight",
                 "funcs_sent", "dead", "idle_since", "klass",
                 "outbuf", "buf_lock", "node_hex", "slots", "pushed",
                 "last_renew", "saturated_until", "ttl", "last_recv",
                 "ping_sent")

    def __init__(self, worker_id: str, addr, klass, node_hex=None,
                 slots=PIPELINE_DEPTH, ttl=0.0):
        self.worker_id = worker_id
        self.addr = addr
        self.conn = None
        self.send_lock = threading.Lock()  # lock-order: io-guard
        self.inflight: Dict[int, dict] = {}  # rid -> entry
        self.funcs_sent: set = set()
        self.dead = False
        self.idle_since = time.monotonic()
        self.klass = klass
        # Lease-plane state (decentralized dispatch): the granting node,
        # the granted execution-slot count (pipeline bound for THIS
        # lease), the GRANTED renewal TTL (authoritative — the head's
        # reaper expires against its own clock, so renewal cadence must
        # come from the grant, never this process's local config; 0 =
        # legacy grant, no renewals), pushes since the last renewal, and
        # the spillback deprioritization deadline.
        self.node_hex = node_hex
        self.slots = max(1, slots)
        self.ttl = float(ttl or 0.0)
        self.pushed = 0
        self.last_renew = time.monotonic()
        self.saturated_until = 0.0
        # Conflation-sender buffer: pushes append here (buf_lock only)
        # while a flush's pickle+write runs under send_lock — appenders
        # never block on an in-flight write, which is what lets batches
        # self-clock with no added latency floor.
        self.outbuf: List[tuple] = []
        self.buf_lock = threading.Lock()  # lock-order: leaf
        # Channel-liveness state (failure detection): last_recv is
        # stamped by the reader on EVERY message; the watchdog probes a
        # channel with in-flight pushes and no traffic for
        # net_stall_timeout_s (dping — the executor's conn thread
        # answers even mid-compute) and closes one whose probe went
        # unanswered for another full window, feeding the existing
        # conn-EOF rediscovery/reroute path.
        self.last_recv = time.monotonic()
        self.ping_sent = 0.0

    def send(self, msg):
        with self.send_lock:
            protocol.send(self.conn, msg)

    def queue_msgs(self, msgs):
        with self.buf_lock:
            self.outbuf.extend(msgs)

    def flush_buffered(self):
        with self.buf_lock:
            if not self.outbuf:
                return
            msgs, self.outbuf = self.outbuf, []
        # Merge the buffered dexec/dexec_batch frames into ONE
        # dexec_batch (dfuncs keep their position before the first exec
        # that needs them), then ship everything as one pickle + write.
        pre, execs = [], []
        for m in msgs:
            if m[0] == "dexec":
                execs.append((m[1], m[2]))
            elif m[0] == "dexec_batch":
                execs.extend(m[1])
            else:
                pre.append(m)
        if execs:
            pre.append(("dexec", execs[0][0], execs[0][1])
                       if len(execs) == 1 else ("dexec_batch", execs))
        with self.send_lock:
            protocol.send_batch(self.conn, pre)


class DirectCaller:
    """Ownership table + lease pools for one worker/driver process.

    ``host`` is an adapter exposing what we need from the enclosing
    runtime:  head_request(build_msg) -> reply, head_send(msg),
    submit_via_head(spec), materialize(descr), shm store, store_id,
    authkey, register_payload(func_id) -> payload bytes.
    """

    def __init__(self, host):
        self.host = host
        self.lock = threading.Lock()
        self.cv = threading.Condition(self.lock)
        self.owned: Dict[ObjectID, OwnedState] = {}
        # sched class key -> pool state
        self.pools: Dict[tuple, dict] = {}
        self.rid_counter = itertools.count(1)
        self._stopped = False
        self._linger_thread = None
        # dep oid_bin -> [entries waiting on it] (caller-side resolution)
        self._dep_waiters: Dict[bytes, list] = {}
        self._pending_exports: set = set()
        # Outbound free/decref messages produced under self.lock; sent
        # after release (a peer's full TCP buffer must never stall the
        # whole ownership table).
        self._outbound: List[tuple] = []
        # actor_id -> channel dict for direct actor calls (reference:
        # direct_actor_task_submitter.h:67 — per-actor ordered pushes
        # straight to the actor's worker).  state: new -> resolving ->
        # direct | head ("head" is sticky: once any call routes through
        # the head, later calls do too, preserving per-caller order).
        self.actor_channels: Dict[bytes, dict] = {}
        # Conflation sender for direct pushes: _push_group buffers per
        # lease and this thread flushes; while one flush's pickle+write
        # runs, later submissions coalesce into the next batch — a
        # fan-out burst costs ~1 syscall per batch instead of one per
        # task (reference: gRPC stream write coalescing on PushTask).
        self._dirty_leases: set = set()
        self._lease_dirty_lock = threading.Lock()
        self._send_event = threading.Event()
        self._sender_thread = None
        # Decentralized-dispatch holder counters, shipped to the head in
        # the periodic xfer_stats deltas (zero while the switch is off):
        # leased_submits = specs pushed over leases (the traffic the head
        # never sees), spillbacks = pushes an oversubscribed executor
        # bounced back.
        self.leased_submits = 0
        self.spillbacks = 0
        # Worker-side lineage (reference: the owner retains its tasks'
        # specs, task_manager.h:174): THIS process is the owner directory
        # for its direct-submitted tasks, so reconstruction of their lost
        # returns must run here — the head never saw the specs.  Bounded
        # by the same byte budget as the head's table; None when the
        # recovery subsystem is off (every counter then stays zero).
        # LOCK ORDER: the table's _lock is an independent LEAF acquired
        # under self.lock (record on submit, release on free) — pinned
        # in tests/test_lockcheck.py.
        cfg = GLOBAL_CONFIG
        self.lineage = (recovery.LineageTable(cfg.lineage_bytes_budget)
                        if cfg.recovery and cfg.lineage_enabled else None)
        self.reconstructions = 0
        self.reconstruction_failures = 0
        # Failure detection: the channel-liveness watchdog's stall
        # window (0 = off, nothing new runs — the legacy behavior where
        # only a conn EOF discovers a dead executor).
        self._fd_stall_t = (cfg.net_stall_timeout_s
                            if cfg.failure_detection else 0.0)

    def stats(self) -> Dict[str, int]:
        """Counter snapshot for the xfer_stats delta shipper."""
        with self.lock:
            return {"leased_submits": self.leased_submits,
                    "spillbacks": self.spillbacks,
                    "reconstructions": self.reconstructions,
                    "reconstruction_failures":
                        self.reconstruction_failures}

    # ------------------------------------------------------------- owned --
    def register_put(self, oid: ObjectID, descr, nested_local, nested_head):
        with self.lock:
            st = OwnedState()
            st.status = READY
            st.descr = descr
            st.local_refs = 1
            st.nested_local = list(nested_local)
            st.nested_head = list(nested_head)
            for b in nested_local:
                inner = self.owned.get(ObjectID(b))
                if inner is not None:
                    inner.pins += 1
            self.owned[oid] = st
        return st

    def addref(self, oid: ObjectID) -> bool:
        """True if ``oid`` is owned here (ref counted locally)."""
        with self.lock:
            st = self.owned.get(oid)
            if st is None:
                return False
            st.local_refs += 1
            return True

    def addref_batch(self, oids: List[ObjectID]) -> List[bytes]:
        """Addref every owned oid under ONE lock pass; returns the bins
        of the foreign (head-owned) ones for the caller to batch-send."""
        foreign: List[bytes] = []
        with self.lock:
            for oid in oids:
                st = self.owned.get(oid)
                if st is None:
                    foreign.append(oid.binary())
                else:
                    st.local_refs += 1
        return foreign

    def decref(self, oid: ObjectID) -> bool:
        """True if owned here.  DELEGATED entries forward to the head when
        the last local ref drops (their head refcount carries exactly one
        aggregate ref for this process)."""
        with self.lock:
            st = self.owned.get(oid)
            if st is None:
                return False
            st.local_refs -= 1
            self._maybe_free_locked(oid, st)
        self._flush_outbound()
        return True

    def _maybe_free_locked(self, oid: ObjectID, st: OwnedState):
        if st.local_refs > 0 or st.pins > 0:
            return
        if st.status == PENDING:
            # Refs dropped before the producing task finished: keep the
            # entry; completion re-checks (the result may still matter for
            # pinned consumers).  Mark for free-on-complete.
            return
        self.owned.pop(oid, None)
        if self.lineage is not None:
            # Lineage pinning ends with the object: the table entry
            # drops when its last return object does (leaf lock; no
            # resources to release worker-side).
            self.lineage.release(oid.binary())
        if st.status == DELEGATED:
            # Head holds one aggregate ref for this process.
            self._outbound.append(("head", ("decref", oid.binary())))
        elif st.descr is not None and st.descr[0] == protocol.SHM:
            self._free_segment_locked(st)
        elif st.descr is not None and st.descr[0] == protocol.SPILLED:
            if st.descr[3] == self.host.store_id:
                try:
                    os.unlink(st.descr[1])
                except OSError:
                    pass
            else:
                self._outbound.append(("head", ("free_remote", st.descr[1],
                                                st.descr[2], st.descr[3])))
        for b in st.nested_local:
            inner = self.owned.get(ObjectID(b))
            if inner is not None:
                inner.pins -= 1
                self._maybe_free_locked(ObjectID(b), inner)
        if st.nested_head:
            self._outbound.append(
                ("head", ("decref_batch", list(st.nested_head))))

    def _free_segment_locked(self, st: OwnedState):
        name, size = st.descr[1], st.descr[2]
        store = st.descr[3] if len(st.descr) > 3 else self.host.store_id
        lease = st.creator
        if lease is not None and not lease.dead and lease.conn is not None:
            # The creating worker pools its pages for in-place reuse iff no
            # other process ever mapped the segment.
            self._outbound.append(
                ("lease", lease,
                 ("dfree", name, size, not st.attached and not st.shipped),
                 ("free_remote", name, size, store)))
        elif store == self.host.store_id:
            try:
                # Self-created segments (owner-local puts) whose descriptor
                # never escaped pool their pages for in-place reuse — this
                # is what keeps a put loop at memcpy speed instead of
                # fresh-page fault+zero speed (plasma arena reuse).
                self.host.shm.unlink(
                    name, size,
                    reusable=(st.creator is None and not st.attached
                              and not st.shipped))
            except Exception:
                pass
        else:
            self._outbound.append(
                ("head", ("free_remote", name, size, store)))

    def _flush_outbound(self):
        if not self._outbound:
            return
        with self.lock:
            out, self._outbound = self._outbound, []
        # Consecutive head-bound messages coalesce into one ("batch", ...)
        # envelope (relative order with lease-bound frees is preserved by
        # flushing in segments) — a result burst's decref storm becomes
        # one pickle + one write.
        head_buf: List[tuple] = []

        def flush_head():
            if not head_buf:
                return
            msgs, head_buf[:] = list(head_buf), []
            try:
                self.host.head_send(protocol.make_batch(msgs))
            except Exception:
                pass

        for item in out:
            if item[0] == "lease":
                flush_head()
                _kind, lease, msg, fallback = item
                try:
                    lease.send(msg)
                    continue
                except Exception:
                    pass
                head_buf.append(fallback)
            else:
                head_buf.append(item[1])
        flush_head()

    # ------------------------------------------------------------ submit --
    def eligible(self, spec: dict) -> bool:
        """Direct-pushable?  Conservative: CPU-only, default strategy, no
        runtime_env; ref args must be owned (pending deps resolved caller-
        side) and not delegated."""
        if "actor_id" in spec:
            return False
        if spec.get("scheduling_strategy") is not None:
            return False
        if spec.get("runtime_env"):
            return False
        if spec.get("retry_exceptions"):
            # Opt-in app-error retry lives in the head's result path
            # (one implementation of the retry budget); conservative
            # eligibility is the direct plane's standing pattern.
            return False
        res = spec.get("resources") or {}
        if any(k != "CPU" for k in res):
            return False
        with self.lock:
            for a in self._iter_ref_args(spec):
                st = self.owned.get(ObjectID(a))
                if st is None or st.status == DELEGATED:
                    return False
        return True

    @staticmethod
    def _iter_ref_args(spec):
        for a in spec.get("args", ()):
            if a[0] == "ref":
                yield a[1]
        for a in (spec.get("kwargs") or {}).values():
            if a[0] == "ref":
                yield a[1]

    def _register_entry_locked(self, spec: dict,
                               retries: int) -> Tuple[dict, list]:
        """Shared submit bookkeeping: owned return states, arg/nested
        pins, and dep-waiter registration for pending owned args."""
        tid = TaskID(spec["task_id"])
        entry = {
            "spec": spec, "rid": None, "retries": retries,
            "deps": 0, "tid_bin": spec["task_id"], "pinned": (),
        }
        # Head-owned refs nested in container args get +1 at the head for
        # the task's lifetime (the head path pins nested_refs in
        # submit_task_from_worker; without this the caller's own decref
        # could free them before the executor deserializes the arg).
        foreign_nested = [b for b in spec.get("nested_refs", ())
                          if self.owned.get(ObjectID(b)) is None]
        if foreign_nested:
            entry["foreign_nested"] = foreign_nested
            self._outbound.append(("head", ("addref_batch",
                                            foreign_nested)))
        states = []
        for i in range(spec["num_returns"]):
            st = OwnedState(spec["task_id"])
            st.local_refs = 1
            self.owned[tid.object_id(i)] = st
            states.append(st)
        pinned = list(itertools.chain(self._iter_ref_args(spec),
                                      spec.get("nested_refs", ())))
        for b in pinned:
            ist = self.owned.get(ObjectID(b))
            if ist is not None:
                ist.pins += 1
        entry["pinned"] = pinned
        for b in self._iter_ref_args(spec):
            ist = self.owned.get(ObjectID(b))
            if ist is not None and ist.status == PENDING:
                entry["deps"] += 1
                self._dep_waiters.setdefault(b, []).append(entry)
        return entry, states

    def submit(self, spec: dict) -> List[OwnedState]:
        """Register owned returns + queue the spec for push.  Caller-side
        dependency resolution: the spec is held until every owned ref arg
        is READY (reference: the caller's LocalDependencyResolver,
        direct_task_transport.cc:33)."""
        return self.submit_many([spec])[0]

    def submit_many(self, specs: List[dict]) -> List[List[OwnedState]]:
        """Bulk submission: every spec's owned returns / arg pins
        register under ONE ownership-lock pass, then each scheduling
        class pumps once for the whole batch (reference: the amortized
        per-SchedulingKey submission of direct_task_transport.cc)."""
        states_out: List[List[OwnedState]] = []
        klasses: List[tuple] = []
        with self.lock:
            for spec in specs:
                entry, states = self._register_entry_locked(
                    spec, spec.get("max_retries", 3))
                if self.lineage is not None \
                        and spec.get("num_returns", 0) > 0:
                    # Owner-side lineage (metadata only — evicted
                    # entries hold nothing to release here; a spec's
                    # lost args reconstruct through their OWN lineage,
                    # the head model).
                    self.lineage.record(spec)
                states_out.append(states)
                if entry["deps"] == 0:
                    klass = self._sched_class(spec)
                    self._pool_locked(klass)["queue"].append(entry)
                    klasses.append(klass)
        # Flush BEFORE returning to user code: the foreign-nested addref
        # must be on the wire before the user can drop their own ref
        # (whose buffered decref rides a later send on the same conn).
        self._flush_outbound()
        for klass in dict.fromkeys(klasses):
            self._pump(klass)
        return states_out

    def _sched_class(self, spec) -> tuple:
        res = spec.get("resources") or {"CPU": 1.0}
        return tuple(sorted(res.items()))

    def _pool_locked(self, klass) -> dict:
        pool = self.pools.get(klass)
        if pool is None:
            pool = self.pools[klass] = {
                "queue": deque(), "leases": [], "requesting": False,
                "last_req": 0.0,
            }
        return pool

    # -------------------------------------------------------------- pump --
    def _pump(self, klass):
        """Push queued specs onto leases with free pipeline slots; request
        more leases (or fall back to the head) when short.

        Lease plane: each lease is bounded by its GRANTED slot count (the
        head capped it at max_tasks_in_flight_per_worker), a recently
        spilled-off lease is throttled to a trickle while its
        saturation window runs (the bulk diverts to other leases or a
        hint-steered request), and the TTL renewal rides out of the same
        pass — one ("lease_renew", ...) per lease_renew_tasks pushes, not
        one per task."""
        cfg = GLOBAL_CONFIG
        to_push: List[Tuple[_Lease, dict]] = []
        need_leases = 0
        renew: List[str] = []
        with self.lock:
            pool = self.pools.get(klass)
            if pool is None:
                return
            leases = [l for l in pool["leases"] if not l.dead]
            pool["leases"] = leases
            q = pool["queue"]
            now = time.monotonic()
            while q:
                lease = None
                for cand in leases:
                    cap = (1 if now < cand.saturated_until
                           else cand.slots)
                    if len(cand.inflight) < cap:
                        lease = cand
                        break
                if lease is None:
                    break
                entry = q.popleft()
                rid = next(self.rid_counter)
                entry["rid"] = rid
                lease.inflight[rid] = entry
                lease.idle_since = None
                lease.pushed += 1
                if lease.ttl > 0 and lease.pushed >= max(
                        1, cfg.lease_renew_tasks):
                    lease.pushed = 0
                    lease.last_renew = now
                    renew.append(lease.worker_id)
                to_push.append((lease, entry))
            if cfg.decentralized_dispatch:
                self.leased_submits += len(to_push)
            if q and not pool["requesting"]:
                if now - pool["last_req"] > 0.05 or not leases:
                    pool["requesting"] = True
                    pool["last_req"] = now
                    need_leases = min(MAX_LEASES_PER_REQ,
                                      max(1, len(q) // PIPELINE_DEPTH))
            if renew:
                self._outbound.append(("head", ("lease_renew", renew)))
        by_lease: Dict[int, Tuple[_Lease, list]] = {}
        for lease, entry in to_push:
            by_lease.setdefault(id(lease), (lease, []))[1].append(entry)
        for lease, entries in by_lease.values():
            self._push_group(lease, entries)
        if renew:
            self._flush_outbound()
        if need_leases:
            threading.Thread(
                target=self._request_leases, args=(klass, need_leases),
                daemon=True).start()

    def _push_group(self, lease: _Lease, entries: List[dict]):
        """Queue a burst of entries for the conflation sender.  The
        sender ships everything buffered per lease as ONE wire frame —
        per-task sends made the push path syscall- and pickle-bound
        under multi-client load (reference: gRPC stream write coalescing
        on the PushTask stream)."""
        cfg = GLOBAL_CONFIG
        # Spillback is opt-in PER PUSH (capability gate): only tasks the
        # caller marks may bounce — an executor never spills a push whose
        # sender would not understand the ("dspill", ...) reply.  Actor
        # channels never spill (per-caller ordering).
        spill_ok = (cfg.decentralized_dispatch
                    and cfg.lease_spillback_depth > 0
                    and not (lease.klass and lease.klass[0] == "actor"))
        tasks, failed = [], []
        for entry in entries:
            try:
                task = self._build_task(entry["spec"])
                if spill_ok:
                    task["_spill_ok"] = True
                tasks.append((entry, task))
            except exc.RayTpuError as e:
                failed.append((entry, e))
        if failed:
            with self.lock:
                for entry, _ in failed:
                    lease.inflight.pop(entry["rid"], None)
            for entry, e in failed:
                self._fail_entry(entry, e)
        if not tasks:
            return
        msgs = []
        for entry, _task in tasks:
            fid = entry["spec"].get("func_id")
            if fid and fid not in lease.funcs_sent:
                payload = self.host.get_payload(fid)
                if payload is not None:
                    msgs.append(("dfunc", fid, payload))
                lease.funcs_sent.add(fid)
        if len(tasks) == 1:
            msgs.append(("dexec", tasks[0][0]["rid"], tasks[0][1]))
        else:
            msgs.append(("dexec_batch", [(e["rid"], t) for e, t in tasks]))
        lease.queue_msgs(msgs)
        self._mark_lease_dirty(lease)

    def _mark_lease_dirty(self, lease: _Lease):
        with self._lease_dirty_lock:
            self._dirty_leases.add(lease)
            if self._sender_thread is None:
                self._sender_thread = threading.Thread(
                    target=self._lease_sender_loop, daemon=True,
                    name="ray_tpu-direct-sender")
                self._sender_thread.start()
        self._send_event.set()

    def _lease_sender_loop(self):
        """Flush dirty leases' push buffers.  Self-clocking: while one
        flush's pickle+write runs here, the submitting thread keeps
        appending to the next batch."""
        while not self._stopped:
            self._send_event.wait()
            self._send_event.clear()
            with self._lease_dirty_lock:
                dirty, self._dirty_leases = self._dirty_leases, set()
            for lease in dirty:
                try:
                    lease.flush_buffered()
                except Exception:
                    self._on_lease_dead(lease)

    def _build_task(self, spec: dict) -> dict:
        """Spec -> executable task dict: owned ref args substituted with
        their descriptors (the caller is the metadata authority)."""
        def subst(a):
            if a[0] != "ref":
                return a
            with self.lock:
                st = self.owned.get(ObjectID(a[1]))
                # DELEGATED entries keep a valid descriptor (exports move
                # metadata authority, not data); only a truly descriptor-
                # less entry is an error.
                if st is None or st.descr is None:
                    raise exc.ObjectLostError(
                        object_id=a[1].hex(),
                        owner=getattr(self.host, "worker_id_hex", None),
                        phase="dispatch")
                st.shipped = True
                return st.descr

        task = {
            "task_id": spec["task_id"],
            "num_returns": spec["num_returns"],
            "name": spec.get("name", "task"),
            "args": [subst(a) for a in spec.get("args", ())],
            "kwargs": {k: subst(v)
                       for k, v in (spec.get("kwargs") or {}).items()},
            "resources": spec.get("resources") or {},
        }
        if "actor_id" in spec:
            task["actor_id"] = spec["actor_id"]
            task["method"] = spec["method"]
        else:
            task["func_id"] = spec["func_id"]
        return task

    # ------------------------------------------------------------ actors --
    def submit_actor(self, spec: dict) -> Optional[List[OwnedState]]:
        """Direct actor-call path.  Returns owned return states when the
        call was queued on a direct channel, or None when the caller must
        route through the head (unresolved/dead actor, foreign ref args,
        sticky head mode).

        Ordering: a channel that must fall back enters ``head_draining``
        — queued-and-future calls are held until every already-pushed
        call acks, then flush through the head in order.  This closes
        the window where a head-routed call could overtake an inflight
        direct push (the sequence-number guarantee of
        direct_actor_task_submitter.h:67)."""
        aid = spec["actor_id"]
        # Export owned nested refs BEFORE the entry becomes pushable: a
        # concurrent _pump_actor may push it the moment it is queued, and
        # the executor resolves container refs through the head.
        owned_nested = [b for b in spec.get("nested_refs", ())
                        if self.status_of(ObjectID(b))
                        not in (None, DELEGATED)]
        if owned_nested:
            self.export_refs(owned_nested)
        with self.lock:
            ch = self.actor_channels.get(aid)
            if ch is None:
                ch = self.actor_channels[aid] = {
                    "state": "new", "lease": None, "queue": deque()}
            if ch["state"] == "head":
                return None
            foreign_arg = False
            for b in self._iter_ref_args(spec):
                st = self.owned.get(ObjectID(b))
                if st is None or (st.descr is None
                                  and st.status == DELEGATED):
                    foreign_arg = True
                    break
            if foreign_arg:
                lease = ch["lease"]
                if ch["state"] == "direct" and lease is not None \
                        and lease.inflight:
                    # Inflight direct pushes: drain before any head
                    # routing (order).  This call joins the held queue
                    # as a head-bound entry.
                    ch["state"] = "head_draining"
                    entry, states = self._register_entry_locked(spec, 0)
                    entry["via_head"] = True
                    ch["queue"].append(entry)
                    return states
                queued = list(ch["queue"])
                ch["queue"].clear()
                ch["state"] = "head"
            else:
                queued = None
                entry, states = self._register_entry_locked(spec, 0)
                if ch["state"] == "head_draining":
                    entry["via_head"] = True
                ch["queue"].append(entry)
                if ch["state"] == "new":
                    ch["state"] = "resolving"
                    threading.Thread(target=self._resolve_actor,
                                     args=(aid,), daemon=True).start()
        if queued is not None:
            for e in queued:
                self._reroute_to_head(e)
            return None
        self._flush_outbound()
        self._pump_actor(aid)
        return states

    def _resolve_actor(self, aid: bytes):
        try:
            reply = self.host.head_request(
                lambda rid: ("actor_addr_req", rid, aid))
        except Exception:
            reply = None
        lease = None
        if reply:
            wid, addr = reply
            lease = _Lease(wid, addr, ("actor", aid))
            try:
                lease.conn = self.host.dial(addr)
            except Exception:
                lease = None
        queued = None
        with self.lock:
            ch = self.actor_channels.get(aid)
            if ch is None:
                return
            if lease is None:
                queued = list(ch["queue"])
                ch["queue"].clear()
                ch["state"] = "head"
            else:
                ch["lease"] = lease
                ch["state"] = "direct"
        if queued is not None:
            self._reroute_many(queued)
            return
        threading.Thread(target=self._lease_reader, args=(lease,),
                         daemon=True).start()
        if self._fd_stall_t > 0:
            # Actor channels live outside the lease pools; the linger
            # loop is also their liveness watchdog.
            self._ensure_linger_thread()
        self._pump_actor(aid)

    def _pump_actor(self, aid: bytes):
        """Strictly FIFO: the queue head pushes only once its deps are
        READY — later entries wait behind it (per-caller ordering, the
        sequence-number guarantee of direct_actor_task_submitter.h:67)."""
        to_push, to_head = [], []
        with self.lock:
            ch = self.actor_channels.get(aid)
            if ch is None:
                return
            if ch["state"] == "head_draining":
                lease = ch["lease"]
                if lease is None or not lease.inflight:
                    # Every direct push acked: safe to flush the held
                    # calls through the head in order.
                    to_head = list(ch["queue"])
                    ch["queue"].clear()
                    ch["state"] = "head"
                    ch["lease"] = None
            elif ch["state"] == "direct":
                lease = ch["lease"]
                q = ch["queue"]
                # Bounded pipeline: beyond ACTOR_PIPELINE unacked pushes,
                # calls wait here and ride out in result-clocked batches —
                # unbounded per-call sends made the channel syscall-bound.
                while q and q[0]["deps"] == 0 \
                        and len(lease.inflight) < ACTOR_PIPELINE:
                    entry = q.popleft()
                    rid = next(self.rid_counter)
                    entry["rid"] = rid
                    lease.inflight[rid] = entry
                    to_push.append((lease, entry))
        if to_head:
            self._reroute_many(to_head)
        if to_push:
            self._push_group(to_push[0][0], [e for _, e in to_push])

    def _pump_any(self, klass):
        if klass and klass[0] == "actor":
            self._pump_actor(klass[1])
        else:
            self._pump(klass)

    def actor_channel_busy(self, aid: bytes) -> bool:
        """True while this process still has queued or unacked direct
        calls to the actor (the worker holds its actor-handle decrefs
        until then — the head cannot see direct pushes)."""
        with self.lock:
            ch = self.actor_channels.get(aid)
            if ch is None:
                return False
            if ch["queue"]:
                return True
            lease = ch.get("lease")
            return lease is not None and bool(lease.inflight)

    def _on_actor_channel_dead(self, lease: _Lease, aid: bytes):
        """Actor worker conn broke: already-pushed calls may have run, so
        they fail (ActorDiedError, the reference's default for actor
        tasks); never-pushed queued calls reroute through the head, which
        knows the actor's restart state authoritatively."""
        with self.lock:
            ch = self.actor_channels.get(aid)
            inflight = list(lease.inflight.values())
            lease.inflight.clear()
            queued = []
            if ch is not None and ch.get("lease") is lease:
                queued = list(ch["queue"])
                ch["queue"].clear()
                ch["state"] = "head"
                ch["lease"] = None
        try:
            if lease.conn is not None:
                lease.conn.close()
        except Exception:
            pass
        for entry in inflight:
            self._fail_entry(entry, exc.ActorDiedError(
                "Actor worker connection lost (direct channel)"))
        self._reroute_many(queued)

    # ------------------------------------------------------------ leases --
    def _request_leases(self, klass, n):
        pool = None
        cfg = GLOBAL_CONFIG
        hint = None
        if cfg.decentralized_dispatch:
            with self.lock:
                p = self.pools.get(klass)
                if p is not None:
                    # One-shot spillback hint: steer this request toward
                    # the node the head named as next-best.
                    hint = p.pop("hint", None)
        try:
            res = dict(klass)
            if cfg.decentralized_dispatch:
                opts = {"v": 1}
                if hint:
                    opts["hint"] = hint
                reply = self.host.head_request(
                    lambda rid: ("lease_req", rid, res, n, opts))
            else:
                reply = self.host.head_request(
                    lambda rid: ("lease_req", rid, res, n))
        except Exception:
            reply = []
        slots, ttl = PIPELINE_DEPTH, 0.0
        if isinstance(reply, dict):
            # v1 grant: per-worker node ids + slot count + TTL + the
            # next-best-node hint for a future spillback.
            slots = int(reply.get("slots") or PIPELINE_DEPTH)
            ttl = float(reply.get("ttl") or 0.0)
            if reply.get("hint"):
                with self.lock:
                    p = self.pools.get(klass)
                    if p is not None:
                        p.setdefault("hint", reply["hint"])
            rows = reply.get("grants") or []
        else:
            rows = [(wid, addr, None) for wid, addr in (reply or [])]
        granted = self._dial_grants(klass, rows, slots, ttl)
        with self.lock:
            pool = self.pools.get(klass)
            if pool is None:
                return
            pool["requesting"] = False
            for lease in granted:
                pool["leases"].append(lease)
            stranded = []
            if not granted and pool["queue"] and not pool["leases"]:
                # Starved even after the head parked the request: route a
                # BOUNDED chunk through the head (progress guarantee) and
                # keep the rest queued for the next lease request — the
                # v1 full-queue dump made every concurrent caller collapse
                # onto the head's single mailbox the moment leases
                # momentarily ran out.
                for _ in range(min(len(pool["queue"]), REROUTE_CHUNK)):
                    stranded.append(pool["queue"].popleft())
                if pool["queue"]:
                    pool["last_req"] = 0.0  # next _pump re-requests now
        for lease in granted:
            threading.Thread(target=self._lease_reader, args=(lease,),
                             daemon=True).start()
        if stranded:
            self._reroute_many(stranded)
        if granted:
            self._pump(klass)
            self._ensure_linger_thread()
        elif stranded:
            # Nothing granted and specs remain queued: re-pump so a fresh
            # lease request goes out (no submit/result event will — the
            # caller may already be parked in ray.get).
            self._pump(klass)

    def _dial_grants(self, klass, rows, slots, ttl) -> List["_Lease"]:
        """Granted (wid, addr, node_hex) rows -> dialed _Lease objects
        (the shared adoption core of solicited replies and unsolicited
        lease_grant pushes).  Dial happens here, once, before the lease
        is visible to _pump: the reader thread and pushers then share
        one connection.  A failed dial returns that lease to the head
        immediately."""
        granted: List[_Lease] = []
        for wid, addr, node_hex in rows or []:
            lease = _Lease(wid, addr, klass, node_hex=node_hex,
                           slots=int(slots or PIPELINE_DEPTH),
                           ttl=float(ttl or 0.0))
            try:
                lease.conn = self.host.dial(addr)
            except Exception:
                try:
                    self.host.head_send(("lease_return", [wid]))
                except Exception:
                    pass
                continue
            granted.append(lease)
        return granted

    def adopt_grant(self, klass_items, grants, slots, ttl, hint):
        """Adopt an UNSOLICITED bulk lease grant the head piggybacked on
        a head-brokered submit burst (("lease_grant", ...)): dial the
        granted workers and fold them into the matching pool so the next
        burst pushes direct.  Runs off the reader thread (dials block).
        Unused grants return via the normal linger path."""
        klass = tuple((k, float(v)) for k, v in klass_items)
        granted = self._dial_grants(klass, grants, slots, ttl)
        if not granted:
            return
        with self.lock:
            pool = self._pool_locked(klass)
            pool["leases"].extend(granted)
            if hint:
                pool.setdefault("hint", hint)
        for lease in granted:
            threading.Thread(target=self._lease_reader, args=(lease,),
                             daemon=True).start()
        self._pump(klass)
        self._ensure_linger_thread()

    def revoke(self, worker_ids):
        """Head-initiated lease revocation (("lease_revoke", ...): node/
        worker death or TTL expiry).  The lease-death path reroutes or
        retries everything the lease still carried — same semantics as
        discovering the death via conn EOF, minus the wait."""
        wids = set(worker_ids)
        with self.lock:
            doomed = [l for p in self.pools.values() for l in p["leases"]
                      if l.worker_id in wids and not l.dead]
            for ch in self.actor_channels.values():
                lease = ch.get("lease")
                if lease is not None and lease.worker_id in wids \
                        and not lease.dead:
                    doomed.append(lease)
        for lease in doomed:
            self._on_lease_dead(lease)

    def _on_spillback(self, lease: _Lease, rid, info):
        """An oversubscribed executor bounced a push (reference: hybrid
        policy spillback).  Re-queue the entry at the FRONT of its class
        (rough submission order) and throttle the bouncing lease for the
        saturation window; the next lease request is steered by the
        next-best-node hint the HEAD attached to the grant (``info``
        names only the bouncing executor's node — the executor has no
        cluster view).  An entry that keeps bouncing reroutes to the
        head — guaranteed progress."""
        reroute = None
        with self.lock:
            entry = lease.inflight.pop(rid, None)
            if entry is None:
                return
            if GLOBAL_CONFIG.decentralized_dispatch:
                self.spillbacks += 1
            lease.saturated_until = time.monotonic() + SATURATED_S
            entry["spills"] = entry.get("spills", 0) + 1
            pool = self._pool_locked(lease.klass)
            bounced = (info or {}).get("node")
            if bounced and pool.get("hint") == bounced:
                # The stored next-best hint points at the node that just
                # bounced us — stale; drop it rather than steer the next
                # lease request back into the hot spot.
                pool.pop("hint", None)
            if entry["spills"] >= SPILL_MAX:
                reroute = entry
            else:
                pool["queue"].appendleft(entry)
            if not lease.inflight:
                lease.idle_since = time.monotonic()
        if reroute is not None:
            self._reroute_to_head(reroute)
        else:
            self._pump(lease.klass)

    def _lease_reader(self, lease: _Lease):
        while not self._stopped:
            try:
                msg = protocol.recv(lease.conn)
            except (EOFError, OSError, TypeError):
                self._on_lease_dead(lease)
                return
            lease.last_recv = time.monotonic()
            if msg[0] == "dresult":
                self._on_result_batch(lease, [msg[1:]])
            elif msg[0] == "dresult_batch":
                self._on_result_batch(lease, msg[1])
            elif msg[0] == "dspill":
                self._on_spillback(lease, msg[1], msg[2])
            elif msg[0] == "dpong":
                pass  # the last_recv stamp above IS the liveness signal

    def _on_result_batch(self, lease: _Lease, items):
        """Apply a burst of results under ONE lock pass (one notify, one
        outbound flush, one pump) — per-result locking was the caller-side
        bottleneck at multi-client rates."""
        exported = []
        dep_klasses = set()
        with self.lock:
            for rid, _ok, returns, meta in items:
                entry = lease.inflight.pop(rid, None)
                if entry is None:
                    continue
                tid = TaskID(entry["tid_bin"])
                nested = meta.get("nested") or [[] for _ in returns]
                for i, descr in enumerate(returns):
                    oid = tid.object_id(i)
                    item_ok = descr[0] != protocol.ERROR
                    bin_ = oid.binary()
                    if bin_ in self._pending_exports:
                        # The shell was exported to the head while pending
                        # (delegated): complete it there too.
                        self._pending_exports.discard(bin_)
                        exported.append((bin_, item_ok, descr,
                                         list(nested[i])
                                         if i < len(nested) else [],
                                         lease.worker_id))
                    st = self.owned.get(oid)
                    if st is None:
                        continue
                    if st.status != DELEGATED:
                        st.status = READY if item_ok else ERRORED
                    st.descr = descr
                    if descr[0] == protocol.SHM:
                        st.creator = lease
                    if i < len(nested) and nested[i]:
                        # The executor addref'd these at the head for us
                        # (borrowed-ref transfer).  Bins WE own pin locally
                        # instead — the head shell the executor's addref
                        # created doesn't protect our local entry — and the
                        # on-behalf head ref is returned immediately.
                        for b in nested[i]:
                            ist = self.owned.get(ObjectID(b))
                            if ist is not None and ist.status != DELEGATED:
                                ist.pins += 1
                                st.nested_local.append(b)
                                self._outbound.append(
                                    ("head", ("decref", b)))
                            else:
                                st.nested_head.append(b)
                    self._maybe_free_locked(oid, st)
                self._unpin_entry_locked(entry)
                dep_klasses.update(self._wake_deps_locked(entry))
            if not lease.inflight:
                lease.idle_since = time.monotonic()
            self.cv.notify_all()
        if exported:
            try:
                self.host.head_send(("export_complete", exported))
            except Exception:
                pass
        self._flush_outbound()
        self._pump_any(lease.klass)
        for klass in dep_klasses:
            if klass != lease.klass:
                self._pump_any(klass)

    def _unpin_entry_locked(self, entry):
        for b in entry.get("pinned", ()):
            ist = self.owned.get(ObjectID(b))
            if ist is not None:
                ist.pins -= 1
                self._maybe_free_locked(ObjectID(b), ist)
        entry["pinned"] = ()
        fn = entry.pop("foreign_nested", None)
        if fn:
            self._outbound.append(("head", ("decref_batch", fn)))

    def _wake_deps_locked(self, entry: dict) -> List[tuple]:
        """Dependent specs waiting on this task's returns may now push;
        returns the scheduling classes to pump (after lock release)."""
        tid = TaskID(entry["tid_bin"])
        ready = []
        for i in range(entry["spec"]["num_returns"]):
            waiters = self._dep_waiters.pop(tid.object_id(i).binary(), None)
            for dep_entry in waiters or ():
                dep_entry["deps"] -= 1
                if dep_entry["deps"] == 0:
                    ready.append(dep_entry)
        klasses = set()
        for dep_entry in ready:
            if dep_entry.get("rerouted"):
                continue
            spec = dep_entry["spec"]
            if "actor_id" in spec:
                # Actor entries never left their channel queue (FIFO);
                # just pump the channel.
                klasses.add(("actor", spec["actor_id"]))
            else:
                klass = self._sched_class(spec)
                self._pool_locked(klass)["queue"].append(dep_entry)
                klasses.add(klass)
        return list(klasses)

    def _on_lease_dead(self, lease: _Lease):
        """Executor died or conn broke: resubmit its inflight work
        (caller-side retries; reference: lease worker failure handling in
        direct_task_transport.cc)."""
        if lease.klass and lease.klass[0] == "actor":
            with self.lock:
                if lease.dead:
                    return
                lease.dead = True
            self._on_actor_channel_dead(lease, lease.klass[1])
            return
        with self.lock:
            if lease.dead:
                return
            lease.dead = True
            inflight = list(lease.inflight.values())
            lease.inflight.clear()
            pool = self.pools.get(lease.klass)
            if pool is not None and lease in pool["leases"]:
                pool["leases"].remove(lease)
        try:
            if lease.conn is not None:
                lease.conn.close()
        except Exception:
            pass
        try:
            self.host.head_send(("lease_return", [lease.worker_id]))
        except Exception:
            pass
        retry, fail = [], []
        with self.lock:
            for entry in inflight:
                if entry["retries"] > 0:
                    entry["retries"] -= 1
                    retry.append(entry)
                else:
                    fail.append(entry)
        for entry in retry:
            with self.lock:
                pool = self._pool_locked(lease.klass)
                pool["queue"].append(entry)
        for entry in fail:
            self._fail_entry(entry, exc.WorkerCrashedError(
                f"worker {lease.worker_id} died running "
                f"{entry['spec'].get('name', 'task')}"))
        if retry:
            self._pump(lease.klass)

    def _fail_entry(self, entry, error: BaseException):
        err_descr = (protocol.ERROR, serialization.dumps_inline(error))
        tid = TaskID(entry["tid_bin"])
        exported = []
        with self.lock:
            for i in range(entry["spec"]["num_returns"]):
                bin_ = tid.object_id(i).binary()
                if bin_ in self._pending_exports:
                    self._pending_exports.discard(bin_)
                    exported.append((bin_, False, err_descr, []))
                st = self.owned.get(tid.object_id(i))
                if st is not None:
                    if st.status != DELEGATED:
                        st.status = ERRORED
                    st.descr = err_descr
                    self._maybe_free_locked(tid.object_id(i), st)
            self._unpin_entry_locked(entry)
            dep_klasses = self._wake_deps_locked(entry)
            self.cv.notify_all()
        if exported:
            try:
                self.host.head_send(("export_complete", exported))
            except Exception:
                pass
        self._flush_outbound()
        for klass in dep_klasses:
            self._pump_any(klass)

    def _reroute_to_head(self, entry):
        self._reroute_many([entry])

    def _reroute_many(self, entries):
        """No leases: delegate these specs (and their owned returns) to
        the head scheduler so progress is guaranteed.  A starved round
        reroutes REROUTE_CHUNK specs — they ship as ONE
        ("submit_batch", ...) message (one export pass, one pickle+write,
        one head registration pass) instead of a single-submit storm,
        which is exactly the multi-client fan-in path under contention.
        The entries' arg pins are released only AFTER the head has the
        specs — the export in submit_via_head must still see the args
        alive (a dropped-ref arg would otherwise be freed before the
        head could pin it).

        Dependents parked on these tasks' returns reroute too: no
        dresult will ever arrive here to wake them, and the head
        resolves delegated deps natively (their shells export with the
        specs)."""
        done = []
        dependents = []
        actor_flips = []
        with self.lock:
            for entry in entries:
                if entry.get("rerouted"):
                    continue
                entry["rerouted"] = True
                spec = entry["spec"]
                tid = TaskID(entry["tid_bin"])
                for i in range(spec["num_returns"]):
                    st = self.owned.get(tid.object_id(i))
                    if st is not None:
                        st.status = DELEGATED
                    for dep_entry in self._dep_waiters.pop(
                            tid.object_id(i).binary(), []) or []:
                        dep_entry["deps"] -= 1
                        if dep_entry.get("rerouted"):
                            continue
                        dspec = dep_entry["spec"]
                        if "actor_id" in dspec:
                            # Actor entries stay in their channel queue;
                            # the channel must go head-mode (order-
                            # preserving drain) since this dep resolves
                            # at the head.
                            actor_flips.append(dspec["actor_id"])
                            dep_entry["via_head"] = True
                        else:
                            dependents.append(dep_entry)
                done.append(entry)
        if not done and not actor_flips:
            return
        if len(done) > 1 and hasattr(self.host, "submit_via_head_many"):
            self.host.submit_via_head_many([e["spec"] for e in done])
        else:
            for entry in done:
                self.host.submit_via_head(entry["spec"])
        with self.lock:
            for entry in done:
                self._unpin_entry_locked(entry)
            for aid in actor_flips:
                ch = self.actor_channels.get(aid)
                if ch is not None and ch["state"] in ("direct",
                                                      "resolving", "new"):
                    ch["state"] = "head_draining"
            self.cv.notify_all()
        self._flush_outbound()
        if dependents:
            self._reroute_many(dependents)
        for aid in set(actor_flips):
            self._pump_actor(aid)

    def _ensure_linger_thread(self):
        # The linger loop clears _linger_thread under self.lock in the
        # same critical section where it confirms no leases remain, so
        # this check can't race a thread that is about to exit.
        with self.lock:
            if self._linger_thread is None:
                self._linger_thread = threading.Thread(
                    target=self._linger_loop, daemon=True,
                    name="ray_tpu-lease-linger")
                self._linger_thread.start()

    def _linger_loop(self):
        """Return idle leases to the head after LEASE_LINGER_S; renew
        BUSY leases' TTLs periodically (a long-running pushed task emits
        no per-task renewals, and an unrenewed lease would be revoked
        out from under it).  The deadline comes from each lease's
        GRANTED ttl — the head's reaper expires against its own config,
        which a config-skewed external client does not share."""
        stall_t = self._fd_stall_t
        tick = (min(LEASE_LINGER_S / 2, stall_t / 2) if stall_t > 0
                else LEASE_LINGER_S / 2)
        while not self._stopped:
            time.sleep(tick)
            to_return: List[_Lease] = []
            renew: List[str] = []
            ping: List[_Lease] = []
            stalled: List[_Lease] = []

            def check_liveness(lease):
                # Channel-liveness watchdog (failure detection): a
                # channel with unacked pushes and no traffic for
                # stall_t gets a dping (answered by the executor's conn
                # thread even mid-compute — a LONG TASK is not a
                # stalled link); a probe unanswered for another full
                # window means the channel, and closing it routes
                # everything through the existing conn-EOF rediscovery.
                if (stall_t <= 0 or lease.conn is None or lease.dead
                        or not lease.inflight):
                    return
                if now - lease.last_recv <= stall_t:
                    return
                if lease.ping_sent <= lease.last_recv:
                    lease.ping_sent = now
                    ping.append(lease)
                elif now - lease.ping_sent > stall_t:
                    stalled.append(lease)

            now = time.monotonic()
            with self.lock:
                any_leases = False
                for pool in self.pools.values():
                    keep = []
                    for lease in pool["leases"]:
                        if (not lease.inflight and not pool["queue"]
                                and lease.idle_since is not None
                                and now - lease.idle_since
                                > LEASE_LINGER_S):
                            to_return.append(lease)
                        else:
                            keep.append(lease)
                            any_leases = True
                            if (lease.ttl > 0 and lease.inflight
                                    and now - lease.last_renew
                                    > lease.ttl / 3):
                                lease.last_renew = now
                                renew.append(lease.worker_id)
                            check_liveness(lease)
                    pool["leases"] = keep
                if stall_t > 0:
                    # Actor channels ride the same watchdog (their
                    # leases live outside the pools) and keep this
                    # thread alive while any exist.
                    for ch in self.actor_channels.values():
                        lease = ch.get("lease")
                        if lease is not None:
                            any_leases = True
                            check_liveness(lease)
            if ping:
                # Outside the lock (socket writes).  SO_SNDTIMEO on
                # direct-channel conns bounds these; a send failure IS
                # the stall verdict.
                for lease in ping:
                    try:
                        lease.send(("dping", 0))
                    except Exception:
                        stalled.append(lease)
            if stalled:
                for lease in stalled:
                    protocol.note_net_event("stall_timeouts")
                    try:
                        # Shutdown, not just close: the reader is by
                        # precondition parked inside a blocked recv,
                        # which close() cannot wake on Linux — shutdown
                        # EOFs it immediately.
                        protocol.shutdown_conn(lease.conn)
                        lease.conn.close()
                    except Exception:
                        pass
                    # The parked reader thread's recv now EOFs and
                    # runs _on_lease_dead: in-flight pushes reroute via
                    # the head exactly like conn-EOF discovery.
            if renew:
                try:
                    self.host.head_send(("lease_renew", renew))
                except Exception:
                    pass
            for lease in to_return:
                lease.dead = True
                try:
                    if lease.conn is not None:
                        lease.conn.close()
                except Exception:
                    pass
            if to_return:
                try:
                    self.host.head_send(
                        ("lease_return", [l.worker_id for l in to_return]))
                except Exception:
                    pass
            if not any_leases and not to_return:
                # Exit decision under the SAME lock acquisition that saw
                # zero leases — a concurrent grant either sees the thread
                # cleared (and respawns it) or appended its lease before
                # this scan (and the loop continues).
                with self.lock:
                    still_empty = not any(
                        p["leases"] for p in self.pools.values())
                    if still_empty:
                        self._linger_thread = None
                        return

    # --------------------------------------------------------------- get --
    def split_refs(self, refs):
        """Partition refs into (owned_here, foreign) for the get path."""
        owned, foreign = [], []
        with self.lock:
            for r in refs:
                st = self.owned.get(r.id())
                if st is not None and st.status != DELEGATED:
                    owned.append(r)
                else:
                    foreign.append(r)
        return owned, foreign

    def wait_owned(self, oids: List[ObjectID], timeout=None) -> bool:
        """Block until every owned oid is READY/ERRORED (DELEGATED counts
        as terminal here — the caller re-routes those to the head).
        Returns False on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self.lock:
            while True:
                pending = [o for o in oids
                           if (st := self.owned.get(o)) is not None
                           and st.status == PENDING]
                if not pending:
                    return True
                if deadline is not None:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        return False
                    self.cv.wait(left)
                else:
                    self.cv.wait()

    def wait_owned_n(self, oids: List[ObjectID], num_returns: int,
                     timeout) -> Tuple[List[bytes], List[bytes]]:
        """ray.wait over owned refs: block until ``num_returns`` are
        READY/ERRORED (or timeout / a ref gets delegated to the head).
        Returns (ready_bins capped at num_returns, delegated_bins) — the
        caller re-routes delegated ones to the head's wait."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self.lock:
            while True:
                ready, delegated = [], []
                for o in oids:
                    st = self.owned.get(o)
                    if st is None or st.status in (READY, ERRORED):
                        ready.append(o.binary())
                    elif st.status == DELEGATED:
                        delegated.append(o.binary())
                if len(ready) >= num_returns or delegated:
                    return ready[:num_returns], delegated
                if deadline is not None:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        return ready, delegated
                    self.cv.wait(left)
                else:
                    self.cv.wait()

    def descr_of(self, oid: ObjectID):
        with self.lock:
            st = self.owned.get(oid)
            if st is None:
                raise exc.ObjectFreedError(
                    object_id=oid.hex(),
                    owner=getattr(self.host, "worker_id_hex", None),
                    phase="get")
            if st.status == PENDING:
                raise exc.GetTimeoutError(f"Object {oid.hex()} not ready")
            return st.descr, st

    def status_of(self, oid: ObjectID) -> Optional[int]:
        with self.lock:
            st = self.owned.get(oid)
            return None if st is None else st.status

    # -------------------------------------------------------- recovery --
    def _lost_object_hex(self, descr) -> Optional[str]:
        """If an ERROR descriptor wraps a RECONSTRUCTABLE lost-object
        failure (directly, or as a TaskError's cause — the shape an
        executor's failed arg fetch produces), the lost object's id hex.
        Keys off the structured error fields, never message text."""
        if descr is None or descr[0] != protocol.ERROR:
            return None
        try:
            err = serialization.loads_inline(descr[1])
        except Exception:
            return None
        for e in (err, getattr(err, "cause", None)):
            if isinstance(e, exc.ObjectLostError):
                return e.object_id if e.reconstructable else None
        return None

    def reconstruct(self, oid: ObjectID, _visited=None) -> bool:
        """Rebuild a lost OWNED object by re-executing its producer from
        this caller's lineage (reference:
        ObjectRecoveryManager::RecoverObject — run by the owner, which
        is this process for direct-submitted tasks).  Covers both loss
        shapes: a READY object whose segment died with its node, and an
        ERRORED object whose producer failed fetching a lost argument —
        the argument reconstructs first (recursively, cycle-safe via
        ``_visited``), then the producer re-runs.  Bounded by the
        lineage entry's max_retries budget; returns True when the
        object is READY again (blocked getters already woke through the
        ownership cv)."""
        if self.lineage is None:
            return False
        visited = set() if _visited is None else _visited
        prefix = oid.binary()[:12]
        if prefix in visited:
            return False  # cycle guard: never re-enter a producer
        visited.add(prefix)
        entry = self.lineage.get(prefix)
        if entry is None:
            with self.lock:
                self.reconstruction_failures += 1
            return False
        spec = entry["spec"]
        for _attempt in range(2):
            with self.lock:
                st = self.owned.get(oid)
                if st is None or st.status == DELEGATED:
                    return False  # freed, or the head owns it now
                pending = st.status == PENDING
                err_descr = (st.descr if st.status == ERRORED else None)
            dep_hex = self._lost_object_hex(err_descr)  # loads: no lock
            if dep_hex:
                dep = ObjectID(bytes.fromhex(dep_hex))
                with self.lock:
                    dep_ours = dep in self.owned
                if dep_ours and dep.binary()[:12] != prefix \
                        and not self.reconstruct(dep, visited):
                    break
            if not pending:
                if not self.lineage.note_attempt(prefix):
                    break  # depleted retries: the loss stands
                self._resubmit_spec(spec)
            if not self.wait_owned([oid], timeout=60.0):
                break
            with self.lock:
                st = self.owned.get(oid)
                if st is not None and st.status == READY:
                    return True
            # ERRORED again: loop once — this attempt may have exposed
            # a lost dependency the next pass can rebuild first.
        with self.lock:
            self.reconstruction_failures += 1
        return False

    def _resubmit_spec(self, spec: dict):
        """Queue the producer again over the SAME task/object ids: the
        owned return states flip back to PENDING (their existing refs
        and waiters carry over — unlike submit_many, NO local_refs are
        added) and the spec rides the normal push path, transparently
        re-homing the results."""
        tid = TaskID(spec["task_id"])
        klass = self._sched_class(spec)
        with self.lock:
            for i in range(spec["num_returns"]):
                rst = self.owned.get(tid.object_id(i))
                if rst is not None and rst.status != DELEGATED:
                    rst.status = PENDING
                    rst.descr = None
                    rst.attached = False
                    rst.shipped = False
                    rst.creator = None
            self.reconstructions += 1
            entry = {"spec": spec, "rid": None, "retries": 0, "deps": 0,
                     "tid_bin": spec["task_id"], "pinned": ()}
            self._pool_locked(klass)["queue"].append(entry)
        self._pump(klass)

    # ------------------------------------------------------------- spill --
    def spill_owned(self, need_bytes: int, spill_dir: str) -> int:
        """Move this worker's unpinned owned resident objects to disk
        until ``need_bytes`` of shm is freed (per-node spilling;
        reference: LocalObjectManager::SpillObjects,
        local_object_manager.h:41 — the v1 design spilled only on the
        head node, so a remote node under pressure just died).  DELEGATED
        entries notify the head of the descriptor flip."""
        victims = []
        with self.lock:
            total = 0
            for oid, st in self.owned.items():
                if (st.descr is not None and st.descr[0] == protocol.SHM
                        and len(st.descr) > 3
                        and st.descr[3] == self.host.store_id
                        and st.creator is None
                        and st.status in (READY, DELEGATED)
                        and st.pins == 0 and not st.attached
                        and not st.shipped):
                    victims.append((oid, st))
                    total += st.descr[2]
                    if total >= need_bytes:
                        break
            for _oid, st in victims:
                st.pins += 1  # survive concurrent frees while copying
        freed = 0
        updates = []
        for oid, st in victims:
            name, size = st.descr[1], st.descr[2]
            try:
                path = self.host.shm.spill(name, size, spill_dir)
            except OSError:
                path = None
            with self.lock:
                st.pins -= 1
                if path is not None:
                    st.descr = (protocol.SPILLED, path, size,
                                self.host.store_id)
                    freed += size
                    if st.status == DELEGATED:
                        updates.append((oid.binary(), st.descr))
                self._maybe_free_locked(oid, st)
        if updates:
            try:
                self.host.head_send(("descr_update", updates))
            except Exception:
                pass
        self._flush_outbound()
        return freed

    # ------------------------------------------------------------ export --
    def export_refs(self, oid_bins) -> None:
        """Make owned objects visible to the head (one-way delegation):
        used when a spec/put carrying them goes through the head path, or
        when a return value embeds them.  The head entry starts with one
        aggregate ref standing for ALL of this process's local refs; the
        final local decref forwards to the head.  Transitive: nested owned
        refs inside an exported container export too (their local pins
        transfer to the head's nested-pin bookkeeping)."""
        batch = []
        unpin_after = []
        with self.lock:
            work = list(oid_bins)
            while work:
                b = work.pop()
                oid = ObjectID(b)
                st = self.owned.get(oid)
                if st is None or st.status == DELEGATED:
                    continue
                if st.status == PENDING:
                    # Export the shell now; _on_result_batch follows up with
                    # ("export_complete", ...).
                    batch.append((b, None, None, [], None))
                    st.status = DELEGATED
                    self._pending_exports.add(b)
                else:
                    inner = list(st.nested_local)
                    batch.append((b, st.status == READY, st.descr,
                                  inner + list(st.nested_head),
                                  (st.creator.worker_id
                                   if st.creator is not None else None)))
                    st.status = DELEGATED
                    # The head now pins nested on this entry's behalf;
                    # release our local pins (after the export message is
                    # on the wire) and export the inner refs too.
                    work.extend(inner)
                    unpin_after.append((st, inner))
                    st.nested_local = []
                    st.nested_head = []
        if not batch:
            return
        try:
            self.host.head_send(("export_obj", batch))
        except Exception:
            return
        with self.lock:
            for _st, inner in unpin_after:
                for b in inner:
                    ist = self.owned.get(ObjectID(b))
                    if ist is not None:
                        ist.pins -= 1
                        self._maybe_free_locked(ObjectID(b), ist)
        self._flush_outbound()

    def held_lease_ids(self) -> List[str]:
        """Worker ids of every live lease this process HOLDS — re-
        advertised at re-register so a restarted head can re-bind the
        lease table rows that survived it (the pushes themselves never
        touched the head)."""
        with self.lock:
            return sorted({lease.worker_id
                           for pool in self.pools.values()
                           for lease in pool["leases"]
                           if not lease.dead})

    def reregister_exports(self) -> List[tuple]:
        """Entries this owner DELEGATED to the (now restarted) head:
        (oid_bin, ok, descr, nested) rows re-advertised at re-register
        so head-routed consumers of our objects keep resolving.  PENDING
        shells are skipped — their export_complete rides the parked
        outbox replay."""
        out = []
        with self.lock:
            for oid, st in self.owned.items():
                if st.status != DELEGATED or st.descr is None:
                    continue
                out.append((oid.binary(),
                            st.descr[0] != protocol.ERROR,
                            st.descr, []))
        return out

    def shutdown(self):
        self._stopped = True
        self._send_event.set()  # unblock the push sender's exit
        with self.lock:
            leases = [l for p in self.pools.values() for l in p["leases"]]
        for lease in leases:
            try:
                if lease.conn is not None:
                    lease.conn.close()
            except Exception:
                pass


class DirectServer:
    """Executor half: accept direct connections from peer callers and feed
    their tasks into the worker's execution queue (reference: the core
    worker's task-receiver gRPC service, core_worker.cc HandlePushTask)."""

    def __init__(self, authkey: bytes, enqueue: Callable[[dict, Any], None],
                 register_func: Callable[[str, bytes], None],
                 shm_unlink: Callable[[str, int, bool], None],
                 on_peer_msg: Optional[Callable] = None,
                 queue_empty: Optional[Callable[[], bool]] = None,
                 on_task_queued: Optional[Callable[[dict], None]] = None,
                 queue_depth: Optional[Callable[[], int]] = None,
                 spill_depth: int = 0,
                 spill_info: Optional[dict] = None):
        from multiprocessing.connection import Listener

        host = os.environ.get("RAY_TPU_AGENT_LISTEN_HOST", "127.0.0.1")
        self._listener = Listener((host, 0), "AF_INET", backlog=128,
                                  authkey=authkey)
        adv = os.environ.get("RAY_TPU_AGENT_ADVERTISE_HOST")
        if adv is None:
            adv = host
            if adv == "0.0.0.0":
                import socket

                adv = socket.gethostbyname(socket.gethostname())
        self.address = (adv, self._listener.address[1])
        self._enqueue = enqueue
        self._register_func = register_func
        self._shm_unlink = shm_unlink
        self._on_peer_msg = on_peer_msg
        self._queue_empty = queue_empty or (lambda: True)
        # Called with each pushed task BEFORE it is enqueued — the
        # worker's argument prefetcher hook: a dexec_batch burst's tasks
        # 2..N land behind task 1 and start pulling their remote args
        # while it computes (direct-path submissions carry the same
        # (size, store) SHM descriptors the head path does).
        self._on_task_queued = on_task_queued
        # Spillback (reference: the raylet hybrid policy bouncing work
        # off an oversubscribed node): a pushed task that opted in
        # (``_spill_ok``, the capability gate) arriving while the local
        # queue is at least spill_depth deep is answered with
        # ("dspill", rid, spill_info) instead of queueing; the holder
        # re-lands it on another lease or the hinted node.  spill_depth
        # 0 disables.
        self._queue_depth = queue_depth or (lambda: 0)
        self._spill_depth = spill_depth
        self._spill_info = spill_info or {}
        # Live reply channels: the worker's exec loop flushes buffered
        # replies on queue drain; the periodic flusher bounds latency.
        self._sources: set = set()
        self._sources_lock = threading.Lock()
        self._stopped = False
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="ray_tpu-direct-accept").start()

    def flush_replies(self):
        with self._sources_lock:
            sources = list(self._sources)
        for src in sources:
            src.flush()

    def _accept_loop(self):
        while not self._stopped:
            try:
                conn = self._listener.accept()
                protocol.enable_nodelay(conn)
            except Exception:
                if self._stopped:
                    return
                continue
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True, name="ray_tpu-direct-rx").start()

    def _serve_conn(self, conn):
        src = _DirectSource(conn, self._queue_empty)
        with self._sources_lock:
            self._sources.add(src)
        try:
            self._serve_conn_inner(conn, src)
        finally:
            with self._sources_lock:
                self._sources.discard(src)

    def _serve_conn_inner(self, conn, src):
        while not self._stopped:
            try:
                msg = protocol.recv(conn)
            except (EOFError, OSError, TypeError):
                try:
                    conn.close()
                except Exception:
                    pass
                return
            if protocol.is_batch(msg):
                for m in msg[1]:
                    self._handle_direct_msg(m, src)
            else:
                self._handle_direct_msg(msg, src)

    def _should_spill(self, task: dict) -> bool:
        # queue_depth is live: the enqueue callback appends synchronously,
        # so tasks accepted earlier in this same batch already count.
        return (self._spill_depth > 0
                and task.get("_spill_ok")
                and "actor_id" not in task
                and self._queue_depth() >= self._spill_depth)

    def _handle_direct_msg(self, msg, src):
        tag = msg[0]
        if tag == "dexec":
            task = msg[2]
            if self._should_spill(task):
                src.spill(msg[1], self._spill_info)
                return
            task["_dreply"] = (src, msg[1])
            src.note_enqueued(1)
            if self._on_task_queued is not None:
                self._on_task_queued(task)
            self._enqueue(task, src)
        elif tag == "dexec_batch":
            for rid, task in msg[1]:
                if self._should_spill(task):
                    src.spill(rid, self._spill_info)
                    continue
                task["_dreply"] = (src, rid)
                src.note_enqueued(1)
                if self._on_task_queued is not None:
                    self._on_task_queued(task)
                self._enqueue(task, src)
        elif tag == "dping":
            # Channel-liveness probe: answer from THIS connection's
            # thread immediately (never buffered behind result batches
            # — the probe exists to distinguish a long task from a
            # stalled link).
            src.pong(msg[1])
        elif tag == "dfunc":
            self._register_func(msg[1], msg[2])
        elif tag == "dfree":
            try:
                self._shm_unlink(msg[1], msg[2], msg[3])
            except Exception:
                pass
        elif tag == "dmsg":
            # Generic peer-to-peer message (host-tier ring
            # collectives ride this; reference: the Gloo transport's
            # peer channels).  (channel, payload) dispatched to the
            # process-local handler registry.
            if self._on_peer_msg is not None:
                try:
                    self._on_peer_msg(msg[1], msg[2])
                except Exception:
                    import traceback

                    traceback.print_exc()

    def close(self):
        self._stopped = True
        try:
            self._listener.close()
        except Exception:
            pass


class _DirectSource:
    """Reply channel for one inbound direct connection.  Replies buffer
    while more tasks are queued behind the current one and ride out as one
    ``dresult_batch`` (mirrors the head-conn ``result_batch`` path) — the
    worker's exec loop flushes on queue drain and the periodic flusher
    bounds worst-case latency."""

    __slots__ = ("conn", "send_lock", "pending", "_queue_empty", "_queued")

    _FLUSH_AT = 16

    def __init__(self, conn, queue_empty=None):
        self.conn = conn
        self.send_lock = threading.Lock()  # lock-order: io-guard
        self.pending: List[tuple] = []
        self._queue_empty = queue_empty or (lambda: True)
        self._queued = 0  # THIS caller's tasks still unanswered

    def note_enqueued(self, n: int):
        with self.send_lock:
            self._queued += n

    def spill(self, rid, info):
        """Bounce one push back to the holder immediately (spillback is
        a flow-control signal — buffering it behind result batches would
        defeat the point)."""
        try:
            with self.send_lock:
                protocol.send(self.conn, ("dspill", rid, dict(info)))
        except Exception:
            pass  # caller went away; its death handling cleans up

    def pong(self, rid):
        """Immediate liveness reply (failure detection) — same
        flow-control exemption as spill()."""
        try:
            with self.send_lock:
                protocol.send(self.conn, ("dpong", rid))
        except Exception:
            pass  # caller went away; its death handling cleans up

    def reply(self, rid, ok, returns, meta):
        with self.send_lock:
            self.pending.append((rid, ok, returns, meta))
            self._queued -= 1
            n = len(self.pending)
            drained = self._queued <= 0
        # Flush on the CALLER's burst boundary, not the worker's global
        # queue: another client's pipelined backlog must not hold a sync
        # caller's lone reply hostage until the periodic flusher.
        if n >= self._FLUSH_AT or drained or self._queue_empty():
            self.flush()

    def flush(self):
        try:
            with self.send_lock:
                if not self.pending:
                    return
                buf, self.pending = self.pending, []
                if len(buf) == 1:
                    protocol.send(self.conn, ("dresult",) + buf[0])
                else:
                    protocol.send(self.conn, ("dresult_batch", buf))
        except Exception:
            pass  # caller went away; its death handling cleans up
