"""Shared-memory object segments — the plasma-store equivalent.

The reference's plasma (``src/ray/object_manager/plasma/store.h``,
``dlmalloc.cc``) is a single mmap arena with a malloc inside, served over a
unix-socket protocol, one store per node, embedded in the raylet.  On a TPU
VM the picture is simpler: host RAM is big, objects are mostly numpy/jax
host arrays moving between one driver and a handful of worker processes on
the same host.  So v1 uses one POSIX shm file per object under ``/dev/shm``
— creation is O(1), cross-process attach is just open+mmap, and the kernel
does refcounting of the mapping for us.  The plasma-arena analog is the
segment pool below: freed-but-still-mapped segments are recycled so writes
go through already-faulted pages at memcpy speed.

Each segment:  [8B magic][8B meta_len][meta pickle][aligned buffers...]

Zero-copy property: consumers ``mmap`` the file and reconstruct numpy/jax
host arrays as views over the mapping — same guarantee plasma gives
(``plasma/client.cc`` Get returns mmap'd buffers).
"""

from __future__ import annotations

import bisect
import mmap
import os
import struct
import threading
from typing import Any, List, Optional, Tuple

from ray_tpu._private import serialization
from ray_tpu._private.ids import ObjectID

_MAGIC = b"RTPUOBJ1"
_HEADER = struct.Struct("<8sQ")  # magic, meta_len

# Large-buffer writes fan out across threads: numpy's copy releases the
# GIL, so a single put saturates memory bandwidth instead of one core's
# memcpy (the plasma store's parallel memcopy, store.cc memcopy_threads).
#
# LOCK ORDER (checked by tests/test_lockcheck.py via devtools.lockcheck):
# the module-level ``_copy_pool_lock`` and every store's ``_lock`` are
# INDEPENDENT LEAVES — no code path may hold one while acquiring the
# other.  Concretely: ``create_from_parts`` runs its copies (which may
# take ``_copy_pool_lock`` to build the pool) BEFORE taking ``_lock`` for
# accounting, and nothing under ``_lock`` ever copies buffer bytes.
# Breaking this would serialize every store's 8 GB/s parallel memcpy
# behind one global mutex — or deadlock against a second store.
_PARALLEL_COPY_MIN = 16 << 20
_COPY_THREADS = min(8, max(1, (os.cpu_count() or 1)))
_copy_pool = None
_copy_pool_lock = threading.Lock()  # lock-order: leaf


def _parallel_copy(mm: mmap.mmap, off: int, buf) -> None:
    global _copy_pool
    import numpy as np

    n = len(buf)
    cores = os.cpu_count() or 1
    if cores < 2:
        mm[off : off + n] = buf
        return
    if _copy_pool is None:
        from concurrent.futures import ThreadPoolExecutor

        with _copy_pool_lock:
            if _copy_pool is None:
                _copy_pool = ThreadPoolExecutor(
                    max_workers=_COPY_THREADS,
                    thread_name_prefix="rtpu-memcpy")
    dst = np.frombuffer(mm, dtype=np.uint8, count=n, offset=off)
    src = np.frombuffer(memoryview(buf).cast("B"), dtype=np.uint8)
    threads = min(_COPY_THREADS, cores)
    step = (n + threads - 1) // threads
    futs = [
        _copy_pool.submit(np.copyto, dst[i : i + step], src[i : i + step])
        for i in range(0, n, step)
    ]
    for f in futs:
        f.result()
    del dst


def _segment_path(shm_dir: str, name: str) -> str:
    return os.path.join(shm_dir, name)


def segment_layout(meta: bytes, buffers: List[memoryview]):
    """(table_pickle, buffer_offsets, total_size) for the on-disk segment
    layout: [header][table][aligned buffers...].  The table is pickled
    together with the payload meta so readers need one load.  Two-pass:
    compute offsets assuming a table pickle of the final length; table
    size varies with offsets' magnitude only slightly, so pad
    generously instead of iterating.  Module-level because the layout is
    a WIRE contract too: a remote pusher (object_transfer.ObjectPusher)
    computes the identical image so its byte-range stripes land at the
    offsets local readers expect."""
    sizes = [len(b) for b in buffers]
    probe = serialization.dumps_inline(([0] * len(sizes), sizes, meta))
    table_room = len(probe) + 256
    base = _HEADER.size + table_room
    offsets, total = serialization.aligned_offsets(sizes, base)
    table = serialization.dumps_inline((offsets, sizes, meta))
    if len(table) > table_room:
        # Offsets grew the pickle beyond the pad (pathological); redo
        # with exact room.
        table_room = len(table) + 256
        base = _HEADER.size + table_room
        offsets, total = serialization.aligned_offsets(sizes, base)
        table = serialization.dumps_inline((offsets, sizes, meta))
    return table, offsets, total


class Segment:
    """An open mapping of one shared object."""

    __slots__ = ("name", "path", "size", "_mm", "_closed")

    def __init__(self, name: str, path: str, size: int, mm: mmap.mmap):
        self.name = name
        self.path = path
        self.size = size
        self._mm = mm
        self._closed = False

    def deserialize(self) -> Any:
        magic, meta_len = _HEADER.unpack_from(self._mm, 0)
        if magic != _MAGIC:
            raise ValueError(f"Corrupt shm segment {self.name}")
        view = memoryview(self._mm)
        meta = bytes(view[_HEADER.size : _HEADER.size + meta_len])
        # Buffer table is pickled inside meta as (offset, length) pairs by
        # the writer; serialization.loads reconstructs via these views.
        table_and_meta = serialization.loads_inline(meta)
        offsets, lengths, payload = table_and_meta
        buffers = [view[o : o + l] for o, l in zip(offsets, lengths)]
        return serialization.loads(payload, buffers)

    def raw_parts(self):
        """(meta, buffer views) WITHOUT deserializing — the wire form for
        cross-node object transfer (the head ships these to another store's
        consumer; reference: object_manager.h:206 chunk push/pull)."""
        magic, meta_len = _HEADER.unpack_from(self._mm, 0)
        if magic != _MAGIC:
            raise ValueError(f"Corrupt shm segment {self.name}")
        view = memoryview(self._mm)
        table = bytes(view[_HEADER.size: _HEADER.size + meta_len])
        offsets, lengths, payload = serialization.loads_inline(table)
        buffers = [view[o: o + l] for o, l in zip(offsets, lengths)]
        return payload, buffers

    def close(self):
        # The deserialized value may hold views into the mapping; mmap.close
        # will fail with BufferError if so — let the GC of those arrays
        # release it instead.  AttributeError: heap-backed receive
        # fallbacks wrap a bytearray, which has nothing to close.
        if self._closed:
            return
        self._closed = True
        try:
            self._mm.close()
        except (BufferError, AttributeError):
            pass


class ShmStore:
    """Create/attach/unlink shared object segments on this node.

    Reference analog: plasma store + client
    (``src/ray/object_manager/plasma/store.h``, ``client.cc``).  Eviction is
    the owner's job here (ownership-based freeing), not an LRU inside the
    store — TPU training workloads want deterministic memory, not surprise
    eviction of a batch mid-step.
    """

    def __init__(self, shm_dir: str = "/dev/shm", capacity: int = 0,
                 session_id: str = "", pool_bytes: int = 0):
        self._dir = shm_dir if os.path.isdir(shm_dir) else "/tmp"
        self._capacity = capacity
        self._session = session_id or os.urandom(4).hex()
        self._lock = threading.Lock()  # lock-order: leaf
        self._used = 0
        # Per-NODE accounting: every process writing this directory under
        # a capacity shares one flock'd counter file, so the cap bounds
        # the node's aggregate usage, not each process's (the reference
        # has one plasma store process per node; we have N writers).
        self._acct_fd = None
        if capacity:
            acct = os.path.join(self._dir, f".rtpu-acct-{self._session}")
            try:
                self._acct_fd = os.open(acct, os.O_CREAT | os.O_RDWR,
                                        0o600)
            except OSError:
                self._acct_fd = None
        self._created: set[str] = set()
        # Segment pool: freed-but-still-mapped segments kept for reuse.
        # Fresh tmpfs pages cost a fault + zero-fill per 4K page (~1 GB/s on
        # a TPU VM); writing through an already-faulted mapping runs at
        # memcpy speed (~8 GB/s).  This is the moral equivalent of plasma's
        # single pre-mapped arena + dlmalloc (``plasma/dlmalloc.cc``):
        # allocate pages once, recycle them across objects.  Only segments
        # whose descriptor never left this process may be pooled (the
        # caller passes ``reusable=True``) — otherwise another process may
        # still hold zero-copy views over the old inode.
        self._pool_limit = pool_bytes
        self._pool_bytes = 0
        self._pool: List[Tuple[int, str, mmap.mmap]] = []  # sorted by size
        self._live_mm: dict = {}  # name -> (mmap, alloc_size), pool=True only

    def _acct(self, delta: int) -> int:
        """Atomically add ``delta`` to the node-shared usage counter;
        returns the new value.  Caller holds self._lock."""
        if self._acct_fd is None:
            return self._used
        import fcntl

        fcntl.flock(self._acct_fd, fcntl.LOCK_EX)
        try:
            os.lseek(self._acct_fd, 0, os.SEEK_SET)
            raw = os.read(self._acct_fd, 16)
            cur = int(raw.decode() or "0") if raw else 0
            cur = max(0, cur + delta)
            os.lseek(self._acct_fd, 0, os.SEEK_SET)
            os.ftruncate(self._acct_fd, 0)
            os.write(self._acct_fd, str(cur).encode())
            return cur
        finally:
            fcntl.flock(self._acct_fd, fcntl.LOCK_UN)

    def _node_used(self) -> int:
        if self._acct_fd is None:
            return self._used
        return self._acct(0)

    def segment_name(self, object_id: ObjectID) -> str:
        return f"rtpu-{self._session}-{object_id.hex()}"

    def create_from_parts(self, object_id: ObjectID, meta: bytes,
                          buffers: List[memoryview]) -> Tuple[str, int]:
        """Write pre-serialized (meta, out-of-band buffers) into a segment —
        the plasma create→write-in-place→seal path (``plasma/client.cc``):
        the caller serializes once and each buffer is memcpy'd exactly once,
        directly into shared memory."""
        table, offsets, total = self._layout(meta, buffers)
        name, mm, alloc = self._acquire_segment(object_id, total)
        _HEADER.pack_into(mm, 0, _MAGIC, len(table))
        mm[_HEADER.size : _HEADER.size + len(table)] = table
        for off, buf in zip(offsets, buffers):
            if len(buf) >= _PARALLEL_COPY_MIN:
                _parallel_copy(mm, off, buf)
            else:
                mm[off : off + len(buf)] = buf
        if self._pool_limit:
            # Keep the mapping open so a future reuse writes through
            # already-faulted pages; released in unlink()/cleanup().
            with self._lock:
                self._live_mm[name] = (mm, alloc)
        else:
            mm.close()
        with self._lock:
            self._used += alloc
            self._acct(alloc)
            self._created.add(name)
        return name, alloc

    def _layout(self, meta: bytes, buffers: List[memoryview]):
        return segment_layout(meta, buffers)

    def _acquire_segment(self, object_id: ObjectID, total: int):
        """A writable mapping of >= ``total`` bytes: pooled if one fits
        (within 2x waste), else a fresh shm file.  Fresh allocations evict
        pooled (free) segments first when that makes room under capacity."""
        evict = []
        new_name = self.segment_name(object_id)
        with self._lock:
            for i, (size, name, mm) in enumerate(self._pool):
                if size >= total:
                    if size <= 2 * total + (1 << 20):
                        self._pool.pop(i)
                        self._pool_bytes -= size
                        self._used -= size  # re-added by create_from_parts
                        self._acct(-size)
                        # Rename to the new object's canonical name: the
                        # mmap stays valid (it binds the inode, not the
                        # path) and the segment-name -> ObjectID invariant
                        # that lineage recovery parses stays true.
                        os.rename(_segment_path(self._dir, name),
                                  _segment_path(self._dir, new_name))
                        return new_name, mm, size
                    break  # sorted: everything later is even more wasteful
            if self._capacity:
                node_used = self._evict_pool_until_fits_locked(total,
                                                               evict)
                if node_used + total > self._capacity:
                    raise MemoryError(
                        f"Object store over capacity: need {total}, "
                        f"node used {node_used}/{self._capacity}")
        self._close_evicted(evict)
        name = self.segment_name(object_id)
        path = _segment_path(self._dir, name)
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        try:
            os.ftruncate(fd, total)
            mm = mmap.mmap(fd, total)
        finally:
            os.close(fd)
        return name, mm, total

    def _evict_pool_until_fits_locked(self, total: int,
                                      evict: list) -> int:
        """Pooled bytes are free memory: pop pool entries (appending
        them to ``evict``) until ``total`` fits under the node cap or
        the pool is empty; returns the final node usage.  The cap
        applies to the whole NODE's usage (shared flock'd counter), not
        this process's.  Caller holds ``self._lock`` and must pass
        ``evict`` to ``_close_evicted`` AFTER releasing it.  One
        implementation for every admission site (_acquire_segment,
        reserve_put) — the shared-counter policy must not diverge."""
        node_used = self._node_used()
        while node_used + total > self._capacity and self._pool:
            size, name, mm = self._pool.pop()
            self._pool_bytes -= size
            self._used -= size
            node_used = self._acct(-size)
            evict.append((name, mm))
        return node_used

    def _close_evicted(self, evict: list):
        for name, mm in evict:
            try:
                mm.close()
            except BufferError:
                pass
            try:
                os.unlink(_segment_path(self._dir, name))
            except OSError:
                pass

    # ------------------------------------------------- zero-copy receive --
    # The cross-node puller's destination buffers (object_transfer.
    # pull_to_segment): reserve a writable mapping up front, let the
    # network stack recv_bytes_into it at final offsets, then seal it as
    # a read Segment.  The backing file is unlinked the moment the
    # mapping exists (an mmap binds the inode, not the path), so a
    # received replica is private to this process, can never collide
    # with the canonical segment name, needs no free/eviction
    # bookkeeping, and cannot leak even if the process dies
    # mid-receive — the kernel reclaims the pages when the last view
    # over the mapping is dropped.

    def reserve_recv(self, name: str, total: int) -> mmap.mmap:
        """A writable ``total``-byte shm mapping for an incoming copy of
        segment ``name``.  Pair with ``commit_recv`` (success) or
        ``abort_recv`` (failure)."""
        if total <= 0:
            raise ValueError(f"cannot reserve {total}-byte segment {name}")
        if self._capacity:
            # Reservations are transient (freed when the consumer drops
            # the value) and deliberately NOT added to the node counter —
            # but a pull that clearly cannot fit must not sparsely
            # overcommit tmpfs and SIGBUS mid-receive.  Raising here
            # sends the caller to its heap-buffer fallback
            # (object_transfer.pull_to_segment), which keeps the store's
            # accounted capacity intact — the pre-reserve behavior.
            with self._lock:
                used = self._node_used()
            if used + total > self._capacity:
                raise MemoryError(
                    f"recv reservation over store capacity: need {total}, "
                    f"node used {used}/{self._capacity}")
        # basename: remote SPILLED descriptors name segments by absolute
        # path; the reservation always lives in THIS store's directory.
        path = _segment_path(
            self._dir,
            f"{os.path.basename(name)}.recv-{os.urandom(4).hex()}")
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        try:
            os.ftruncate(fd, total)
            mm = mmap.mmap(fd, total)
        finally:
            os.close(fd)
        try:
            os.unlink(path)
        except OSError:
            pass
        return mm

    def commit_recv(self, name: str, mm: mmap.mmap, total: int) -> Segment:
        """Seal a filled reservation as a read Segment (its buffers are
        zero-copy views over the received mapping)."""
        return Segment(name, "", total, mm)

    def abort_recv(self, mm: mmap.mmap):
        try:
            mm.close()
        except BufferError:
            pass

    # --------------------------------------------------- direct-put ingest --
    # The write-direction twin of reserve_recv: a remote pusher
    # (object_transfer verbs reserve_put/put_range/commit_put) streams a
    # value's byte-range stripes straight into a preallocated mapping —
    # but unlike a received replica, the destination is a PUBLIC named
    # segment other processes on this node will attach, so the file
    # stays linked, the bytes are capacity-accounted up front (admission
    # gates on the NODE counter, so concurrent pushers cannot overcommit
    # tmpfs), and an over-capacity reservation degrades to the spill
    # path (a disk-backed mapping under ``spill_dir``) instead of
    # raising — the reference's plasma CreateObject fallback queue.

    # Set by the embedding runtime/agent after construction; "" disables
    # the spill degradation (over-capacity reservations then raise).
    spill_dir: str = ""

    def reserve_put(self, oid_bin: bytes, total: int) -> "PutReservation":
        """A writable mapping for a pushed object, registered under the
        object's canonical public segment name.  Pair with the
        reservation's ``commit()`` (seal; file stays) or ``abort()``
        (unlink + accounting rollback)."""
        if total <= 0:
            raise ValueError(f"cannot reserve {total}-byte put")
        name = self.segment_name(ObjectID(oid_bin))
        evict = []
        over = False
        newly_tracked = False
        with self._lock:
            if self._capacity:
                node_used = self._evict_pool_until_fits_locked(total,
                                                               evict)
                over = node_used + total > self._capacity
            if not over:
                self._used += total
                self._acct(total)
                newly_tracked = name not in self._created
                self._created.add(name)
        self._close_evicted(evict)
        if over:
            if not self.spill_dir:
                raise MemoryError(
                    f"put reservation over store capacity: need {total} "
                    f"(capacity {self._capacity}) and no spill_dir")
            os.makedirs(self.spill_dir, exist_ok=True)
            path = os.path.join(self.spill_dir, name)
            mm = self._map_new_file(path, total)
            return PutReservation(self, "spilled", name, path, total, mm)
        path = _segment_path(self._dir, name)
        try:
            mm = self._map_new_file(path, total)
        except BaseException:
            with self._lock:
                # Roll back only what THIS call added: on an EEXIST
                # collision the _created entry belongs to the existing
                # segment, not to us.
                if newly_tracked:
                    self._created.discard(name)
                self._used -= total
                self._acct(-total)
            raise
        return PutReservation(self, "shm", name, name, total, mm)

    @staticmethod
    def _map_new_file(path: str, total: int) -> mmap.mmap:
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        try:
            os.ftruncate(fd, total)
            return mmap.mmap(fd, total)
        finally:
            os.close(fd)

    def _finish_put(self, res: "PutReservation", commit: bool):
        try:
            res.mm.close()
        except BufferError:
            pass  # a straggling writer's view; the GC releases it
        if commit:
            return
        try:
            os.unlink(res.ident if res.kind == "spilled"
                      else _segment_path(self._dir, res.name))
        except OSError:
            pass
        if res.kind == "shm":
            with self._lock:
                self._created.discard(res.name)
                self._used -= res.total
                self._acct(-res.total)

    def attach(self, name: str) -> Segment:
        return self.attach_path(_segment_path(self._dir, name))

    def attach_path(self, path: str) -> Segment:
        """Map a segment by absolute path — shm or a spill file (restore
        path; reference: local_object_manager.h:41 restore-from-external).
        The on-disk layout is identical, so readers cannot tell spilled
        objects from resident ones."""
        fd = os.open(path, os.O_RDONLY)
        try:
            size = os.fstat(fd).st_size
            mm = mmap.mmap(fd, size, prot=mmap.PROT_READ)
        finally:
            os.close(fd)
        return Segment(os.path.basename(path), path, size, mm)

    def spill(self, name: str, size: int, spill_dir: str) -> str:
        """Copy a resident segment to ``spill_dir`` and free its shm pages
        (reference: LocalObjectManager::SpillObjects,
        local_object_manager.h:41).  Copy (not rename): /dev/shm -> disk is
        cross-device, and the point is releasing tmpfs RAM."""
        import shutil

        os.makedirs(spill_dir, exist_ok=True)
        src = _segment_path(self._dir, name)
        dst = os.path.join(spill_dir, name)
        with open(src, "rb") as f, open(dst, "wb") as g:
            shutil.copyfileobj(f, g, 1 << 20)
        self.unlink(name, size)
        return dst

    def create_spilled(self, object_id: ObjectID, meta: bytes,
                       buffers: List[memoryview],
                       spill_dir: str) -> Tuple[str, int]:
        """Serialize directly to a spill file, bypassing shm entirely — the
        over-capacity path when nothing (enough) can be evicted."""
        os.makedirs(spill_dir, exist_ok=True)
        table, offsets, total = self._layout(meta, buffers)
        path = os.path.join(spill_dir, self.segment_name(object_id))
        with open(path, "wb") as f:
            mm_bytes = bytearray(_HEADER.size)
            _HEADER.pack_into(mm_bytes, 0, _MAGIC, len(table))
            f.write(mm_bytes)
            f.write(table)
            pos = _HEADER.size + len(table)
            for off, buf in zip(offsets, buffers):
                if off > pos:
                    f.write(b"\x00" * (off - pos))
                f.write(buf)
                pos = off + len(buf)
        return path, total

    def unlink(self, name: str, size: int = 0, reusable: bool = False):
        """Free a segment.  ``reusable=True`` (caller guarantees no other
        process ever saw this segment's descriptor) pools the still-open
        mapping for in-place reuse instead of returning pages to the kernel.
        """
        with self._lock:
            entry = self._live_mm.pop(name, None)
            if (reusable and entry is not None
                    and self._pool_bytes + entry[1] <= self._pool_limit):
                mm, alloc = entry
                bisect.insort(self._pool, (alloc, name, mm),
                              key=lambda t: t[0])
                self._pool_bytes += alloc
                self._created.discard(name)
                return
        if entry is not None:
            try:
                entry[0].close()
            except BufferError:
                pass
        path = _segment_path(self._dir, name)
        removed = False
        try:
            os.unlink(path)
            removed = True
        except FileNotFoundError:
            pass
        with self._lock:
            if name in self._created:
                self._created.discard(name)
                self._used -= size
                self._acct(-size)
            elif removed and size:
                # Another process created this segment (owner-routed
                # free): its bytes leave the node-shared count here.
                self._acct(-size)

    def cleanup(self):
        """Unlink everything this process created (driver shutdown path)."""
        with self._lock:
            names = list(self._created)
            names += [name for _, name, _ in self._pool]
            mms = [mm for mm, _ in self._live_mm.values()]
            mms += [mm for _, _, mm in self._pool]
            self._created.clear()
            self._live_mm.clear()
            self._pool.clear()
            self._pool_bytes = 0
            self._used = 0
        for mm in mms:
            try:
                mm.close()
            except BufferError:
                pass
        for name in names:
            try:
                os.unlink(_segment_path(self._dir, name))
            except OSError:
                pass
        if self._acct_fd is not None:
            try:
                os.close(self._acct_fd)
                os.unlink(os.path.join(self._dir,
                                       f".rtpu-acct-{self._session}"))
            except OSError:
                pass
            self._acct_fd = None


class PutReservation:
    """One pending direct-put destination: a writable public mapping the
    object server's ``put_range`` stripes recv straight into.

    ``kind`` is ``"shm"`` (``ident`` == segment name) or ``"spilled"``
    (``ident`` == absolute spill-file path — the over-capacity
    degradation).  ``writers``/``dead`` belong to the object server's
    put registry (guarded by ITS lock): concurrent stripe connections
    ref-count in-flight writes so an abort never closes the mapping
    under an active ``recv_bytes_into``."""

    __slots__ = ("store", "kind", "name", "ident", "total", "mm",
                 "writers", "dead")

    def __init__(self, store: ShmStore, kind: str, name: str, ident: str,
                 total: int, mm: mmap.mmap):
        self.store = store
        self.kind = kind
        self.name = name
        self.ident = ident
        self.total = total
        self.mm = mm
        self.writers = 0
        self.dead = False

    def commit(self):
        """Seal: close the writable mapping; the (linked, accounted)
        file becomes attachable like any locally-created segment."""
        self.store._finish_put(self, commit=True)

    def abort(self):
        """Tear down: close + unlink + restore store accounting."""
        self.store._finish_put(self, commit=False)


def put_local(store: ShmStore, oid_bin: bytes, meta: bytes,
              buffers: List[memoryview]):
    """Write a full segment image into THIS node's store through the
    same reserve/commit admission the remote put verbs use — the local
    short-circuit of ``ObjectPusher.push`` (a shuffle map task whose
    reducer lives on its own node must not dial itself).  Inherits
    reserve_put's over-capacity degradation, so the return mirrors the
    pusher's: ``(kind, ident, total)`` with kind ``"shm"`` or
    ``"spilled"``."""
    table, offsets, total = segment_layout(meta, buffers)
    res = store.reserve_put(oid_bin, total)
    try:
        mm = res.mm
        _HEADER.pack_into(mm, 0, _MAGIC, len(table))
        mm[_HEADER.size: _HEADER.size + len(table)] = table
        for off, buf in zip(offsets, buffers):
            if len(buf) >= _PARALLEL_COPY_MIN:
                _parallel_copy(mm, off, buf)
            else:
                mm[off: off + len(buf)] = buf
    except BaseException:
        res.abort()
        raise
    res.commit()
    return res.kind, res.ident, total
