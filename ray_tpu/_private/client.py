"""Client mode: attach an external process to a running cluster.

Reference: ``python/ray/util/client/`` (Ray Client — a gRPC proxy that
lets a process outside the cluster drive tasks/actors/objects;
ARCHITECTURE.md).  Re-designed for this runtime's symmetric worker
protocol: a client IS a worker connection that never takes a lease — it
dials the head's TCP listener, handshakes ``client_ready``, and then the
existing submit/mget/put/actor messages just work.  Large values ship as
parts and land in the HEAD's store (clients cannot assume a shared
/dev/shm), and large results stream back via the direct object-transfer
pull or the head relay.

Usage::

    import ray_tpu as ray
    ray.init(address="tcp://head:port", _authkey="<hex>")
    # or env: RAY_TPU_CLIENT_ADDRESS / RAY_TPU_CLIENT_AUTHKEY
"""

from __future__ import annotations

import os
import pickle
import tempfile
import threading
from typing import Optional

from ray_tpu._private import object_transfer, protocol, serialization
from ray_tpu._private import object_ref as object_ref_mod
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.shm_store import ShmStore
from ray_tpu._private.worker_main import _WorkerRuntime

# Small-put coalescing bounds: buffered inline puts flush as ONE
# ("batch", ...) pickle+write once this many accumulate (or this many
# payload bytes), before any other outgoing message, and at worst on the
# 0.25s periodic flusher.
_PUT_FLUSH_COUNT = 16
_PUT_FLUSH_BYTES = 4 << 20

# Direct-put floor: below this, the legacy fire-and-forget put_parts
# message (one local pickle+write, no reply awaited) beats the direct
# path's three blocking round trips (reserve ack, range ack, commit ack)
# on any link with real latency; above it, transfer time dominates and
# the zero-copy data plane wins.
_DIRECT_PUT_MIN = 4 << 20


class ClientRuntime(_WorkerRuntime):
    """Worker runtime minus execution: submits, gets, puts, actors."""

    is_client = True

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        # (store_id, object_addr, caps) of the head's object server,
        # from the client_ack info dict — None against an old head (no
        # info element) keeps every put on the legacy path.
        self._head_put_info = None
        # Failover re-dial target, set by client_connect (clients have
        # no RAY_TPU_ADDRESS env; the worker-flavor _redial is
        # overridden below).
        self._address = None
        self._authkey = b""
        # Buffered small ("put", ...)/("addref", ...) message pairs:
        # many tiny puts ride out as one pickle+write instead of one
        # each (PR 2's conflation envelope, applied to the put path).
        self._put_buf: list = []
        self._put_buf_bytes = 0
        self._put_lock = threading.Lock()  # lock-order: leaf

    def put_object(self, value) -> ObjectRef:
        oid = ObjectID.for_put()
        self.begin_ref_collection()
        try:
            res = serialization.dumps_adaptive(value, self.max_inline)
        finally:
            nested = self.end_ref_collection()
        if res[0] == "inline":
            # Coalesced: the ref's addref rides the same buffer (in
            # order), so _register=False below — the head still counts
            # exactly one ref for this client.
            self._queue_small_put(
                ("put", oid.binary(), (protocol.INLINE, res[1]), nested),
                oid, len(res[1]))
            self._cache_put(oid, value)
            return ObjectRef(oid, _register=False)
        descr = (self._direct_put(oid, res[1], res[2])
                 if res[3] >= _DIRECT_PUT_MIN else None)
        if descr is not None:
            # Payload already landed in the head's store over the data
            # plane; the control connection carries only this O(1)
            # commit.
            self._send(("put_commit", oid.binary(), descr, nested))
        else:
            # Legacy path: ship parts for the head to assemble into ITS
            # store (clients share no /dev/shm).  PickleBuffer wrapping
            # sends the buffer views — pickle already copies once into
            # the message stream; the old [bytes(b) ...] copied twice.
            self._send(("put_parts", oid.binary(), res[1],
                        [pickle.PickleBuffer(b) for b in res[2]], nested))
        self._cache_put(oid, value)
        return ObjectRef(oid)

    def _direct_put(self, oid: ObjectID, meta, views):
        """Push a large value straight into the head's store over the
        object-transfer data plane; returns the committed descriptor, or
        None (caller falls back to legacy put_parts) when the head never
        advertised the put verbs, the master switch is off, or the push
        failed."""
        from ray_tpu._private.config import GLOBAL_CONFIG as _cfg

        info = self._head_put_info
        if info is None or not _cfg.direct_puts:
            return None
        store_id, addr, caps = info
        if not object_transfer.peer_accepts_puts(caps):
            return None
        try:
            kind, ident, size = self._pusher.push(
                store_id, addr, oid.binary(), meta, views, caps=caps)
        except Exception:
            return None
        if kind == "spilled":
            # Admission degraded the reservation to the head node's
            # spill path rather than overcommitting tmpfs.
            return (protocol.SPILLED, ident, size, store_id)
        return (protocol.SHM, ident, size, store_id)

    def _queue_small_put(self, msg, oid: ObjectID, nbytes: int):
        with self._put_lock:
            self._put_buf.append(msg)
            self._put_buf.append(("addref", oid.binary()))
            self._put_buf_bytes += nbytes
            full = (len(self._put_buf) >= 2 * _PUT_FLUSH_COUNT
                    or self._put_buf_bytes >= _PUT_FLUSH_BYTES)
        if full:
            self.flush_puts()

    def _drain_put_buffer(self) -> list:
        with self._put_lock:
            buf, self._put_buf = self._put_buf, []
            self._put_buf_bytes = 0
        return buf

    def flush_puts(self):
        # Drain under send_lock: a drained-but-unwritten batch here must
        # not let a concurrent _send (whose message may reference one of
        # these puts) overtake it on the wire.  _send_wire parks the
        # batch across a head blip instead of raising.
        with self.send_lock:
            buf = self._drain_put_buffer()
            self._send_wire(buf)

    def serialize_value(self, value, object_id: ObjectID):
        """By-value task args travel inline or as parts inside the spec —
        never via a client-local shm segment nobody else can map.

        bytes() SNAPSHOT, deliberately: unlike put_object (whose message
        pickles synchronously before return), a spec can sit UNPICKLED
        in lease queues / dep-wait lists and be (re)pickled much later —
        live PickleBuffer views would capture the caller's buffer at
        push time, so a mutation after .remote() (reused rollout
        buffers) would tear the argument."""
        res = serialization.dumps_adaptive(value, self.max_inline)
        if res[0] == "inline":
            return (protocol.INLINE, res[1])
        return (protocol.PARTS, res[1], [bytes(b) for b in res[2]])

    def request(self, builder):
        """Generic control request (cluster_info, jobs, state...)."""
        return self._request(builder)

    # Client-side spellings of the head's introspection surface (the
    # failover drill drives an external head purely through a client).
    def list_nodes(self):
        return self.request(lambda rid: ("cluster_info", rid))["nodes"]

    def state_query(self, kind: str, **kwargs):
        out = self.request(lambda rid: ("state_req", rid, kind, kwargs))
        if isinstance(out, Exception):
            raise out
        return out

    def transfer_stats(self):
        return self.state_query("transfer_stats")[0]

    def dial(self, addr):
        """Direct-plane dials (granted lease workers, actor channels)
        use THIS session's authkey — the env fallback the worker-side
        dial reads may hold a stale key from an earlier client session
        in the same process (client_connect's setdefault), which would
        silently break every lease adoption with an auth error.
        Deadline-aware (connect timeout + SO_KEEPALIVE) like every
        other dial site."""
        conn = protocol.dial(tuple(addr), authkey=self._authkey)
        if self._fd_on and self._net_stall_t > 0:
            # Send half only (see _WorkerRuntime.dial).
            protocol.set_send_deadline(conn, self._net_stall_t)
        return conn

    # -- head failover (client flavor of the worker machinery) -------------
    def _redial(self):
        return protocol.dial(protocol.parse_address(self._address),
                             authkey=self._authkey)

    def _re_handshake(self, conn):
        """Clients re-enter through the client_ready handshake (which
        refreshes the head's direct-put bootstrap), then re-register
        in-band: held leases and delegated objects re-advertised so the
        restarted head can reconcile them."""
        protocol.send(conn, ("client_ready", os.urandom(16).hex()))
        msg = protocol.recv(conn)
        if msg[0] != "client_ack":
            return None
        info = msg[2] if len(msg) > 2 else {}
        if isinstance(info, dict) and info.get("object_addr") \
                and info.get("store_id"):
            self._head_put_info = (info["store_id"],
                                   info["object_addr"],
                                   tuple(info.get("object_caps") or ()))
        protocol.send(conn, ("reregister", {
            "held_leases": self.direct.held_lease_ids(),
            "objects": self.direct.reregister_exports(),
        }))
        return True

    def disconnect(self):
        self._shutting_down = True  # the reader must exit, not re-dial
        try:
            self.flush_puts()
            self.flush_decrefs()
        except Exception:
            pass
        try:
            self.conn.close()
        except Exception:
            pass
        for pools in (self._puller, self._pusher):
            try:
                pools.close()
            except Exception:
                pass
        from ray_tpu._private import api_internal

        if api_internal.get_runtime() is self:
            api_internal.set_global_runtime(None)


def client_connect(address: str, authkey: bytes,
                   max_inline: int = 1024 * 1024) -> ClientRuntime:
    import time

    addr = protocol.parse_address(address)
    conn = None
    err: Optional[BaseException] = None
    for attempt in range(20):
        try:
            # Deadline-aware dial: a black-holed head address fails
            # each attempt in net_connect_timeout_s (the kernel default
            # is ~2 min — twenty of those is not a retry loop).
            conn = protocol.dial(addr, authkey=authkey)
            break
        except (ConnectionError, OSError) as e:
            err = e
            time.sleep(0.1 * (attempt + 1))
    if conn is None:
        raise ConnectionError(f"cannot reach cluster at {address}: {err}")
    os.environ.setdefault("RAY_TPU_AUTHKEY", authkey.hex())
    shm = ShmStore(shm_dir=tempfile.mkdtemp(prefix="ray_tpu_client_"))
    send_lock = threading.Lock()  # lock-order: io-guard
    rt = ClientRuntime(conn, send_lock, shm, max_inline)
    rt._address = address
    rt._authkey = authkey
    # The puller dials remote object servers (including the head's own —
    # large results stream back directly instead of relaying through the
    # control-plane connection).  Hand it THIS cluster's authkey
    # explicitly: the env setdefault above must not leave a stale key
    # from an earlier session on the pull path.
    rt._puller._authkey = authkey
    rt._pusher._authkey = authkey
    protocol.send(conn, ("client_ready", os.urandom(16).hex()))
    msg = protocol.recv(conn)
    assert msg[0] == "client_ack", msg
    rt.store_id = f"client-{os.urandom(4).hex()}"  # nothing shares it
    # Direct-put bootstrap (this release's heads): the head's store
    # identity + object-server address + advertised verbs.  An old
    # 2-tuple ack leaves _head_put_info None — every put then rides the
    # legacy put_parts path, and no new verb is ever sent.
    info = msg[2] if len(msg) > 2 else {}
    if isinstance(info, dict) and info.get("object_addr") \
            and info.get("store_id"):
        rt._head_put_info = (info["store_id"], info["object_addr"],
                             tuple(info.get("object_caps") or ()))

    def handle(m):
        tag = m[0]
        if protocol.is_batch(m):
            # Conflation-sender frame from the head: unwrap in order.
            for sub in m[1]:
                handle(sub)
        elif tag == "obj":
            rt.deliver_reply(m[1], (m[2], m[3]))
        elif tag == "mgot":
            rt.deliver_reply(m[1], m[2])
        elif tag == "waited":
            rt.deliver_reply(m[1], m[2])
        elif tag == "reply":
            rt.deliver_reply(m[1], m[2])
        elif tag == "lease_grant":
            # Unsolicited bulk grant piggybacked on this client's
            # head-brokered submit burst; adopt off the reader thread
            # (adoption dials the granted workers).
            threading.Thread(
                target=rt.direct.adopt_grant,
                args=(m[1], m[2], m[3], m[4], m[5]),
                daemon=True, name="ray_tpu-client-lease").start()
        elif tag == "lease_revoke":
            rt.direct.revoke(m[1])

    def reader():
        while True:
            try:
                m = protocol.recv(rt.conn)
            except (EOFError, OSError, TypeError):
                # Head gone.  Park in-flight calls and re-dial for the
                # grace window (worker-flavor machinery, client-flavor
                # handshake) — a head restart becomes a stall, not a
                # dead session.  disconnect() sets _shutting_down so a
                # deliberate close still exits here.
                if not rt._reconnect_head():
                    return
            else:
                rt.note_head_recv()  # any head message is liveness
                handle(m)

    threading.Thread(target=reader, daemon=True,
                     name="ray_tpu-client-reader").start()

    def flusher():
        import time as _t

        while True:
            _t.sleep(0.25)
            try:
                rt.flush_decrefs()
                # Lease-plane counter deltas (leased_submits/spillbacks):
                # a client drives direct pushes too and its counters feed
                # the same head-side transfer_stats aggregation.
                rt.flush_xfer_stats()
                # Failure detection: heartbeat floor + stalled-head
                # watchdog (client flavor of the worker machinery).
                rt.heartbeat_and_watchdog()
            except Exception:
                return

    threading.Thread(target=flusher, daemon=True,
                     name="ray_tpu-client-flush").start()
    # Route ObjectRef callbacks through the GLOBAL accessor, not a
    # closure over this client: after disconnect + re-init, refs must
    # see the new runtime, not a closed connection.
    from ray_tpu._private import api_internal

    object_ref_mod._set_runtime_accessor(api_internal.get_runtime)
    return rt
