"""Client mode: attach an external process to a running cluster.

Reference: ``python/ray/util/client/`` (Ray Client — a gRPC proxy that
lets a process outside the cluster drive tasks/actors/objects;
ARCHITECTURE.md).  Re-designed for this runtime's symmetric worker
protocol: a client IS a worker connection that never takes a lease — it
dials the head's TCP listener, handshakes ``client_ready``, and then the
existing submit/mget/put/actor messages just work.  Large values ship as
parts and land in the HEAD's store (clients cannot assume a shared
/dev/shm), and large results stream back via the direct object-transfer
pull or the head relay.

Usage::

    import ray_tpu as ray
    ray.init(address="tcp://head:port", _authkey="<hex>")
    # or env: RAY_TPU_CLIENT_ADDRESS / RAY_TPU_CLIENT_AUTHKEY
"""

from __future__ import annotations

import os
import tempfile
import threading
from typing import Optional

from ray_tpu._private import protocol, serialization
from ray_tpu._private import object_ref as object_ref_mod
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.shm_store import ShmStore
from ray_tpu._private.worker_main import _WorkerRuntime


class ClientRuntime(_WorkerRuntime):
    """Worker runtime minus execution: submits, gets, puts, actors."""

    is_client = True

    def put_object(self, value) -> ObjectRef:
        oid = ObjectID.for_put()
        self.begin_ref_collection()
        try:
            res = serialization.dumps_adaptive(value, self.max_inline)
        finally:
            nested = self.end_ref_collection()
        if res[0] == "inline":
            self._send(("put", oid.binary(),
                        (protocol.INLINE, res[1]), nested))
        else:
            # Ship parts: the head writes them into ITS store so cluster
            # workers can consume them (clients share no /dev/shm).
            self._send(("put_parts", oid.binary(), res[1],
                        [bytes(b) for b in res[2]], nested))
        self._cache_put(oid, value)
        return ObjectRef(oid)

    def serialize_value(self, value, object_id: ObjectID):
        """By-value task args travel inline or as parts inside the spec —
        never via a client-local shm segment nobody else can map."""
        res = serialization.dumps_adaptive(value, self.max_inline)
        if res[0] == "inline":
            return (protocol.INLINE, res[1])
        return (protocol.PARTS, res[1], [bytes(b) for b in res[2]])

    def request(self, builder):
        """Generic control request (cluster_info, jobs, state...)."""
        return self._request(builder)

    def disconnect(self):
        try:
            self.flush_decrefs()
        except Exception:
            pass
        try:
            self.conn.close()
        except Exception:
            pass
        from ray_tpu._private import api_internal

        if api_internal.get_runtime() is self:
            api_internal.set_global_runtime(None)


def client_connect(address: str, authkey: bytes,
                   max_inline: int = 1024 * 1024) -> ClientRuntime:
    import time
    from multiprocessing.connection import Client as _Dial

    addr = protocol.parse_address(address)
    conn = None
    err: Optional[BaseException] = None
    for attempt in range(20):
        try:
            conn = _Dial(addr, authkey=authkey)
            protocol.enable_nodelay(conn)
            break
        except (ConnectionError, OSError) as e:
            err = e
            time.sleep(0.1 * (attempt + 1))
    if conn is None:
        raise ConnectionError(f"cannot reach cluster at {address}: {err}")
    os.environ.setdefault("RAY_TPU_AUTHKEY", authkey.hex())
    shm = ShmStore(shm_dir=tempfile.mkdtemp(prefix="ray_tpu_client_"))
    rt = ClientRuntime(conn, threading.Lock(), shm, max_inline)
    # The puller dials remote object servers (including the head's own —
    # large results stream back directly instead of relaying through the
    # control-plane connection).  Hand it THIS cluster's authkey
    # explicitly: the env setdefault above must not leave a stale key
    # from an earlier session on the pull path.
    rt._puller._authkey = authkey
    protocol.send(conn, ("client_ready", os.urandom(16).hex()))
    msg = protocol.recv(conn)
    assert msg[0] == "client_ack", msg
    rt.store_id = f"client-{os.urandom(4).hex()}"  # nothing shares it

    def handle(m):
        tag = m[0]
        if protocol.is_batch(m):
            # Conflation-sender frame from the head: unwrap in order.
            for sub in m[1]:
                handle(sub)
        elif tag == "obj":
            rt.deliver_reply(m[1], (m[2], m[3]))
        elif tag == "mgot":
            rt.deliver_reply(m[1], m[2])
        elif tag == "waited":
            rt.deliver_reply(m[1], m[2])
        elif tag == "reply":
            rt.deliver_reply(m[1], m[2])
        elif tag == "lease_grant":
            # Unsolicited bulk grant piggybacked on this client's
            # head-brokered submit burst; adopt off the reader thread
            # (adoption dials the granted workers).
            threading.Thread(
                target=rt.direct.adopt_grant,
                args=(m[1], m[2], m[3], m[4], m[5]),
                daemon=True, name="ray_tpu-client-lease").start()
        elif tag == "lease_revoke":
            rt.direct.revoke(m[1])

    def reader():
        while True:
            try:
                m = protocol.recv(conn)
            except (EOFError, OSError, TypeError):
                return
            handle(m)

    threading.Thread(target=reader, daemon=True,
                     name="ray_tpu-client-reader").start()

    def flusher():
        import time as _t

        while True:
            _t.sleep(0.25)
            try:
                rt.flush_decrefs()
                # Lease-plane counter deltas (leased_submits/spillbacks):
                # a client drives direct pushes too and its counters feed
                # the same head-side transfer_stats aggregation.
                rt.flush_xfer_stats()
            except Exception:
                return

    threading.Thread(target=flusher, daemon=True,
                     name="ray_tpu-client-flush").start()
    # Route ObjectRef callbacks through the GLOBAL accessor, not a
    # closure over this client: after disconnect + re-init, refs must
    # see the new runtime, not a closed connection.
    from ray_tpu._private import api_internal

    object_ref_mod._set_runtime_accessor(api_internal.get_runtime)
    return rt
