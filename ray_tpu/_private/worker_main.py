"""Worker process: execution loop + worker-side runtime.

Reference analog: the worker half of the core worker
(``src/ray/core_worker/core_worker.cc:2413`` RunTaskExecutionLoop +
``python/ray/_raylet.pyx:702`` execute_task +
``python/ray/_private/workers/default_worker.py``).

A worker is a plain Python process wired to the driver by one duplex pipe.
A reader thread demultiplexes incoming messages into (a) a task queue and
(b) response slots for in-flight requests this worker made (object gets,
nested submits).  Execution runs on the main thread; actors with
``max_concurrency > 1`` get a thread pool, and ``async def`` actor methods
run on a persistent asyncio loop (reference: async actors,
``python/ray/_private/async_compat.py``).

TPU ownership: if the driver granted this worker TPU chips, the spawn env
carries ``TPU_VISIBLE_CHIPS``/``JAX_PLATFORMS`` so that when user code
imports jax *inside this process* it sees exactly its chips — the TPU-native
equivalent of the reference's CUDA_VISIBLE_DEVICES plumbing
(``python/ray/_private/worker.py`` set_cuda_visible_devices).
"""

from __future__ import annotations

import asyncio
import itertools
import os
import queue
import sys
import threading
import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Optional

from ray_tpu._private import direct as direct_mod
from ray_tpu._private import object_transfer, protocol, recovery, \
    serialization
from ray_tpu._private.ids import ActorID, ObjectID, TaskID, new_task_id
from ray_tpu._private import object_ref as object_ref_mod
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.shm_store import ShmStore
from ray_tpu import exceptions as exc


class _WorkerRuntime:
    """Worker-side implementation of the runtime accessor used by ObjectRef
    and the public API when running inside a worker."""

    # Bounded caches: pooled workers and long-lived actors must not retain
    # every task's results forever.
    _CACHE_CAP = 64

    def __init__(self, conn, send_lock, shm: ShmStore, max_inline: int):
        self.conn = conn
        self.send_lock = send_lock  # lock-order: io-guard
        self.shm = shm
        self.max_inline = max_inline
        self.req_counter = itertools.count(1)
        self.pending: Dict[int, "queue.SimpleQueue"] = {}
        self.pending_lock = threading.Lock()  # lock-order: leaf
        # Dropped refs accumulate here and ride out as one ("decref_batch")
        # before the next outgoing message (or via the periodic flusher).
        # Append-only from ObjectRef.__del__: __del__ can fire from GC *during*
        # protocol.send's pickling, so it must never take send_lock itself.
        # RLock, not Lock: a GC pass triggered by an allocation made while
        # holding this lock can re-enter __del__ on the same thread.
        self._decref_buf: list = []
        self._decref_lock = threading.RLock()
        # Actor-handle drops, buffered for the same __del__ reasons.
        self._actor_decref_buf: list = []
        # Per-thread task context: concurrent actor threads must not
        # cross-contaminate (reference: per-thread context in worker.py).
        self._tls = threading.local()
        self.worker_id_hex = ""
        self.node_id_hex = ""
        self.job_id_hex = ""
        # Which host object store this worker can mmap directly; SHM
        # descriptors from other stores are shipped as parts via the driver.
        self.store_id = os.environ.get("RAY_TPU_STORE_ID", "")
        # Per-node spill directory: deterministic from the session so
        # every process on a node (and the head's restore path) agrees.
        self.spill_dir = os.environ.get(
            "RAY_TPU_SPILL_DIR_OVERRIDE",
            f"/tmp/ray_tpu_spill_{os.environ.get('RAY_TPU_SESSION', '')}")
        # Peer messaging over the direct-push listener: channel ->
        # handler(payload).  Host-tier collectives register here.
        self.direct_addr = None  # set by worker_entry
        self.peer_handlers: Dict[str, Any] = {}
        self._peer_handlers_lock = threading.Lock()
        self.assigned_resources: Dict[str, float] = {}
        self.tpu_chips: list = []
        # Objects fetched or created locally, cached: id -> value (LRU).
        from collections import OrderedDict, deque as _deque

        self._local_cache: "OrderedDict[ObjectID, Any]" = OrderedDict()
        self._segments = _deque(maxlen=self._CACHE_CAP)
        # Direct chunked pulls from remote object servers; the driver
        # brokers locations only (reference: ObjectManager::Pull through
        # the owner's directory, object_manager.h:206).
        self._puller = object_transfer.ObjectPuller(
            bytes.fromhex(os.environ.get("RAY_TPU_AUTHKEY", "")))
        # Write-direction twin: streams a put's payload straight into a
        # remote store's object server (capability-gated; the client
        # runtime's large puts to the head ride this).  Cheap to hold —
        # pools dial lazily on first push.
        self._pusher = object_transfer.ObjectPusher(
            bytes.fromhex(os.environ.get("RAY_TPU_AUTHKEY", "")))
        # store_id -> (addr, caps) for stores with a reachable object
        # server; misses are never cached (a recovering peer gets its
        # fast path back on the next pull).
        self._store_addrs: Dict[str, Any] = {}
        # Singleflight registry for remote-segment pulls: N concurrent
        # materializations of one segment (prefetcher + executing tasks)
        # share one pull; prefetched segments are retained here until
        # _load_args consumes them (reference: the raylet's pull-manager
        # dedup + dependency prefetch).
        self._pull_registry = object_transfer.PullRegistry()
        self._xfer_sent: Dict[str, int] = {}
        self._xfer_lock = threading.Lock()  # lock-order: leaf
        self.arg_prefetch_depth = int(
            os.environ.get("RAY_TPU_ARG_PREFETCH_DEPTH", "2") or 0)
        self.prefetcher = _ArgPrefetcher(self, self.arg_prefetch_depth)
        # Tasks currently inside _execute (heuristic for "a task is
        # running, queued work is BEHIND it" — the prefetch condition).
        # Lock-guarded updates: threaded actors (max_concurrency > 1)
        # run _execute concurrently, and a lost increment/decrement
        # would wedge the counter (and the prefetch heuristic) forever.
        self._executing = 0
        self._exec_lock = threading.Lock()
        # Completed-task results buffered between queue drains: back-to-
        # back short tasks ride to the driver as ONE result_batch message
        # (reference: batched reply streams; kills per-task head wakeups).
        self._result_buf: list = []
        self._result_lock = threading.Lock()
        # Task execution spans, shipped to the head in periodic batches
        # (reference: task events / tracing_helper.py span injection —
        # every task records submit->run->finish wall times; the head
        # aggregates them for `ray timeline`).
        self._span_buf: list = []
        # Set by worker_entry: True when no tasks are queued.  Results
        # buffer only while more work is queued behind them; a threaded
        # actor's lone reply must go out immediately, not on the 0.25s
        # timer.
        self.queue_empty = lambda: True
        # Caller-side ownership + direct push (reference:
        # direct_task_transport.cc:568 + reference_count.h:61 — this
        # worker OWNS its puts and its direct-submitted tasks' returns;
        # the head is only the lease scheduler for them).
        self._fn_payloads: Dict[str, bytes] = {}
        self.direct = direct_mod.DirectCaller(self)
        # Restartable-actor checkpointing: actor_id -> {"interval",
        # "last"} armed at create_actor when the head said the actor can
        # restart AND the class defines __ray_save__/__ray_restore__.
        self._actor_ck: Dict[bytes, dict] = {}
        self._actor_ck_lock = threading.Lock()
        # --- Head failover (reference: workers reconnecting across GCS
        # restart, gcs_failover_worker_reconnect_timeout).  On head-conn
        # EOF this process PARKS instead of exiting: outgoing head
        # messages buffer in _head_outbox (order preserved), in-flight
        # head requests stay registered in ``pending`` and are replayed
        # verbatim after the re-dial + re-register handshake.  All
        # _conn_down/_head_outbox mutation happens under send_lock.
        self._failover = os.environ.get("RAY_TPU_HEAD_FAILOVER",
                                        "1") == "1"
        self._reconnect_grace = float(os.environ.get(
            "RAY_TPU_HEAD_RECONNECT_GRACE_S", "20") or 0)
        self._conn_down = False
        self._head_outbox: list = []
        # lock-order: io-guard -- serializes re-dial+handshake+replay IO
        self._reconn_lock = threading.Lock()
        self._shutting_down = False
        self.head_reconnects = 0
        # Head-routed PLAIN task specs retained until a return is
        # materialized: their fate at a dead head is unknown, so the
        # re-register replay re-offers them (the head skips any it
        # already knows — at-least-once, the reference retry contract).
        # Bounded FIFO; actor calls are excluded (replay would break
        # per-channel ordering).
        from collections import OrderedDict as _OD

        self._inflight_head_specs: "_OD[bytes, dict]" = _OD()
        self._spec_lock = threading.Lock()  # lock-order: leaf
        # Hooks worker_entry fills in for the re-register payload.
        self.snapshot_tasks = lambda: []
        self.snapshot_actors = lambda: []
        self._executing_tasks: list = []  # (task, is_direct) pairs
        # --- Failure detection (gray failures): head-connection
        # watchdog state.  _last_head_recv/_last_head_send feed the
        # heartbeat floor (quiet link -> one heartbeat per
        # health_check_period_s) and the stalled-head detector: a
        # pending request older than net_stall_timeout_s with total
        # head silence first sends an hc_ping probe; continued silence
        # CLOSES the conn, turning the gray stall into the clean EOF
        # the PR-10 reconnect-and-replay machinery already survives.
        from ray_tpu._private.config import GLOBAL_CONFIG as _cfg

        self._fd_on = _cfg.failure_detection
        self._hc_period = _cfg.health_check_period_s
        self._net_stall_t = _cfg.net_stall_timeout_s
        self._last_head_recv = time.monotonic()
        self._last_head_send = time.monotonic()
        self._hc_probe_sent = 0.0

    # -- peer messaging (ring collectives etc.) ----------------------------
    def register_peer_handler(self, channel: str, fn):
        with self._peer_handlers_lock:
            self.peer_handlers[channel] = fn

    def unregister_peer_handler(self, channel: str):
        with self._peer_handlers_lock:
            self.peer_handlers.pop(channel, None)

    def dispatch_peer_msg(self, channel: str, payload):
        with self._peer_handlers_lock:
            fn = self.peer_handlers.get(channel)
        if fn is not None:
            fn(payload)

    # -- DirectCaller host adapter -----------------------------------------
    def head_request(self, msg_builder):
        return self._request(msg_builder)

    def head_send(self, msg):
        # Raw send: no decref-buffer flush (this is called from within the
        # decref-processing path itself; flushing would recurse into
        # send_lock).
        with self.send_lock:
            self._send_wire([msg])

    def _send_wire(self, msgs: list):
        """One batched write to the head — MUST be called under
        send_lock.  On a broken head conn with failover on, the messages
        PARK in _head_outbox (order preserved) for replay after the
        reconnect instead of raising: every caller on this path is
        fire-and-forget, and the reader thread drives the re-dial."""
        if not msgs:
            return
        if self._conn_down:
            self._head_outbox.extend(msgs)
            return
        try:
            protocol.send_batch(self.conn, msgs)
            self._last_head_send = time.monotonic()
        except Exception:
            if not self._failover or self._shutting_down:
                raise
            self._conn_down = True
            self._head_outbox.extend(msgs)

    def dial(self, addr):
        # Deadline-aware dial (connect timeout + SO_KEEPALIVE when
        # failure detection is on): direct channels to black-holed
        # peers fail in net_connect_timeout_s, not the kernel default.
        conn = protocol.dial(tuple(addr),
                             authkey=bytes.fromhex(
                                 os.environ.get("RAY_TPU_AUTHKEY", "")))
        if self._fd_on and self._net_stall_t > 0:
            # Send half only: pushes to a stalled executor error the
            # sender into the channel-death reroute; the reader stays
            # fully blocking (an idle channel is not a stalled one).
            protocol.set_send_deadline(conn, self._net_stall_t)
        return conn

    def get_payload(self, func_id: str) -> Optional[bytes]:
        return self._fn_payloads.get(func_id)

    def submit_via_head(self, spec: dict):
        # Rerouted specs may carry owned refs: make them head-visible
        # first (same-conn FIFO puts the export before the spec).
        self._export_for_head_path(spec)
        self._note_head_spec(spec)
        self._send(("submit", 0, spec))

    def submit_via_head_many(self, specs: list):
        """Bulk reroute: a starved lease round's REROUTE_CHUNK specs ship
        as ONE ("submit_batch", ...) message (exports first, same-conn
        FIFO) instead of a single-submit storm on the head."""
        for spec in specs:
            self._export_for_head_path(spec)
            self._note_head_spec(spec)
        self._send(("submit_batch", specs))

    @property
    def current_task_id(self) -> Optional[TaskID]:
        return getattr(self._tls, "task_id", None)

    @current_task_id.setter
    def current_task_id(self, v):
        self._tls.task_id = v

    @property
    def current_actor_id(self) -> Optional[ActorID]:
        return getattr(self._tls, "actor_id", None)

    @current_actor_id.setter
    def current_actor_id(self, v):
        self._tls.actor_id = v

    def _cache_put(self, oid: ObjectID, value: Any):
        self._local_cache[oid] = value
        self._local_cache.move_to_end(oid)
        while len(self._local_cache) > self._CACHE_CAP:
            self._local_cache.popitem(last=False)

    # -- plumbing ----------------------------------------------------------
    def _drain_decrefs(self) -> list:
        """Pop the buffered ref drops and apply the OWNED ones locally;
        returns the bins that belong to the head.  Runs outside send_lock
        (owned frees may message lease conns / the head)."""
        with self._decref_lock:
            buf, self._decref_buf = self._decref_buf, []
        if not buf:
            return buf
        head_bins = []
        for b in buf:
            if not self.direct.decref(ObjectID(b)):
                head_bins.append(b)
        return head_bins

    def _drain_put_buffer(self) -> list:
        """Buffered small-put messages that must precede any other
        outgoing message (put -> addref -> later decref ordering).
        Workers put owner-locally so the base buffer is always empty;
        ClientRuntime overrides with its coalescing buffer."""
        return []

    def _send(self, msg):
        head_bins = self._drain_decrefs()
        abuf = self._drain_actor_decrefs()
        # One ("batch", ...) pickle + one write for the whole burst —
        # buffered ref drops ride the same syscall as the payload.  The
        # put buffer is drained UNDER send_lock (drain is lock-append
        # only, no I/O): draining earlier would open a window where a
        # concurrent flusher's drained-but-unwritten puts let this
        # message overtake a put it references.  Puts precede decrefs —
        # a drop of a coalesced put's ref must never land first.
        with self.send_lock:
            msgs = self._drain_put_buffer()
            if head_bins:
                msgs.append(("decref_batch", head_bins))
            if abuf:
                msgs.append(("actor_decref_batch", abuf))
            msgs.append(msg)
            self._send_wire(msgs)

    def send_result(self, entry):
        """Buffer one completed task's (task_id, ok, returns, meta);
        batches only form while more tasks are queued behind this one."""
        with self._result_lock:
            self._result_buf.append(entry)
            n = len(self._result_buf)
        if n >= 16 or self.queue_empty():
            self.flush_results()

    def flush_results(self):
        with self._result_lock:
            if not self._result_buf:
                return
            buf, self._result_buf = self._result_buf, []
        # _send coalesces the results with any buffered decref_batch /
        # actor_decref_batch into ONE ("batch", ...) envelope: the reply
        # burst for N short tasks is one pickle + one write.
        if len(buf) == 1:
            e = buf[0]
            self._send(("result", e[0], e[1], e[2], e[3]))
        else:
            self._send(("result_batch", buf))

    def record_span(self, task_id_bin: bytes, name: str, start: float,
                    end: float, kind: str):
        with self._result_lock:
            self._span_buf.append((task_id_bin, name, start, end, kind))

    def flush_spans(self):
        with self._result_lock:
            if not self._span_buf:
                return
            buf, self._span_buf = self._span_buf, []
        self._send(("spans", buf))

    def flush_xfer_stats(self):
        """Ship data-plane counter deltas (pull dedup, prefetch hit/waste
        bytes) to the head, which aggregates them next to its
        brokered_parts/relayed_segments stats.  Rides the periodic
        flusher and the queue-drain flush; no-delta calls send nothing.

        The stats() snapshots run OUTSIDE _xfer_lock (each takes its own
        lock — the pull registry's leaf, the DirectCaller's big
        ownership lock — and holding the claim lock across them was an
        undeclared nesting edge, found by protocheck RTL505).  The claim
        itself stays atomic under _xfer_lock, and because every counter
        is cumulative, per-key MONOTONIC claiming makes racing flushers
        safe: a flusher that snapshotted earlier but claims later sees
        nothing new and ships nothing — never a duplicate or negative
        delta."""
        cur = self._pull_registry.stats()
        # Lease-plane counters ride the same delta stream (the head
        # aggregates leased_submits/spillbacks next to its own
        # lease_grants/head_brokered_submits).
        cur.update(self.direct.stats())
        # Failure-detection counters (stall_timeouts / net_retries /
        # hedged_fetches) — process-wide in the protocol deadline core,
        # aggregated by the head exactly like the rest.
        cur.update(protocol.net_stats())
        # Push-shuffle counters, only if a shuffle actually ran in this
        # process (lazy module lookup: importing the data layer from
        # every worker just to read zeros would be waste).
        shuffle_mod = sys.modules.get("ray_tpu.data.shuffle")
        if shuffle_mod is not None:
            cur.update(shuffle_mod.shuffle_stats())
        # Distributed-training counters, same lazy-lookup contract:
        # present only in workers hosting a pipeline stage actor or an
        # IMPALA learner (stage restores count here too — the restored
        # actor's fresh process imports the module in __ray_restore__).
        train_mod = sys.modules.get("ray_tpu.train.pipeline_actors")
        if train_mod is not None:
            cur.update(train_mod.train_stats())
        with self._xfer_lock:
            delta = {}
            for k, v in cur.items():
                sent = self._xfer_sent.get(k, 0)
                if v > sent:
                    delta[k] = v - sent
                    self._xfer_sent[k] = v
            if not delta:
                return
        self._send(("xfer_stats", delta))

    def flush_decrefs(self):
        head_bins = self._drain_decrefs()
        abuf = self._drain_actor_decrefs()
        with self.send_lock:
            # Put drain under send_lock (see _send); puts precede their
            # refs' decrefs in the envelope.
            msgs = self._drain_put_buffer()
            if not msgs and not head_bins and not abuf:
                return
            if head_bins:
                msgs.append(("decref_batch", head_bins))
            if abuf:
                msgs.append(("actor_decref_batch", abuf))
            self._send_wire(msgs)

    # Actor-handle refcounts (reference: actor out-of-scope GC) — the head
    # keeps the authoritative count; addref is sent inline (pickle-time,
    # safe context), decref buffers (fires from __del__).
    def actor_handle_addref(self, actor_id: bytes):
        self._send(("actor_addref", actor_id))

    def actor_handle_serialized(self, actor_id: bytes, token: bytes):
        self._send(("actor_token_new", actor_id, token))

    def actor_handle_deserialized(self, actor_id: bytes, token: bytes):
        self._send(("actor_token_used", actor_id, token))

    def actor_handle_decref(self, actor_id: bytes):
        try:
            with self._decref_lock:
                self._actor_decref_buf.append(actor_id)
        except Exception:
            pass  # shutting down

    def _drain_actor_decrefs(self) -> list:
        """Pop buffered actor-handle drops, HOLDING any whose direct
        channel still has queued/inflight calls — the head cannot see
        direct pushes, so a decref racing ahead of this worker's own
        in-flight calls could zero the count and GC-kill the actor
        mid-call."""
        with self._decref_lock:
            abuf, self._actor_decref_buf = self._actor_decref_buf, []
        if not abuf:
            return abuf
        out, keep = [], []
        for aid in abuf:
            (keep if self.direct.actor_channel_busy(aid)
             else out).append(aid)
        if keep:
            with self._decref_lock:
                self._actor_decref_buf.extend(keep)
        return out

    def _request(self, msg_builder):
        req_id = next(self.req_counter)
        slot: "queue.SimpleQueue" = queue.SimpleQueue()
        msg = msg_builder(req_id)
        with self.pending_lock:
            # The built message is retained alongside the slot: a head
            # restart replays every still-pending request verbatim to
            # the new incarnation (park-and-replay).  The timestamp
            # feeds the head-connection watchdog (a request aging past
            # net_stall_timeout_s under total head silence is the
            # gray-failure signal).
            self.pending[req_id] = (slot, msg, time.monotonic())
        self._send(msg)
        reply = slot.get()
        with self.pending_lock:
            self.pending.pop(req_id, None)
        return reply

    def deliver_reply(self, req_id, payload):
        with self.pending_lock:
            ent = self.pending.get(req_id)
        if ent is not None:
            ent[0].put(payload)

    # -- failure detection: heartbeat floor + head-conn watchdog -----------
    def note_head_recv(self):
        """Reader-thread hook: any head message is liveness."""
        self._last_head_recv = time.monotonic()
        self._hc_probe_sent = 0.0

    def heartbeat_and_watchdog(self):
        """Periodic-flusher hook (failure detection; no-op with the
        switch off).  Two jobs: (a) the heartbeat FLOOR — a link with
        no other outgoing traffic for health_check_period_s sends one
        ("heartbeat", ...) so head-side silence is a signal; (b) the
        stalled-head WATCHDOG — a pending request older than
        net_stall_timeout_s under total head silence probes with
        hc_ping, and a probe unanswered for another full window closes
        the conn, converting the gray stall into the clean EOF the
        reconnect-and-replay machinery (PR 10) already survives."""
        if not self._fd_on or self._shutting_down or self._conn_down:
            return
        now = time.monotonic()
        if self._hc_period > 0 \
                and now - self._last_head_send > self._hc_period:
            try:
                self._send(("heartbeat", self.worker_id_hex))
            except Exception:
                return
        stall_t = self._net_stall_t
        if stall_t <= 0 or not self._failover:
            # Without failover the only answer to a stalled head would
            # be this worker's exit — strictly worse than waiting.
            return
        with self.pending_lock:
            oldest = min((ent[2] for ent in self.pending.values()),
                         default=None)
        if oldest is None:
            self._hc_probe_sent = 0.0
            return
        if now - oldest < stall_t or now - self._last_head_recv < stall_t:
            return
        if not self._hc_probe_sent:
            # First strike: probe.  A busy-but-alive head answers with
            # a generic reply and the reader resets the clock.
            self._hc_probe_sent = now
            try:
                self._send(("hc_ping", next(self.req_counter)))
            except Exception:
                pass
            return
        if now - self._hc_probe_sent > stall_t:
            # Probe unanswered for a full window: the conn is stalled,
            # not busy.  Shutdown (not just close — the reader is by
            # precondition parked inside a blocked recv, which close()
            # cannot wake) so its recv EOFs into _reconnect_head, which
            # re-dials, re-registers, and replays every parked request.
            protocol.note_net_event("stall_timeouts")
            self._hc_probe_sent = 0.0
            try:
                protocol.shutdown_conn(self.conn)
                self.conn.close()
            except Exception:
                pass

    # -- head failover: park, re-dial, re-register, replay -----------------
    def _redial(self):
        """One dial attempt to the head's listener; raises on refusal."""
        addr = protocol.parse_address(os.environ["RAY_TPU_ADDRESS"])
        return protocol.dial(addr, authkey=bytes.fromhex(
            os.environ.get("RAY_TPU_AUTHKEY", "")))

    def _re_handshake(self, conn):
        """Re-register this surviving process with the (restarted) head.
        True = re-admitted; False = permanently refused (nack — the head
        did not restore our cluster); None = transient, retry."""
        protocol.send(conn, ("reregister", self._reregister_info()))
        msg = protocol.recv(conn)  # the ack is first on this conn (FIFO)
        if msg[0] == "reregister_ack":
            return True
        if msg[0] == "reregister_nack":
            return False
        return None

    def _reregister_info(self) -> dict:
        """Everything the restarted head needs to reconcile us back in:
        identity, the actor incarnation we host, our queued/running
        head-dispatched tasks, re-advertised delegated objects, and the
        peer leases we hold."""
        hosted = list(self.snapshot_actors())
        return {
            "worker_id": self.worker_id_hex,
            "node_id": self.node_id_hex,
            "store_id": self.store_id,
            "env_key": os.environ.get("RAY_TPU_ENV_KEY", ""),
            "pid": os.getpid(),
            "direct_addr": self.direct_addr,
            "tpu_chips": list(self.tpu_chips),
            "actor_id": (hosted[0] if hosted else None),
            "resources": dict(self.assigned_resources),
            "tasks": self.snapshot_tasks(),
            "objects": self.direct.reregister_exports(),
            "held_leases": self.direct.held_lease_ids(),
        }

    def _reconnect_head(self) -> bool:
        """Reader-thread entry on head-conn EOF: re-dial with backoff
        for the grace window, re-register, then replay pending requests
        and the parked outbox.  False = give up (caller exits, the
        pre-failover behavior)."""
        if not self._failover or self._shutting_down:
            return False
        with self._reconn_lock:
            with self.send_lock:  # noqa: RTL505 -- the reconnect serializer is strictly OUTER to send_lock; no send path takes _reconn_lock
                self._conn_down = True
            deadline = time.monotonic() + self._reconnect_grace
            delay = 0.05
            while time.monotonic() < deadline \
                    and not self._shutting_down:
                conn = None
                try:
                    conn = self._redial()
                    ok = self._re_handshake(conn)
                except Exception:
                    ok = None
                if ok is False:
                    try:
                        conn.close()
                    except Exception:
                        pass
                    return False
                if ok:
                    replay_ok = False
                    with self.send_lock:  # noqa: RTL505 -- reconnect serializer OUTER to send_lock (see above); the replay must exclude concurrent senders
                        self.conn = conn
                        outbox, self._head_outbox = self._head_outbox, []
                        # Requests PARKED while down already sit in the
                        # outbox (in order); replay only the ones that
                        # made it onto the dead conn before the failure,
                        # so nothing is sent twice.
                        parked = {id(m) for m in outbox}
                        with self.pending_lock:
                            replay = [ent[1] for ent in
                                      self.pending.values()
                                      if ent[1] is not None
                                      and id(ent[1]) not in parked]
                        try:
                            # Pending requests were on the wire before
                            # the parked messages existed: replay them
                            # first, then the outbox, in one batch.
                            protocol.send_batch(conn, replay + outbox)
                            self._conn_down = False
                            self.head_reconnects += 1
                            replay_ok = True
                        except Exception:
                            self._head_outbox = outbox
                    if replay_ok:
                        self._after_reconnect()
                        return True
                    # Replay failed (head died again mid-replay): back
                    # off OUTSIDE send_lock so task threads keep parking
                    # into the outbox instead of blocking on the lock.
                    try:
                        conn.close()
                    except Exception:
                        pass
                    time.sleep(delay)
                    delay = min(1.0, delay * 1.7)
                    continue
                if conn is not None:
                    try:
                        conn.close()
                    except Exception:
                        pass
                time.sleep(delay)
                delay = min(1.0, delay * 1.7)
            return False

    def _after_reconnect(self):
        """Post-replay reconciliation: re-offer retained head-routed
        specs whose returns we never materialized — the head runs the
        ones it doesn't already know (at-least-once)."""
        with self._spec_lock:
            specs = list(self._inflight_head_specs.values())
        if specs:
            self._send(("resubmit_batch", specs))

    _HEAD_SPEC_CAP = 512

    def _note_head_spec(self, spec: dict):
        """Retain a head-routed PLAIN spec for failover replay (dropped
        once a return materializes, or FIFO-evicted past the cap)."""
        if not self._failover or "actor_id" in spec:
            return
        with self._spec_lock:
            self._inflight_head_specs[spec["task_id"][:12]] = spec
            while len(self._inflight_head_specs) > self._HEAD_SPEC_CAP:
                self._inflight_head_specs.popitem(last=False)

    def _prune_head_specs(self, oid_bins):
        if not self._inflight_head_specs:
            return
        with self._spec_lock:
            for b in oid_bins:
                self._inflight_head_specs.pop(b[:12], None)

    # -- descriptor handling ----------------------------------------------
    def materialize(self, descr) -> Any:
        try:
            return self._materialize_tracked(descr)
        except exc.ObjectLostError as e:
            # Lost segment: if WE own the object and its lineage
            # survives, re-execute the producer and consume the re-homed
            # result (reference: ObjectRecoveryManager — recovery runs
            # at the owner; head-owned objects already recovered inside
            # the getparts relay, so reaching here means the head
            # refused).
            if not e.reconstructable:
                raise
            oid = self._owned_oid_of(descr)
            if oid is None or not self.direct.reconstruct(oid):
                raise
            try:
                descr2, _st = self.direct.descr_of(oid)
            except Exception:
                raise e from None
            if descr2 is None or descr2[0] == protocol.ERROR:
                raise
            return self._materialize_tracked(descr2)

    def _owned_oid_of(self, descr) -> Optional[ObjectID]:
        """The owned ObjectID a SHM/SPILLED descriptor names (segment
        names embed the oid hex), or None when it isn't ours to
        recover."""
        if descr is None or descr[0] not in (protocol.SHM,
                                             protocol.SPILLED):
            return None
        oid_hex = recovery.seg_oid_hex(descr[1])
        if oid_hex is None:
            return None
        oid = ObjectID(bytes.fromhex(oid_hex))
        if self.direct.status_of(oid) in (None, direct_mod.DELEGATED):
            return None
        return oid

    def _materialize_tracked(self, descr) -> Any:
        prev = getattr(self._tls, "reg_load", None)
        self._tls.reg_load = []
        try:
            return self._materialize_inner(descr)
        finally:
            coll = getattr(self._tls, "reg_load", None)
            self._tls.reg_load = prev
            if coll:
                if prev is not None:
                    prev.extend(coll)  # nested load: outermost applies
                else:
                    adds = [oid for oid, d in coll if d > 0]
                    drops = [oid for oid, d in coll if d <= 0]
                    foreign = self.direct.addref_batch(adds)
                    if foreign:
                        # Rides the conn BEFORE any buffered drop of the
                        # same oid (per-conn FIFO).
                        self._send(("addref_batch", foreign))
                    for oid in drops:
                        if not self.direct.decref(oid):
                            with self._decref_lock:
                                self._decref_buf.append(oid.binary())

    def _materialize_inner(self, descr) -> Any:
        kind = descr[0]
        if kind == protocol.INLINE:
            return serialization.loads_inline(descr[1])
        if kind == protocol.PARTS:
            return serialization.loads(descr[1], descr[2])
        if kind in (protocol.SHM, protocol.SPILLED):
            if len(descr) > 3 and descr[3] != self.store_id:
                # Segment homed in another node's store: pull it directly
                # from that node's object server in 1 MB chunks; the head
                # relays only if the home store has no server (in-process
                # test nodes) or the pull fails.
                if kind == protocol.SHM:
                    value = self._direct_pull(descr)
                    if value is not _PULL_MISS:
                        return value
                ok, reply = self._request(
                    lambda rid: ("getparts", rid, tuple(descr)))
                if not ok:
                    raise self.materialize_error(reply)
                return self.materialize(reply)
            try:
                if kind == protocol.SPILLED:
                    # Same-host spill file: restore by direct read.
                    seg = self.shm.attach_path(descr[1])
                else:
                    seg = self.shm.attach(descr[1])
            except FileNotFoundError:
                # Raced with the owner's spiller (segment moved to disk) or
                # a restore: the owner always knows the current location.
                ok, reply = self._request(
                    lambda rid: ("getparts", rid, tuple(descr)))
                if not ok:
                    raise self.materialize_error(reply)
                return self.materialize(reply)
            self._segments.append(seg)
            return seg.deserialize()
        if kind == protocol.ERROR:
            raise serialization.loads_inline(descr[1])
        raise ValueError(f"bad descriptor {descr!r}")

    def _direct_pull(self, descr):
        seg = self._pull_remote_segment(descr)
        if seg is None:
            return _PULL_MISS
        try:
            meta, bufs = seg.raw_parts()
            return serialization.loads(meta, bufs)
        except Exception:
            # Corrupt/truncated receive: the brokered getparts path
            # re-fetches through the owner (and drives recovery).
            return _PULL_MISS

    def _pull_remote_segment(self, descr, prefetch: bool = False):
        """Singleflight pull of a remote SHM segment into a local read
        Segment (one copy, socket -> mapping).  Concurrent callers for
        the same segment share the leader's pull; a retained prefetched
        segment is consumed directly.  Returns None on any failure — the
        caller falls back to the brokered getparts path (which also
        drives recovery), and a failed leader wakes every waiter into
        that same fallback."""
        key = (descr[3], descr[1])
        reg = self._pull_registry
        for _attempt in range(2):
            ent, leader = reg.begin(key, prefetch=prefetch)
            if leader:
                seg = None
                try:
                    seg = self._pull_segment_once(descr)
                finally:
                    # Publish under all circumstances (incl. an
                    # unexpected raise): waiters must never hang on a
                    # dead leader.
                    reg.finish(key, ent, seg,
                               retain=prefetch and seg is not None)
                return seg
            if prefetch:
                return None  # already in flight or retained: nothing to do
            if not ent.event.is_set():
                ent.wait()
            seg = reg.take(key, ent)
            if seg is not None or ent.failed:
                # A failed leader means the pull path itself is broken:
                # fall back (getparts relay) rather than retry in place.
                return seg
            # Retention evicted the segment between begin() and take():
            # loop once more and re-pull directly as a fresh leader.
        return None

    def resolve_store_addr(self, store):
        """(addr, caps) of a peer store's object server, cached, or None
        when the peer has no server right now.  Shared by the pull path
        and the shuffle map tasks' partition pushes — both need the same
        never-cache-a-miss behavior so a recovered peer gets its fast
        path back."""
        ent = self._store_addrs.get(store)
        if ent is not None:
            return ent
        reply = self._request(
            lambda rid: ("store_addr", rid, store))
        # (addr, caps) from this release's head; a bare addr (no
        # advertised verbs) from an older one.
        if isinstance(reply, tuple):
            addr, caps = reply[0], tuple(reply[1] or ())
        else:
            addr, caps = reply, ()
        if not addr:
            # No server right now (agent dead or mid-restart): do
            # NOT cache the miss — the next pull re-asks, so a
            # recovered peer gets its fast path back.  The relay
            # fallback this returns into is far costlier than the
            # one extra location lookup.
            return None
        ent = self._store_addrs[store] = (addr, caps)
        return ent

    def forget_store_addr(self, store):
        """Drop the cached server address after a failed push/pull so a
        restarted peer re-resolves."""
        self._store_addrs.pop(store, None)

    def _pull_segment_once(self, descr):
        """One actual pull attempt (address resolution + chunk stream);
        returns None instead of raising so singleflight failure wakes
        waiters into their own fallback."""
        store = descr[3]
        ent = self.resolve_store_addr(store)
        if ent is None:
            return None
        addr, caps = ent
        try:
            # One-copy receive: chunks land straight in a local shm
            # mapping; deserialization builds zero-copy views over it
            # (the value's arrays keep the mapping alive).
            return object_transfer.pull_to_segment(
                self._puller, self.shm, store, addr, descr[1], caps=caps)
        except Exception as e:  # noqa: BLE001 -- every failure has the same fallback
            # Agent gone or segment moved: the owner knows the truth —
            # fall back to the brokered path (which also drives recovery).
            # Forget the cached address so a restarted peer re-resolves.
            # A STALLED pull (deadline tripped, transport retries
            # exhausted) lands here too — that fallback is the hedge.
            if protocol.is_stall(e) or (
                    isinstance(e, exc.ObjectLostError)
                    and getattr(e, "phase", None) == "stalled"):
                protocol.note_net_event("hedged_fetches")
            self._store_addrs.pop(store, None)
            return None

    def serialize_value(self, value: Any, object_id: ObjectID):
        """Value -> descriptor, choosing inline vs shm by size (one
        serialization pass; shm buffers memcpy'd once, into the segment).
        Store-full falls back to per-node spilling then direct-to-disk
        (reference: LocalObjectManager spilling + plasma's
        CreateRequestQueue fallback, local_object_manager.h:41)."""
        res = serialization.dumps_adaptive(value, self.max_inline)
        if res[0] == "inline":
            return (protocol.INLINE, res[1])
        try:
            name, size = self.shm.create_from_parts(object_id, res[1],
                                                    res[2])
        except MemoryError:
            need = sum(len(b) for b in res[2]) + len(res[1]) + 65536
            self.direct.spill_owned(need, self.spill_dir)
            try:
                name, size = self.shm.create_from_parts(object_id, res[1],
                                                        res[2])
            except MemoryError:
                path, size = self.shm.create_spilled(
                    object_id, res[1], res[2], self.spill_dir)
                return (protocol.SPILLED, path, size, self.store_id)
        return (protocol.SHM, name, size, self.store_id)

    # -- runtime accessor API (mirrors driver Runtime) ---------------------
    def add_local_reference(self, object_id: ObjectID):
        coll = getattr(self._tls, "reg_load", None)
        if coll is not None:
            # Deserialization in progress: batch-registered at load end —
            # one ownership-lock pass for owned refs, ONE head message for
            # foreign ones (a 10k-ref container otherwise sends 10k
            # addrefs).
            coll.append((object_id, 1))
            return
        if self.direct.addref(object_id):
            return
        self._send(("addref", object_id.binary()))

    def remove_local_reference(self, object_id: ObjectID):
        # Mid-deserialization drop on the loading thread: defer with the
        # batched increments (a drop drained by a nested getparts send
        # could otherwise reach the owner before its matching deferred
        # +1 and transit zero).
        coll = getattr(self._tls, "reg_load", None)
        if coll is not None:
            coll.append((object_id, -1))
            return
        # Buffered, not sent: this runs from ObjectRef.__del__, which the GC
        # may invoke mid-pickle inside _send — taking send_lock here would
        # self-deadlock.  The batch is flushed before the next outgoing
        # message and by the periodic flusher thread.
        try:
            with self._decref_lock:
                self._decref_buf.append(object_id.binary())
        except Exception:
            pass  # shutting down

    def on_ref_serialized(self, object_id: ObjectID):
        # Collect-only, like the driver: the carrying submit/put message
        # lists these ids and the driver pins them on receipt.  Message FIFO
        # per-connection guarantees the pin lands before this worker's own
        # decref for the same ref can.
        collector = getattr(self._tls, "ref_collector", None)
        if collector is not None:
            collector.append(object_id.binary())

    def begin_ref_collection(self):
        self._tls.ref_collector = []

    def end_ref_collection(self) -> list:
        out = getattr(self._tls, "ref_collector", None) or []
        self._tls.ref_collector = None
        return out

    def _notify_blocked(self) -> bool:
        """Whether blocking in get/wait should send the head the
        blocked/unblocked envelope.  The envelope lets the head release
        this worker's lease slot and — crucially for plain task workers
        — excludes it from pipelined dispatch while it waits
        (``w.blocked`` in the pipelinable-worker scan), so PLAIN tasks
        always send it regardless of resources: suppressing it for a
        0-CPU task could queue its own dependency behind its blocked
        get.  ACTOR workers are never pipelined-to (``w.actor_id``
        exclusion) and a client runtime holds no lease at all, so for a
        zero-resource actor (num_cpus=0 normalizes to {"CPU": 0.0} —
        the serve RequestProxy shape, blocking once per routed request)
        and for clients the pair is two head messages per get of pure
        hot-path chatter and is skipped.  Empty/unknown resources keep
        the envelope."""
        if getattr(self, "is_client", False):
            return False
        if self.current_actor_id is None:
            return True
        res = self.assigned_resources
        return not res or any(res.values())

    def get_objects(self, refs, timeout=None):
        """Batched get: owned refs resolve against the local ownership
        table (zero head traffic — the caller IS the metadata authority,
        reference_count.h:61); the rest go to the head in ONE round trip
        (CoreWorker::Get, core_worker.cc:1250)."""
        values = [None] * len(refs)
        owned = []
        missing = []
        for i, ref in enumerate(refs):
            oid = ref.id()
            if oid in self._local_cache:
                values[i] = self._local_cache[oid]
            elif self.direct.status_of(oid) not in (None,
                                                    direct_mod.DELEGATED):
                owned.append((i, oid))
            else:
                missing.append((i, oid))
        if not owned and not missing:
            return values
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        tid = self.current_task_id
        # Suppression applies only to purely-OWNED gets (the proxy hot
        # path): before any head fetch — initial misses OR refs that
        # become delegated mid-get — _upgrade_notify below sends the
        # envelope, because the head may need this worker's blocked
        # credit (lend slots) to make the dependency runnable at all on
        # a saturated node.  Clients stay suppressed throughout — they
        # register outside the node worker tables, so their flag feeds
        # nothing.
        notify = self._notify_blocked()
        if notify:
            self._send(("blocked", tid.binary() if tid else b""))

        def _upgrade_notify():
            nonlocal notify
            if not notify and not getattr(self, "is_client", False):
                notify = True
                self._send(("blocked", tid.binary() if tid else b""))
        try:
            if owned:
                done = self.direct.wait_owned([o for _, o in owned],
                                              timeout)
                if not done:
                    raise exc.GetTimeoutError(
                        f"Timed out getting owned objects after {timeout}s")
                for i, oid in owned:
                    if self.direct.status_of(oid) in (
                            None, direct_mod.DELEGATED):
                        # Delegated to the head mid-get (lease starvation
                        # reroute): the head is the authority now.
                        missing.append((i, oid))
                        continue
                    descr, st = self.direct.descr_of(oid)
                    if descr[0] == protocol.ERROR:
                        descr, st = self._maybe_recover_owned(oid, descr,
                                                              st)
                    if descr[0] == protocol.ERROR:
                        raise self.materialize_error(descr)
                    values[i] = self.materialize(descr)
                    if descr[0] == protocol.SHM:
                        st.attached = True
                    self._cache_put(oid, values[i])
            if missing:
                _upgrade_notify()
                left = (None if deadline is None
                        else max(0.0, deadline - _time.monotonic()))
                reply = self._request(
                    lambda rid: ("mget", rid,
                                 [oid.binary() for _, oid in missing],
                                 left))
                self._prune_head_specs(
                    [oid.binary() for ((_i, oid), (ok, _d))
                     in zip(missing, reply) if ok])
                for (i, _oid), (ok, descr) in zip(missing, reply):
                    if not ok:
                        raise self.materialize_error(descr)
                    values[i] = self.materialize(descr)
        finally:
            if notify:
                self._send(("unblocked", tid.binary() if tid else b""))
        return values

    def _maybe_recover_owned(self, oid: ObjectID, descr, st):
        """An ERRORED owned object whose failure wraps a reconstructable
        loss (the producer couldn't fetch a lost argument, or its worker
        died holding the only copy): rebuild through this owner's
        lineage and return the refreshed (descr, state); on refusal the
        original error stands."""
        if self.direct.lineage is None:
            return descr, st
        if self.direct._lost_object_hex(descr) is None:
            return descr, st
        if not self.direct.reconstruct(oid):
            return descr, st
        try:
            return self.direct.descr_of(oid)
        except Exception:
            return descr, st

    def materialize_error(self, descr):
        try:
            return serialization.loads_inline(descr[1])
        except Exception:
            return exc.RayTpuError("unknown error from driver")

    def publish_event(self, topic: str, payload: bytes):
        """Fire-and-forget pubsub to the driver (train session streaming)."""
        self._send(("event", topic, payload))

    def put_object(self, value) -> ObjectRef:
        """Owner-local put: the value lands in this node's store and the
        descriptor stays HERE — no head message at all (reference: plasma
        put + owner-resident metadata; the v1 design registered every put
        at the head, which serialized multi-client put bandwidth through
        one mailbox)."""
        # Apply buffered ref drops first: a put loop's previous segment is
        # freed (and its pages pooled) BEFORE the next allocation, keeping
        # the loop at memcpy speed.  Head-owned drops go back in the
        # buffer — they ride out with the next head message as usual.
        head_bins = self._drain_decrefs()
        if head_bins:
            with self._decref_lock:
                self._decref_buf[:0] = head_bins
        oid = ObjectID.for_put()
        self.begin_ref_collection()
        try:
            descr = self.serialize_value(value, oid)
        finally:
            nested = self.end_ref_collection()
        nested_local, nested_head = [], []
        for b in nested:
            if self.direct.status_of(ObjectID(b)) not in (
                    None, direct_mod.DELEGATED):
                nested_local.append(b)
            else:
                nested_head.append(b)
        if nested_head:
            # Foreign refs nested in the value: hold +1 at the head for
            # this entry's lifetime (pairs with the decref on local free).
            self._send(("addref_batch", nested_head))
        self.direct.register_put(oid, descr, nested_local, nested_head)
        self._cache_put(oid, value)
        return ObjectRef(oid, _register=False)

    def _export_for_head_path(self, spec: dict):
        """A spec routed through the head may carry owned refs (args or
        nested): make them head-visible first (ordering: the export rides
        the same FIFO conn, so it lands before the spec)."""
        bins = set()
        for a in spec.get("args", ()):
            if a[0] == "ref":
                bins.add(a[1])
        for v in (spec.get("kwargs") or {}).values():
            if v[0] == "ref":
                bins.add(v[1])
        bins.update(spec.get("nested_refs", ()))
        owned = [b for b in bins
                 if self.direct.status_of(ObjectID(b))
                 not in (None, direct_mod.DELEGATED)]
        if owned:
            self.direct.export_refs(owned)

    def submit_task(self, spec: dict) -> list:
        """Task submission from inside a worker.  Direct-eligible specs
        are pushed straight to leased peer workers with caller-owned
        returns (direct_task_transport.cc:568); the rest go through the
        head scheduler fire-and-forget (per-conn FIFO makes later uses of
        the returned refs safe)."""
        tid = TaskID(spec["task_id"])
        if spec.get("func_payload") is not None:
            self._fn_payloads.setdefault(spec["func_id"],
                                         spec["func_payload"])
        if "actor_id" in spec:
            states = self.direct.submit_actor(spec)
            if states is not None:
                return [ObjectRef(tid.object_id(i), _register=False)
                        for i in range(spec["num_returns"])]
            self._export_for_head_path(spec)
            self._send(("submit", 0, spec))
            return [ObjectRef(tid.object_id(i), _register=False)
                    for i in range(spec["num_returns"])]
        if self.direct.eligible(spec):
            owned_nested = [
                b for b in spec.get("nested_refs", ())
                if self.direct.status_of(ObjectID(b))
                not in (None, direct_mod.DELEGATED)]
            if owned_nested:
                # Containers in args embed these refs; the executor
                # resolves them through the head, so export first.
                self.direct.export_refs(owned_nested)
            self.direct.submit(spec)
            return [ObjectRef(tid.object_id(i), _register=False)
                    for i in range(spec["num_returns"])]
        self._export_for_head_path(spec)
        self._note_head_spec(spec)
        self._send(("submit", 0, spec))
        # _register=False: the driver counts this worker's reference when it
        # receives the spec (see Runtime.submit_task_from_worker).
        return [ObjectRef(tid.object_id(i), _register=False)
                for i in range(spec["num_returns"])]

    def submit_tasks(self, specs: list) -> list:
        """Bulk fan-out submission from a worker/client: direct-eligible
        specs register in the ownership table under one lock pass and
        pump once per scheduling class (DirectCaller.submit_many);
        head-bound plain specs ship as ONE ("submit_batch", ...) message
        instead of n ("submit", ...) sends.  Actor specs keep the
        per-channel FIFO path (ordering).  Returns one ref list per
        spec, same as n submit_task calls."""
        out = [None] * len(specs)
        direct_specs = []
        head_specs = []
        for i, spec in enumerate(specs):
            if "actor_id" in spec:
                out[i] = self.submit_task(spec)
                continue
            tid = TaskID(spec["task_id"])
            if spec.get("func_payload") is not None:
                self._fn_payloads.setdefault(spec["func_id"],
                                             spec["func_payload"])
            out[i] = [ObjectRef(tid.object_id(j), _register=False)
                      for j in range(spec["num_returns"])]
            if self.direct.eligible(spec):
                direct_specs.append(spec)
            else:
                head_specs.append(spec)
        if direct_specs:
            owned_nested = [
                b for spec in direct_specs
                for b in spec.get("nested_refs", ())
                if self.direct.status_of(ObjectID(b))
                not in (None, direct_mod.DELEGATED)]
            if owned_nested:
                self.direct.export_refs(owned_nested)
            self.direct.submit_many(direct_specs)
        if head_specs:
            for spec in head_specs:
                self._export_for_head_path(spec)
                self._note_head_spec(spec)
            self._send(("submit_batch", head_specs))
        return out

    def wait_objects(self, refs, num_returns, timeout, fetch_local):
        # Same blocked/unblocked envelope as get_objects: the lease's CPU
        # slot is released while this worker sits in ray.wait, so tasks
        # stolen off its pipeline (or anyone else) can actually run.
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        tid = self.current_task_id
        # As in get_objects: suppression only for purely-owned waits —
        # foreign (head-routed) refs, whether present up front or
        # appearing mid-wait via delegation, upgrade to the envelope
        # before any head RPC (the blocked credit feeds the head's
        # lend/steal paths).
        notify = self._notify_blocked()
        if notify:
            self._send(("blocked", tid.binary() if tid else b""))

        def _upgrade_notify():
            nonlocal notify
            if not notify and not getattr(self, "is_client", False):
                notify = True
                self._send(("blocked", tid.binary() if tid else b""))

        try:
            while True:
                left = (None if deadline is None
                        else max(0.0, deadline - _time.monotonic()))
                owned, foreign = self.direct.split_refs(refs)
                if foreign:
                    _upgrade_notify()
                if not foreign:
                    ready, delegated = self.direct.wait_owned_n(
                        [r.id() for r in owned], num_returns, left)
                    ready_bin = set(ready)
                    if delegated and len(ready_bin) < num_returns and (
                            left is None or left > 0):
                        continue  # re-split: some refs moved to the head
                    break
                if not owned:
                    ready_bin = set(self._request(
                        lambda rid: ("wait", rid,
                                     [r.id().binary() for r in refs],
                                     num_returns, left)))
                    break
                # Mixed ownership: probe the head (timeout=0 answers
                # immediately, registers nothing) and pace on the local
                # condition variable — no per-poll head state.
                ready, _delegated = self.direct.wait_owned_n(
                    [r.id() for r in owned], num_returns, 0)
                ready_bin = set(ready)
                if len(ready_bin) < num_returns:
                    ready_bin.update(self._request(
                        lambda rid: ("wait", rid,
                                     [r.id().binary() for r in foreign],
                                     num_returns - len(ready_bin), 0)))
                if len(ready_bin) >= num_returns:
                    break
                if deadline is not None and \
                        _time.monotonic() >= deadline:
                    break
                with self.direct.cv:
                    self.direct.cv.wait(0.05)
        finally:
            if notify:
                self._send(("unblocked", tid.binary() if tid else b""))
        ready = [r for r in refs if r.id().binary() in ready_bin]
        not_ready = [r for r in refs if r.id().binary() not in ready_bin]
        return ready, not_ready

    def object_future(self, object_id):
        raise RuntimeError("ObjectRef.future() is driver-only")

    def is_worker(self):
        return True

    # -- restartable-actor checkpoints -------------------------------------
    def arm_actor_checkpoint(self, actor_id: bytes, actor,
                             interval) -> None:
        """Arm periodic __ray_save__ checkpointing for one actor (only
        when the head sent an interval — recovery on + max_restarts != 0
        — and the class actually defines the hook)."""
        if interval is None or not hasattr(actor, "__ray_save__"):
            return
        with self._actor_ck_lock:
            self._actor_ck[actor_id] = {"interval": float(interval),
                                        "last": 0.0}

    def maybe_checkpoint_actor(self, actor_id: bytes, actor) -> None:
        """After a successful method call: serialize __ray_save__ state
        through the store (spill-aware — serialize_value's store-full
        path) and ship the DESCRIPTOR to the head, which retains it for
        the next restart's __ray_restore__.  Throttled by
        actor_checkpoint_interval_s; a failing checkpoint never fails
        the method call that triggered it."""
        ck = self._actor_ck.get(actor_id)
        if ck is None:
            return
        import time as _time

        now = _time.monotonic()
        with self._actor_ck_lock:
            if ck["last"] and now - ck["last"] < ck["interval"]:
                return
            ck["last"] = now
        try:
            state = actor.__ray_save__()
            oid = ObjectID.for_put()
            descr = self.serialize_value(state, oid)
            self._send(("actor_checkpoint", actor_id, descr))
        except Exception:
            traceback.print_exc()

    def force_checkpoint_actor(self, actor_id: bytes, actor) -> None:
        """Drain-time forced checkpoint (head's ``checkpoint_now``):
        serialize ``__ray_save__`` state as raw PARTS — never through
        this node's store, which is about to die with the drain — and
        ship them for the head to re-home on its surviving store.
        ALWAYS replies (descr None without the hook or on a failed
        save) so the head's deadline-bounded drain never stalls on an
        actor that cannot checkpoint."""
        descr = None
        if actor is not None and hasattr(actor, "__ray_save__"):
            try:
                state = actor.__ray_save__()
                kind = serialization.dumps_adaptive(state, self.max_inline)
                if kind[0] == "inline":
                    descr = (protocol.INLINE, kind[1])
                else:
                    # bytes() snapshots: the views borrow the actor's
                    # live buffers, and the send pickles lazily.
                    descr = (protocol.PARTS, kind[1],
                             [bytes(v) for v in kind[2]])
            except Exception:
                traceback.print_exc()
        try:
            # 4th element marks the FORCED reply: the head's drain
            # rendezvous keys on it — a racing periodic checkpoint must
            # not release the drain early (nor clobber the re-homed
            # state; the head guards that side too).
            self._send(("actor_checkpoint", actor_id, descr, True))
        except Exception:
            pass


_PULL_MISS = object()


def _iter_remote_shm_descrs(rt: "_WorkerRuntime", task: dict):
    """The task's arg/kwarg descriptors that live in ANOTHER node's
    store — the ones whose materialization pays a network pull."""
    for d in itertools.chain(task.get("args", ()),
                             (task.get("kwargs") or {}).values()):
        if (isinstance(d, tuple) and d and d[0] == protocol.SHM
                and len(d) > 3 and d[3] != rt.store_id):
            yield d


class _ArgPrefetcher:
    """Pulls the remote SHM args of QUEUED tasks while the current task
    computes, so transfer overlaps compute instead of sitting on the
    task's critical path (reference: the raylet pulls task dependencies
    before the worker starts — dependency_manager.h).

    At most ``depth`` pulls are in flight (one per lazily-started worker
    thread); results land in the runtime's singleflight PullRegistry as
    RETAINED segments that ``_load_args`` consumes.  Everything is
    best-effort: a failed prefetch just leaves the task's own load path
    to do the pull (or fall back to the head relay)."""

    def __init__(self, rt: "_WorkerRuntime", depth: int):
        self._rt = rt
        self._depth = depth
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._threads = 0
        self._lock = threading.Lock()  # lock-order: leaf
        # Keys queued but not yet processed: duplicate offers of one
        # segment (enqueue-time hook + _load_args, or N queued tasks
        # sharing an arg) collapse to one queue entry instead of N
        # stale items that could re-pull after the segment is consumed.
        self._queued: set = set()

    def offer(self, task: dict):
        """Queue the task's remote args for background pulling."""
        self.offer_descrs(_iter_remote_shm_descrs(self._rt, task))

    def offer_descrs(self, descrs):
        if self._depth <= 0:
            return
        for d in descrs:
            if d[2] > object_transfer.PullRegistry.RETAIN_BYTES:
                # Larger than the retention budget: finish(retain=True)
                # would immediately self-evict it, so a prefetch pull
                # would be pure double transfer — let the task's own
                # load path stream it once.
                continue
            key = (d[3], d[1])
            with self._lock:
                if key in self._queued:
                    continue
                self._queued.add(key)
            self._q.put(d)
            self._ensure_thread()

    def _ensure_thread(self):
        with self._lock:
            if self._threads >= self._depth:
                return
            self._threads += 1
        threading.Thread(target=self._loop, daemon=True,
                         name="ray_tpu-arg-prefetch").start()

    def _loop(self):
        while True:
            d = self._q.get()
            with self._lock:
                self._queued.discard((d[3], d[1]))
            try:
                self._rt._pull_remote_segment(d, prefetch=True)
            except Exception:
                pass  # best-effort; the task's own load path recovers


_runtime: Optional[_WorkerRuntime] = None


def get_worker_runtime() -> Optional[_WorkerRuntime]:
    return _runtime


class _FunctionCache:
    def __init__(self, rt: Optional["_WorkerRuntime"] = None):
        self._fns: Dict[str, Any] = {}
        self._rt = rt

    def has(self, func_id: str) -> bool:
        return func_id in self._fns

    def put(self, func_id: str, payload: bytes):
        self._fns[func_id] = serialization.loads_inline(payload)
        # Raw payloads kept so this worker can re-push definitions to
        # executors it leases directly (reference: the function table is
        # content-addressed and shippable by any holder).
        if self._rt is not None:
            self._rt._fn_payloads[func_id] = payload

    def get(self, func_id: str):
        return self._fns[func_id]


def _execute(rt: _WorkerRuntime, fns: _FunctionCache, task: dict,
             actors: Dict[bytes, Any]):
    """Run one task/actor method; ship results back.

    Reference: _raylet.pyx:702 execute_task — deserialize args, invoke,
    store returns (small inline to owner, large to plasma/shm)."""
    import time as _time

    recovery.syncpoint("exec_start")
    task_id = TaskID(task["task_id"])
    dreply = task.pop("_dreply", None)
    rt.current_task_id = task_id
    num_returns = task["num_returns"]
    name = task.get("name", "task")
    span_start = _time.time()
    with rt._exec_lock:
        rt._executing += 1
        # Tracked for the failover re-register payload: a head restart
        # mid-execution must learn this task is still producing results
        # here (direct-pushed tasks are owned by their caller, not the
        # head, and are excluded at snapshot time).
        rt._executing_tasks.append((task, dreply is not None))
    try:
        args, kwargs = _load_args(rt, task)
        if "actor_id" in task:
            actor = actors[task["actor_id"]]
            rt.current_actor_id = ActorID(task["actor_id"])
            method = getattr(actor, task["method"])
            result = method(*args, **kwargs)
            if asyncio.iscoroutine(result):
                result = _run_coroutine(result)
        else:
            fn = fns.get(task["func_id"])
            result = fn(*args, **kwargs)
            if asyncio.iscoroutine(result):
                result = _run_coroutine(result)
        returns, nested = _pack_returns(rt, task_id, result, num_returns)
        if dreply is not None:
            # Direct-pushed task: the reply goes straight to the owning
            # caller on its connection, never through the head.  Nested
            # ref bins ride in meta; this worker addrefs them at the head
            # ON THE CALLER'S BEHALF (the caller's owned entry decrefs on
            # free) so an LRU eviction here cannot free a returned ref
            # before the caller materializes it.
            meta = {}
            if any(nested):
                rt._send(("addref_batch",
                          [b for lst in nested for b in lst]))
                meta = {"nested": nested}
            dreply[0].reply(dreply[1], True, returns, meta)
        else:
            rt.send_result((task["task_id"], True, returns, {}))
        if "actor_id" in task:
            # After the reply (off the caller's latency path): persist
            # __ray_save__ state for restartable actors.
            rt.maybe_checkpoint_actor(task["actor_id"], actor)
    except Exception as e:  # noqa: BLE001 — task errors become objects
        err = exc.TaskError.from_exception(name, e)
        payload = _pickle_error(err)
        returns = [(protocol.ERROR, payload)] * max(1, num_returns)
        if dreply is not None:
            dreply[0].reply(dreply[1], False, returns, {})
        else:
            rt.send_result((task["task_id"], False, returns, {}))
    finally:
        with rt._exec_lock:
            rt._executing -= 1
            rt._executing_tasks = [
                (t, d) for t, d in rt._executing_tasks
                if t is not task]
        rt.current_task_id = None
        rt.current_actor_id = None
        rt.record_span(task["task_id"], name, span_start, _time.time(),
                       "actor_method" if "actor_id" in task else "task")


def _pickle_error(err):
    try:
        return serialization.dumps_inline(err)
    except Exception:
        # Exception not picklable — strip the cause, keep the traceback text.
        err.cause = None
        try:
            return serialization.dumps_inline(err)
        except Exception:
            return serialization.dumps_inline(
                exc.RayTpuError(f"unpicklable error: {err}")
            )


def _load_args(rt: _WorkerRuntime, task: dict):
    """Materialize the task's arguments.  Remote SHM args are pulled
    CONCURRENTLY (bounded by arg_prefetch_depth helper threads) instead
    of one blocking stream at a time; materialize() below then consumes
    the pulled segments through the singleflight registry — which also
    makes this a no-op for anything the prefetcher already fetched."""
    depth = getattr(rt, "arg_prefetch_depth", 0)
    if depth > 0:
        remote: Dict[tuple, tuple] = {}
        for d in _iter_remote_shm_descrs(rt, task):
            remote.setdefault((d[3], d[1]), d)
        if len(remote) > 1:
            # The first remote arg streams on THIS thread (inside
            # materialize); the prefetcher's bounded thread pool pulls
            # the rest in parallel — materialize() consumes them through
            # the singleflight registry as they land.
            rt.prefetcher.offer_descrs(list(remote.values())[1:])
    args = [rt.materialize(d) for d in task["args"]]
    kwargs = {k: rt.materialize(d) for k, d in task.get("kwargs", {}).items()}
    return args, kwargs


def _pack_returns(rt: _WorkerRuntime, task_id: TaskID, result, num_returns):
    if num_returns == 1:
        values = [result]
    elif num_returns == 0:
        values = []
    else:
        values = list(result)
        if len(values) != num_returns:
            raise ValueError(
                f"Task declared num_returns={num_returns} but returned "
                f"{len(values)} values"
            )
    out = []
    nested_lists = []
    for i, v in enumerate(values):
        oid = task_id.object_id(i)
        rt.begin_ref_collection()
        try:
            out.append(rt.serialize_value(v, oid))
        finally:
            nested_lists.append(rt.end_ref_collection())
        rt._cache_put(oid, v)
    nested_all = [b for lst in nested_lists for b in lst]
    if nested_all:
        # Returned values embed ObjectRefs: any owned by THIS worker must
        # become head-visible before the consumer tries to use them
        # (simplified borrow protocol — the consumer's addref/get go to
        # the head).
        owned = [b for b in nested_all
                 if rt.direct.status_of(ObjectID(b))
                 not in (None, direct_mod.DELEGATED)]
        if owned:
            rt.direct.export_refs(owned)
    return out, nested_lists


_async_loop = None
_async_loop_lock = threading.Lock()


def _get_async_loop():
    global _async_loop
    with _async_loop_lock:
        if _async_loop is None:
            loop = asyncio.new_event_loop()
            import sys as _sys

            lockcheck = _sys.modules.get("ray_tpu.devtools.lockcheck")
            if lockcheck is not None and lockcheck.enabled():
                # Record async actor handlers that block this loop >50ms
                # (a blocking get/sleep in an async method stalls EVERY
                # coroutine sharing the loop; lint rule RTL101 catches the
                # static cases, this catches the dynamic ones).  Checking
                # sys.modules instead of the env flag honors programmatic
                # lockcheck.install() too, and never imports devtools on
                # the normal path.
                lockcheck.watch_loop(loop)
            t = threading.Thread(target=loop.run_forever, daemon=True,
                                 name="ray_tpu-async")
            t.start()
            _async_loop = loop
    return _async_loop


def _run_coroutine(coro):
    fut = asyncio.run_coroutine_threadsafe(coro, _get_async_loop())
    return fut.result()


def main():
    """Subprocess entry: dial back to the driver's unix socket (reference:
    python/ray/_private/workers/default_worker.py — raylet-spawned worker
    connecting back over the raylet socket)."""
    import time

    from multiprocessing import AuthenticationError

    # runtime_env pip: build/reuse the requirements venv and re-exec
    # under its interpreter BEFORE anything else loads (reference:
    # _private/runtime_env/pip.py materialization).
    from ray_tpu._private.runtime_env_pip import maybe_reexec_into_pip_env

    maybe_reexec_into_pip_env()

    address = protocol.parse_address(os.environ["RAY_TPU_ADDRESS"])
    authkey = bytes.fromhex(os.environ["RAY_TPU_AUTHKEY"])
    conn = None
    for attempt in range(20):
        try:
            # Deadline-aware dial: each attempt bounded by the connect
            # timeout instead of the kernel default.
            conn = protocol.dial(address, authkey=authkey)
            break
        except AuthenticationError:
            # Transient: the accept loop can drop a challenge mid-
            # handshake under load (it serves one handshake at a time);
            # the key itself is from this session's spawn env, so retry.
            time.sleep(0.05 * (attempt + 1))
        except (ConnectionError, OSError):
            time.sleep(0.05 * (attempt + 1))
    if conn is None:
        import sys as _s

        print(f"[ray_tpu worker {os.getpid()}] could not reach driver at "
              f"{address} after 20 attempts", file=_s.stderr)
        raise SystemExit(1)
    worker_entry(
        conn,
        os.environ["RAY_TPU_WORKER_ID"],
        os.environ["RAY_TPU_SESSION"],
        os.environ["RAY_TPU_SHM_DIR_OVERRIDE"],
        int(os.environ["RAY_TPU_MAX_INLINE"]),
        {},
        os.environ["RAY_TPU_NODE_ID"],
        os.environ["RAY_TPU_JOB_ID"],
    )


def _setup_working_dir(rt: "_WorkerRuntime", pkg_id: str):
    """Fetch + extract the job's working_dir package, then chdir into it
    (reference: runtime_env working_dir — agent-materialized per worker;
    here the package ships over the worker's own connection)."""
    import io
    import sys as _sys
    import zipfile

    dest = f"/tmp/ray_tpu_pkg_{pkg_id}"
    if not os.path.isdir(dest):
        blob = rt._request(lambda rid: ("get_package", rid, pkg_id))
        if blob is None:
            return
        tmp = dest + f".tmp{os.getpid()}"
        with zipfile.ZipFile(io.BytesIO(blob)) as z:
            z.extractall(tmp)
        try:
            os.rename(tmp, dest)
        except OSError:
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
    os.chdir(dest)
    _sys.path.insert(0, dest)


def worker_entry(conn, worker_id_hex: str, session: str, shm_dir: str,
                 max_inline: int, env: Dict[str, str], node_id_hex: str,
                 job_id_hex: str):
    """Worker runtime setup + execution loop (reference:
    core_worker.cc:2413 RunTaskExecutionLoop)."""
    os.environ.update(env)
    # Opt-in chaos rules (RAY_TPU_CHAOS): deterministic self-kills at
    # named syncpoints — armed before anything else so boot-path points
    # fire too.  No-op (and zero steady-state cost) when the var is
    # unset.
    recovery.maybe_arm_env_chaos("worker")
    # Net-chaos rules (RAY_TPU_CHAOS_NET, "worker:<point>:<action>:<n>"):
    # gray failures (stalls/drops/delays) at the protocol seam.
    if os.environ.get("RAY_TPU_CHAOS_NET"):
        from ray_tpu import chaos as chaos_mod

        chaos_mod.maybe_arm_env_net_chaos("worker")
    global _runtime
    send_lock = threading.Lock()  # lock-order: io-guard
    # Workers pool freed segments too (the driver routes "free_segment" back
    # to the creating worker) — without this, every worker-side put writes
    # fresh tmpfs pages at fault+zero speed instead of memcpy speed.
    shm = ShmStore(shm_dir=shm_dir, session_id=session,
                   capacity=int(os.environ.get("RAY_TPU_STORE_BYTES", "0")),
                   pool_bytes=int(os.environ.get("RAY_TPU_POOL_BYTES", "0")))
    rt = _WorkerRuntime(conn, send_lock, shm, max_inline)
    rt.worker_id_hex = worker_id_hex
    rt.node_id_hex = node_id_hex
    rt.job_id_hex = job_id_hex
    rt.tpu_chips = [
        c for c in os.environ.get("TPU_VISIBLE_CHIPS", "").split(",") if c
    ]
    _runtime = rt
    object_ref_mod._set_runtime_accessor(lambda: _runtime)

    fns = _FunctionCache(rt)
    actors: Dict[bytes, Any] = {}
    # Deque + condition (not SimpleQueue) so the driver can steal back
    # queued-but-unstarted tasks when this worker blocks in ray.get
    # (reference: work stealing in direct_task_transport's pipelining).
    import collections

    tasks = collections.deque()
    tq_cv = threading.Condition()
    pool: Optional[ThreadPoolExecutor] = None
    max_concurrency = 1

    def steal(steal_id, wanted: set):
        stolen = []
        with tq_cv:
            kept = collections.deque()
            while tasks:
                m = tasks.popleft()
                if m[0] == "exec" and "actor_id" not in m[1] \
                        and m[1]["task_id"] in wanted:
                    stolen.append(m[1]["task_id"])
                else:
                    kept.append(m)
            tasks.extend(kept)
        rt._send(("stolen", steal_id, stolen))

    def handle(msg):
        tag = msg[0]
        if tag in ("exec", "create_actor", "kill"):
            with tq_cv:
                queued_behind = bool(tasks) or rt._executing > 0
                tasks.append(msg)
                tq_cv.notify()
            if tag == "exec" and queued_behind:
                # The task landed BEHIND running/queued work: start
                # pulling its remote args now so transfer overlaps the
                # compute ahead of it (the prefetcher is a no-op for
                # local/inline args and when depth is 0).
                rt.prefetcher.offer(msg[1])
        elif tag == "batch" or tag == "msg_batch":
            # Wire-batch envelope (or the legacy conflation-sender
            # spelling): a burst of buffered messages in send order.
            for m in msg[1]:
                handle(m)
        elif tag == "steal":
            steal(msg[1], set(msg[2]))
        elif tag == "lease_grant":
            # Unsolicited bulk lease grant piggybacked on a head-brokered
            # submit burst: adopt off-thread (adoption dials the granted
            # workers; the reader must keep draining).
            threading.Thread(
                target=rt.direct.adopt_grant,
                args=(msg[1], msg[2], msg[3], msg[4], msg[5]),
                daemon=True, name="ray_tpu-lease-adopt").start()
        elif tag == "lease_revoke":
            rt.direct.revoke(msg[1])
        elif tag == "checkpoint_now":
            # Drain: force a __ray_save__ of the hosted actor, parts-
            # shipped so the head re-homes the state on a surviving
            # store.  Rides the EXECUTION queue, not a fresh thread —
            # the save must serialize with the running method exactly
            # like the periodic post-call checkpoint does, or a
            # mid-method snapshot could tear multi-field state.  Jumps
            # the queue (ahead of pending calls, after the running one)
            # unless the actor's create_actor is itself still queued.
            with tq_cv:
                if msg[1] in actors:
                    tasks.appendleft(msg)
                else:
                    tasks.append(msg)
                tq_cv.notify()
        elif tag == "func":
            fns.put(msg[1], msg[2])
        elif tag == "obj":
            rt.deliver_reply(msg[1], (msg[2], msg[3]))
        elif tag == "mgot":
            rt.deliver_reply(msg[1], msg[2])
        elif tag == "waited":
            rt.deliver_reply(msg[1], msg[2])
        elif tag == "reply":
            rt.deliver_reply(msg[1], msg[2])
        elif tag == "hc_probe":
            # Suspicion probe from the head: answer from this reader
            # thread immediately, independent of the exec thread's
            # state — a long task must never read as a dead link.
            rt._send(("heartbeat", rt.worker_id_hex))
        elif tag == "free_segment":
            # The owner freed an object whose segment this worker
            # created; pool the pages for in-place reuse when no other
            # process ever mapped them (reference: plasma arena reuse).
            try:
                rt.shm.unlink(msg[1], msg[2], reusable=msg[3])
            except Exception:
                pass

    def reader():
        while True:
            try:
                msg = protocol.recv(rt.conn)
            except (EOFError, OSError, TypeError):
                # Head gone.  With failover on, PARK: keep executing,
                # buffer outgoing head traffic, re-dial + re-register
                # for the grace window — a head restart is then a blip,
                # not this worker's death.  Reference: workers
                # reconnecting across GCS restart.
                if not rt._reconnect_head():
                    os._exit(0)
            else:
                rt.note_head_recv()  # any head message is liveness
                handle(msg)

    def _queue_empty():
        with tq_cv:
            return not tasks

    rt.queue_empty = _queue_empty

    def snapshot_tasks():
        """Queued + running HEAD-dispatched tasks for the re-register
        payload: (task_id, num_returns, is_actor_call) rows.  Direct-
        pushed tasks are excluded — their owner (the pushing caller) is
        their metadata authority, not the head."""
        with tq_cv:
            queued = [m[1] for m in tasks
                      if m[0] == "exec" and "_dreply" not in m[1]]
        with rt._exec_lock:
            running = [t for t, is_direct in rt._executing_tasks
                       if not is_direct]
        return [(t["task_id"], t["num_returns"], "actor_id" in t)
                for t in queued + running]

    rt.snapshot_tasks = snapshot_tasks
    rt.snapshot_actors = lambda: list(actors.keys())

    threading.Thread(target=reader, daemon=True, name="ray_tpu-reader").start()

    # Direct-push server: peer workers that leased THIS worker connect
    # here and push tasks into the same execution queue (reference: the
    # core worker's PushTask service, core_worker.cc HandlePushTask).
    def direct_enqueue(task: dict, _src):
        with tq_cv:
            tasks.append(("exec", task))
            tq_cv.notify()

    def maybe_prefetch(task: dict):
        # DirectServer calls this BEFORE enqueueing each pushed task:
        # when the task will land behind running/queued work, its remote
        # args start pulling while that work computes.
        with tq_cv:
            busy = bool(tasks) or rt._executing > 0
        if busy:
            rt.prefetcher.offer(task)

    from ray_tpu._private.config import GLOBAL_CONFIG as _cfg

    direct_server = direct_mod.DirectServer(
        bytes.fromhex(os.environ.get("RAY_TPU_AUTHKEY", "")),
        direct_enqueue, fns.put, rt.shm.unlink,
        on_peer_msg=rt.dispatch_peer_msg, queue_empty=_queue_empty,
        on_task_queued=maybe_prefetch,
        queue_depth=lambda: len(tasks),
        spill_depth=(_cfg.lease_spillback_depth
                     if _cfg.decentralized_dispatch else 0),
        spill_info={"node": node_id_hex})
    rt.direct_addr = direct_server.address

    def decref_flusher():
        import time as _time

        while True:
            _time.sleep(0.25)
            try:
                rt.flush_decrefs()
                # Bounds result-batch latency when a long task follows
                # buffered short-task results.
                rt.flush_results()
                rt.flush_spans()
                rt._pull_registry.sweep()
                rt.flush_xfer_stats()
                # Failure detection: the heartbeat floor + the stalled-
                # head watchdog ride the same periodic thread.
                rt.heartbeat_and_watchdog()
                direct_server.flush_replies()
            except Exception:
                return  # conn gone; reader exits the process

    threading.Thread(target=decref_flusher, daemon=True,
                     name="ray_tpu-decref").start()
    protocol.send(conn, ("ready", worker_id_hex, os.getpid(),
                         direct_server.address))

    # After the handshake (the accept loop requires "ready" first): fetch
    # and enter the working_dir package before any task executes — exec
    # messages just queue behind this.
    pkg_id = os.environ.get("RAY_TPU_WORKING_DIR_PKG")
    if pkg_id:
        _setup_working_dir(rt, pkg_id)

    while True:
        with tq_cv:
            drained = not tasks
        if drained:
            # Queue drained: everything buffered goes out as one batch
            # before this worker parks.  Outside tq_cv: the flushes take
            # send locks and must not hold up direct enqueues.
            rt.flush_results()
            rt.flush_xfer_stats()
            direct_server.flush_replies()
        with tq_cv:
            while not tasks:
                tq_cv.wait()
            msg = tasks.popleft()
        tag = msg[0]
        if tag == "kill":
            os._exit(0)
        elif tag == "checkpoint_now":
            # On the exec thread: the running method (if any) finished
            # before this popped, so the forced save sees settled state
            # (max_concurrency>1 actors keep the same exposure their
            # periodic checkpoints already have).
            rt.force_checkpoint_actor(msg[1], actors.get(msg[1]))
        elif tag == "create_actor":
            spec = msg[1]
            rt.assigned_resources = spec.get("resources", {})
            max_concurrency = spec.get("max_concurrency", 1)
            if max_concurrency > 1:
                pool = ThreadPoolExecutor(max_workers=max_concurrency)
            try:
                cls = fns.get(spec["func_id"])
                args = [rt.materialize(d) for d in spec["args"]]
                kwargs = {
                    k: rt.materialize(d) for k, d in spec["kwargs"].items()
                }
                actor = cls(*args, **kwargs)
                ck = spec.get("checkpoint")
                if ck is not None and hasattr(actor, "__ray_restore__"):
                    # Restart with retained state: __init__ ran fresh
                    # above, then the last __ray_save__ state restores
                    # over it.  A broken checkpoint degrades to the
                    # fresh actor — it must never fail the restart
                    # (that would turn recovery into the outage).
                    try:
                        actor.__ray_restore__(rt.materialize(ck))
                    except Exception:
                        traceback.print_exc()
                rt.arm_actor_checkpoint(spec["actor_id"], actor,
                                        spec.get("checkpoint_interval"))
                actors[spec["actor_id"]] = actor
                rt._send(("result", spec["task_id"], True,
                          [(protocol.INLINE,
                            serialization.dumps_inline(None))], {}))
            except Exception as e:  # noqa: BLE001
                err = exc.TaskError.from_exception(
                    spec.get("name", "actor.__init__"), e)
                rt._send(("result", spec["task_id"], False,
                          [(protocol.ERROR, _pickle_error(err))], {}))
        elif tag == "exec":
            task = msg[1]
            if "actor_id" not in task:
                # Actor-method execs keep the CREATION resources: the
                # actor's worker holds those for its lifetime, and the
                # head's per-method record defaults to {"CPU": 1} even
                # for a 0-CPU actor (which would wrongly re-enable the
                # blocked envelope on the serve proxy hot path).
                rt.assigned_resources = task.get("resources",
                                                 rt.assigned_resources)
            if pool is not None and "actor_id" in task:
                pool.submit(_execute, rt, fns, task, actors)
            else:
                _execute(rt, fns, task, actors)


if __name__ == "__main__":
    # Run through the canonical module so module globals (the worker runtime
    # singleton) live in ray_tpu._private.worker_main, not __main__.
    from ray_tpu._private.worker_main import main as _canonical_main

    _canonical_main()
