"""Direct node-to-node object transfer, chunked.

Reference: ``src/ray/object_manager/object_manager.h:117,206`` +
``object_buffer_pool.h`` — objects move between nodes in bounded chunks
directly between the object managers; the control plane (GCS) only brokers
*locations*.  Here every node agent runs an object server on its own TCP
listener; consumers (workers on other nodes, or the driver) dial it and
pull the segment as a stream of ≤1 MB chunks.  The head carries location
lookups only — never payload bytes.

Flow control: one segment streams per connection at a time in CHUNK-sized
sends; the receiver reads with ``recv_bytes_into`` straight into the
destination buffer (one copy end-to-end), and TCP's window bounds the
bytes in flight (the reference's in-flight chunk cap).
"""

from __future__ import annotations

import struct
import threading
from typing import Dict, List, Optional, Tuple

from ray_tpu._private import protocol, serialization
from ray_tpu._private.shm_store import _HEADER, _MAGIC

CHUNK = 1 << 20  # 1 MB, the reference's object-manager chunk size


def _true_extent(view: memoryview) -> int:
    """Bytes actually used by the segment — pooled reuse can leave a file
    up to ~2x the object (plus stale freed-object bytes); shipping the
    slack would waste network and receiver memory."""
    try:
        _magic, meta_len = _HEADER.unpack_from(view, 0)
        table = bytes(view[_HEADER.size:_HEADER.size + meta_len])
        offsets, lengths, _payload = serialization.loads_inline(table)
        end = _HEADER.size + meta_len
        for o, n in zip(offsets, lengths):
            end = max(end, o + n)
        return min(end, len(view))
    except Exception:
        return len(view)


def serve_connection(conn, store):
    """Agent-side loop for one consumer connection: stream requested
    segments chunk by chunk (reference: ObjectManager::Push)."""
    try:
        while True:
            msg = protocol.recv(conn)
            if msg[0] == "fetch":
                name = msg[1]
                try:
                    seg = store.attach(name)
                except Exception as e:  # noqa: BLE001
                    protocol.send(conn, ("err", repr(e)))
                    continue
                try:
                    mv = memoryview(seg._mm)
                    total = _true_extent(mv)
                    protocol.send(conn, ("ok", total))
                    for off in range(0, total, CHUNK):
                        conn.send_bytes(mv[off:min(off + CHUNK, total)])
                finally:
                    del mv
                    seg.close()
            elif msg[0] == "close":
                return
    except (EOFError, OSError, TypeError):
        return
    finally:
        try:
            conn.close()
        except Exception:
            pass


class ObjectPuller:
    """Consumer-side client: cached connections to home-store object
    servers, pulling segments as chunk streams (reference:
    ObjectManager::Pull + ObjectBufferPool chunk assembly).

    LOCK ORDER (checked by tests/test_lockcheck.py via devtools.lockcheck):
    the registry ``_lock`` and the per-connection locks are INDEPENDENT
    LEAVES — neither may be acquired while the other is held.  The
    registry lock guards only the ``_conns`` dict (lookup/insert/pop,
    never I/O under it); a per-connection lock is held across an entire
    fetch stream (seconds of I/O), so taking ``_lock`` inside it would
    stall every other connection's lookup, and taking a connection lock
    inside ``_lock`` inverts that order.  Note ``fetch``'s error path:
    ``drop`` (registry lock) runs only AFTER the ``with lock`` block has
    released the connection lock.
    """

    def __init__(self, authkey: bytes):
        self._authkey = authkey
        self._conns: Dict[str, tuple] = {}  # store_id -> (conn, lock)
        self._lock = threading.Lock()

    def _conn_for(self, store_id: str, addr: str):
        with self._lock:
            ent = self._conns.get(store_id)
        if ent is not None:
            return ent
        from multiprocessing.connection import Client

        conn = Client(protocol.parse_address(addr), authkey=self._authkey)
        protocol.enable_nodelay(conn)
        ent = (conn, threading.Lock())
        with self._lock:
            # A racing dialer may have won; keep one, close the other.
            cur = self._conns.setdefault(store_id, ent)
            if cur is not ent:
                try:
                    conn.close()
                except Exception:
                    pass
            return cur

    def drop(self, store_id: str):
        with self._lock:
            ent = self._conns.pop(store_id, None)
        if ent is not None:
            try:
                ent[0].close()
            except Exception:
                pass

    def fetch(self, store_id: str, addr: str, name: str) -> bytearray:
        """The raw segment bytes, pulled in CHUNK pieces."""
        conn, lock = self._conn_for(store_id, addr)
        try:
            with lock:
                protocol.send(conn, ("fetch", name))
                tag, val = protocol.recv(conn)
                if tag != "ok":
                    from ray_tpu import exceptions as exc

                    raise exc.ObjectLostError(
                        f"segment {name} unreadable at {store_id}: {val}")
                total = val
                buf = bytearray(total)
                view = memoryview(buf)
                off = 0
                while off < total:
                    off += conn.recv_bytes_into(view, off)
                return buf
        except (EOFError, OSError, TypeError, struct.error):
            self.drop(store_id)
            raise

    def close(self):
        with self._lock:
            conns, self._conns = list(self._conns.values()), {}
        for conn, _ in conns:
            try:
                protocol.send(conn, ("close",))
            except Exception:
                pass
            try:
                conn.close()
            except Exception:
                pass


def parse_segment_bytes(buf) -> Tuple[bytes, List[memoryview]]:
    """(payload_meta, buffer views) from raw segment bytes — the same
    layout Segment.raw_parts reads from an mmap (shm_store.py)."""
    view = memoryview(buf)
    magic, meta_len = _HEADER.unpack_from(view, 0)
    if magic != _MAGIC:
        raise ValueError("corrupt segment stream")
    table = bytes(view[_HEADER.size:_HEADER.size + meta_len])
    offsets, lengths, payload = serialization.loads_inline(table)
    buffers = [view[o:o + n] for o, n in zip(offsets, lengths)]
    return payload, buffers
